"""Flagship-config numerics at the real shapes, on the virtual CPU mesh.

The standard suite runs vocab 2^11-2^14; the flagship config
(examples/criteo_1tb_dist.cfg) is vocab 2^26 / batch 262k.  These tests
drive the sharded paths at (or at the boundaries of) those shapes so the
int32 metadata, the _cumsum_counts 2^24 exactness cutoff, and the real
delta/stream shapes execute somewhere before a hardware window does
(VERDICT r4 next-round #4).

The full-shape parity test takes many minutes of interpret-mode kernels
and ~20 GB RAM, so it is gated behind FAST_TFFM_SCALE_TESTS=1 in
addition to the slow marker:

    FAST_TFFM_SCALE_TESTS=1 python -m pytest tests/test_scale_shapes.py -v
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import sparse_apply
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train import shardmap_step, sparse as sparse_lib

_SCALE = os.environ.get("FAST_TFFM_SCALE_TESTS") == "1"
needs_scale_env = pytest.mark.skipif(
    not _SCALE, reason="set FAST_TFFM_SCALE_TESTS=1 (slow, ~20 GB RAM)"
)


def test_cumsum_counts_2e24_cutoff_exact():
    """The single-level MXU prefix sum is f32-exact only below 2^24
    counts; at and above the cutoff _cumsum_counts must switch to the
    two-level split (MXU within < 2^24 segments + exact int32 offsets)
    and stay integer-exact.  All-ones flags maximize the total, so the
    tail elements are exactly where f32 would round."""
    for n in [
        (1 << 24) - 128,   # single-level MXU path, just under cutoff
        1 << 24,           # two-level path, seg = 2^23
        512 * 32769,       # > 2^24 with odd segment count: seg shrinks
                           # to 512 (deep halving) and 32769 segments
    ]:
        flags = jnp.ones((n,), jnp.int32)
        out = sparse_apply._cumsum_counts(flags)
        np.testing.assert_array_equal(
            np.asarray(out[-4:]), np.arange(n - 3, n + 1), err_msg=str(n)
        )
        # A middle probe too (offsets wrong by a segment would show).
        mid = n // 2 + 64
        assert int(out[mid - 1]) == mid, n


def test_tile_starts_int32_at_flagship_vocab():
    """tile_start metadata at vocab 2^26: boundaries, counts, and the
    sentinel handling must be exact in int32 (no kernel execution)."""
    vocab = 1 << 26
    rng = np.random.default_rng(0)
    n = 200_000
    ids = np.concatenate([
        rng.integers(0, vocab, n - 3).astype(np.int32),
        np.array([0, vocab - 1, vocab - 1], np.int32),  # edge rows
    ])
    sidx = jnp.sort(jnp.asarray(ids))
    flags = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        (sidx[1:] != sidx[:-1]).astype(jnp.int32),
    ])
    upos = sparse_apply._cumsum_counts(flags) - 1
    boundaries = jnp.arange(
        0, vocab + 1, sparse_apply.TILE, dtype=sidx.dtype
    )
    ts = np.asarray(sparse_apply._tile_starts(sidx, upos, boundaries))
    assert ts.dtype == np.int32
    n_unique = int(upos[-1]) + 1
    assert ts[0] == 0 and ts[-1] == n_unique
    assert (np.diff(ts) >= 0).all()
    # Spot-check: entries below each of a few boundaries == unique count
    # of ids below it.
    ids_u = np.unique(ids)
    for b_idx in (1, 1000, len(ts) - 2):
        bound = b_idx * sparse_apply.TILE
        assert ts[b_idx] == (ids_u < bound).sum()


@pytest.mark.parametrize("exchange", ["entries", "dense"])
def test_flagship_shapes_trace_full_fidelity(exchange):
    """vocab 2^26 / global batch 64k / F=39 on the 2x4 virtual mesh,
    traced at FULL fidelity via eval_shape (no interpret-mode kernel
    execution — an interpret sweep of 2^26 rows takes hours on one CPU
    core).  Tracing executes every shape/dtype/metadata computation:
    int32 tile_start at 65537 boundaries, the real [2^24, 18]
    delta aval in dense mode, the real merged-stream avals in entries
    mode.  Cheap enough to run in default CI."""
    vocab, b, f, k = 1 << 26, 1 << 16, 39, 8
    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4),
        (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
    )
    cfg = FmConfig(
        vocabulary_size=vocab, factor_num=k, max_features=f, batch_size=b,
        optimizer="adagrad", learning_rate=0.05, lookup="shardmap",
        sparse_exchange=exchange,
    )
    assert shardmap_step.supports_shardmap(cfg, mesh)
    batch = Batch(
        labels=jax.ShapeDtypeStruct((b,), jnp.float32),
        ids=jax.ShapeDtypeStruct((b, f), jnp.int32),
        vals=jax.ShapeDtypeStruct((b, f), jnp.float32),
        fields=jax.ShapeDtypeStruct((b, f), jnp.int32),
        weights=jax.ShapeDtypeStruct((b,), jnp.float32),
    )
    d = cfg.embedding_dim
    params = fm.FmParams(
        w0=jax.ShapeDtypeStruct((), jnp.float32),
        table=jax.ShapeDtypeStruct((vocab, d), jnp.float32),
    )
    opt = sparse_lib.SparseAdagradState(
        acc=fm.FmParams(
            w0=jax.ShapeDtypeStruct((), jnp.float32),
            table=jax.ShapeDtypeStruct((vocab, d), jnp.float32),
        )
    )
    p_out, o_out, scores = jax.eval_shape(
        lambda p, o, bb: shardmap_step.sparse_step_shardmap(
            cfg, p, o, bb, mesh
        ),
        params, opt, batch,
    )
    assert p_out.table.shape == (vocab, d)
    assert o_out.acc.table.shape == (vocab, d)
    assert scores.shape == (b,)


@pytest.mark.slow
@needs_scale_env
def test_flagship_entries_exchange_executes_at_real_shapes():
    """The batch-proportional half of the flagship step, EXECUTED at the
    real shapes: vocab_local 2^24 (one model shard of 2^26 over 4),
    64k-example data shard (2.5M occurrences).  K1 is batch-proportional
    so interpret mode handles it; the K2 vocab sweep is covered
    separately at entry-bounded cost below.  Validates the deduped
    stream and the 2-shard merge bit-exactly against numpy per-row
    sums."""
    vocab_local, b, f = 1 << 24, 1 << 15, 39  # one shard's view
    rng = np.random.default_rng(2)
    n = b * f
    cap = sparse_apply.entries_cap(n, vocab_local)
    rows_all, pay_all, shard_data, check_rids = [], [], [], [12345]
    for shard in range(2):
        ids = rng.integers(0, vocab_local, n).astype(np.int32)
        ids[: n // 100] = 12345  # a hot id crossing shards
        g = rng.uniform(-1, 1, (n, 9)).astype(np.float32)
        shard_data.append((ids, g))
        rows_s, pay_s, count = sparse_apply.unique_entries(
            jnp.asarray(ids), jnp.asarray(g), vocab=vocab_local, cap=cap
        )
        rows_s, pay_s = np.asarray(rows_s), np.asarray(pay_s)
        n_unique = len(np.unique(ids))
        assert int(count) == n_unique
        # Spot-check payload sums on the hot id + 3 random ids.
        for rid in [12345] + list(rng.choice(ids, 3)):
            mask = ids == rid
            pos = np.searchsorted(rows_s[: int(count)], rid)
            assert rows_s[pos] == rid
            np.testing.assert_allclose(
                pay_s[pos, :9], g[mask].sum(axis=0), rtol=1e-4, atol=1e-4
            )
            check_rids.append(int(rid))
        rows_all.append(rows_s)
        pay_all.append(pay_s)
    # Merged totals must sum over BOTH shards' raw data (a rid sampled
    # from one shard can occur in the other too).
    want = {
        rid: sum(g[ids == rid].sum(axis=0) for ids, g in shard_data)
        for rid in set(check_rids)
    }
    u, ts = sparse_apply.merge_entries(
        jnp.asarray(np.concatenate(rows_all)),
        jnp.asarray(np.concatenate(pay_all)), vocab=vocab_local,
    )
    ts = np.asarray(ts)
    assert ts.dtype == np.int32 and ts.shape == (vocab_local // 256 + 1,)
    u = np.asarray(u)
    # The hot id's merged entry must hold the cross-shard total.
    for rid, total in want.items():
        tile = rid // sparse_apply.TILE
        lrow = rid % sparse_apply.TILE
        window = u[ts[tile]:ts[tile + 1]]
        hit = window[window[:, 2 * 9].astype(np.int32) == lrow]
        assert hit.shape[0] == 1
        np.testing.assert_allclose(
            hit[0, :9], total, rtol=1e-4, atol=1e-4
        )


@pytest.mark.slow
@needs_scale_env
def test_flagship_vocab_compact_apply_matches_scatter():
    """K2 at vocab 2^26, EXECUTED — compact mode bounds the interpret
    sweep to the touched groups, so the real 262k-boundary int32
    tile_start, the 32k-group compact list, and far-offset window DMAs
    all run.  Scatter reference on the full table.  n is kept small:
    the compact grid pads to n_pad groups x GROUP subtiles and interpret
    mode also pays full-array host ops on the [2^26, 9] tables
    (measured: n=900 -> ~27 min on this 1-core host)."""
    vocab, n = 1 << 26, 900
    rng = np.random.default_rng(3)
    ids = jnp.asarray(
        np.concatenate([
            rng.integers(0, vocab, n - 2).astype(np.int32),
            np.array([0, vocab - 1], np.int32),  # extreme rows
        ])
    )
    g = jnp.asarray(rng.uniform(-1, 1, (n, 9)).astype(np.float32))
    table = jnp.zeros((vocab, 9), jnp.float32)
    acc = jnp.full((vocab, 9), 0.1, jnp.float32)
    t1, a1 = sparse_apply.adagrad_apply(
        table, acc, ids, g, lr=0.1, eps=1e-7, compact=True
    )
    a_ref = acc.at[ids].add(g * g)
    t_ref = table.at[ids].add(-0.1 * g * jax.lax.rsqrt(a_ref[ids] + 1e-7))
    np.testing.assert_allclose(
        np.asarray(t1), np.asarray(t_ref), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(a1), np.asarray(a_ref), rtol=1e-4, atol=1e-4
    )
