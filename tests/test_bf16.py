"""compute_dtype=bfloat16: parity and convergence vs float32.

bf16 mode rounds only the interaction operands (gathered rows, vals);
parameters, accumulation, scores, loss, and optimizer state stay f32.
These tests pin that contract: per-step scores within bf16 rounding of
f32, training losses match to ~1e-2, and both kernel paths agree.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import interaction
from fast_tffm_tpu.train import sparse


def _batch(rng, b, f, vocab):
    return Batch(
        labels=(rng.random(b) < 0.4).astype(np.float32),
        ids=rng.integers(0, vocab, size=(b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, size=(b, f)).astype(np.float32),
        fields=np.zeros((b, f), np.int32),
        weights=np.ones((b,), np.float32),
    )


def _cfg(**kw):
    base = dict(
        vocabulary_size=2048, factor_num=8, max_features=16, batch_size=256,
        learning_rate=0.05, sparse_apply="scatter", use_pallas=False,
    )
    base.update(kw)
    return FmConfig(**base)


def _init(cfg, seed=0):
    params = fm.init_params(jax.random.PRNGKey(seed), cfg)
    opt = sparse.init_sparse_opt_state(cfg, params)
    return params, opt


class TestScoresParity:
    def test_interaction_bf16_close_to_f32(self, rng):
        b, f, d = 128, 16, 9
        rows = jnp.asarray(rng.normal(0, 0.1, (b, f, d)), jnp.float32)
        vals = jnp.asarray(rng.uniform(0.1, 1.0, (b, f)), jnp.float32)
        ref = interaction.fm_interaction(rows, vals, False)
        got = interaction.fm_interaction(
            rows.astype(jnp.bfloat16), vals.astype(jnp.bfloat16), False
        )
        assert got.dtype == jnp.float32
        # bf16 has ~3 decimal digits; products of two rounded operands.
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.02)

    def test_interaction_bf16_pallas_matches_jnp(self, rng):
        b, f, d = 128, 16, 9
        rows = jnp.asarray(
            rng.normal(0, 0.1, (b, f, d)), jnp.bfloat16
        )
        vals = jnp.asarray(rng.uniform(0.1, 1.0, (b, f)), jnp.bfloat16)
        jn = interaction.fm_interaction(rows, vals, False)
        pa = interaction.fm_interaction(rows, vals, True)
        np.testing.assert_allclose(pa, jn, rtol=2e-3, atol=1e-4)

    def test_interaction_bf16_grads_match_jnp(self, rng):
        b, f, d = 64, 8, 9
        rows = jnp.asarray(rng.normal(0, 0.1, (b, f, d)), jnp.bfloat16)
        vals = jnp.asarray(rng.uniform(0.1, 1.0, (b, f)), jnp.bfloat16)

        def loss(r, use_pallas):
            return jnp.sum(interaction.fm_interaction(r, vals, use_pallas) ** 2)

        gj = jax.grad(lambda r: loss(r, False))(rows)
        gp = jax.grad(lambda r: loss(r, True))(rows)
        assert gj.dtype == gp.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            gp.astype(np.float32), gj.astype(np.float32), rtol=0.05, atol=0.02
        )


class TestTrainingParity:
    @pytest.mark.parametrize("optimizer", ["adagrad", "ftrl"])
    def test_bf16_loss_tracks_f32(self, rng, optimizer):
        """20 steps of bf16 training end within 1e-2 logloss of f32."""
        losses = {}
        for dtype in ("float32", "bfloat16"):
            cfg = _cfg(optimizer=optimizer, compute_dtype=dtype)
            params, opt = _init(cfg)
            step = jax.jit(
                lambda p, o, b, cfg=cfg: sparse.sparse_step(cfg, p, o, b)
            )
            brng = np.random.default_rng(7)
            last = None
            for _ in range(20):
                batch = _batch(brng, cfg.batch_size, cfg.max_features,
                               cfg.vocabulary_size)
                params, opt, scores = step(params, opt, batch)
                per = fm.example_losses(
                    jnp.asarray(scores), jnp.asarray(batch.labels), "logistic"
                )
                last = float(jnp.mean(per))
            losses[dtype] = last
        assert abs(losses["bfloat16"] - losses["float32"]) < 1e-2

    def test_bf16_dense_path_runs(self, rng):
        """Dense (optax adam) path accepts bf16 compute too."""
        from fast_tffm_tpu.train.loop import Trainer

        cfg = _cfg(
            optimizer="adam", compute_dtype="bfloat16",
            model_file="/tmp/fast_tffm_bf16_dense_test",
        )
        import shutil

        shutil.rmtree(cfg.model_file, ignore_errors=True)
        t = Trainer(cfg)
        brng = np.random.default_rng(3)
        b = t._put(_batch(brng, cfg.batch_size, cfg.max_features,
                          cfg.vocabulary_size))
        s0 = t.state
        t.state = t._train_step(t.state, b)
        assert int(t.state.step) == 1
        assert t.state.params.table.dtype == jnp.float32  # params stay f32


class TestFfmBf16:
    def test_ffm_scores_bf16_close_to_f32(self, rng):
        """FFM bf16 mode must RUN off-TPU (XLA:CPU cannot execute
        bf16 x bf16 -> f32 dots, so the einsums fall back to f32 operands
        there) and stay close to f32 scores."""
        b, f, p, k = 64, 8, 3, 4
        w0 = jnp.float32(0.1)
        rows = jnp.asarray(rng.normal(0, 0.1, (b, f, 1 + p * k)), jnp.float32)
        vals = jnp.asarray(rng.uniform(0.1, 1.0, (b, f)), jnp.float32)
        fields = jnp.asarray(rng.integers(0, p, (b, f)), jnp.int32)
        ref = fm.ffm_scores_from_rows(w0, rows, vals, fields, k, p)
        got = fm.ffm_scores_from_rows(
            w0, rows, vals, fields, k, p, jnp.bfloat16
        )
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.02)

    def test_ffm_shardmap_bf16_runs_on_mesh(self, rng):
        """The FFM+bf16 shardmap step must execute on a CPU mesh (the
        multichip dryrun config; a bf16 dot would abort one device and
        strand the rest at the next collective)."""
        from jax.sharding import Mesh

        from fast_tffm_tpu.parallel import mesh as mesh_lib
        from fast_tffm_tpu.train import shardmap_step

        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(4, 2),
            (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
        )
        cfg = _cfg(
            field_num=3, compute_dtype="bfloat16", sparse_apply="tile",
            use_pallas=False,
        )
        params, opt = _init(cfg)
        brng = np.random.default_rng(9)
        batch = _batch(brng, cfg.batch_size, cfg.max_features,
                       cfg.vocabulary_size)
        batch = batch._replace(
            fields=brng.integers(
                0, 3, (cfg.batch_size, cfg.max_features)
            ).astype(np.int32)
        )
        p, o, scores = shardmap_step.sparse_step_shardmap(
            cfg, params, opt, batch, mesh
        )
        assert np.isfinite(np.asarray(scores)).all()


class TestShardmapBf16:
    def test_shardmap_bf16_close_to_f32(self, rng):
        from jax.sharding import Mesh

        from fast_tffm_tpu.parallel import mesh as mesh_lib
        from fast_tffm_tpu.train import shardmap_step

        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(4, 2),
            (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
        )
        brng = np.random.default_rng(5)
        out = {}
        for dtype in ("float32", "bfloat16"):
            cfg = _cfg(sparse_apply="tile", use_pallas=False,
                       compute_dtype=dtype)
            assert shardmap_step.supports_shardmap(cfg, mesh)
            params, opt = _init(cfg)
            batch = _batch(brng, cfg.batch_size, cfg.max_features,
                           cfg.vocabulary_size)
            _, _, scores = shardmap_step.sparse_step_shardmap(
                cfg, params, opt, batch, mesh
            )
            out[dtype] = np.asarray(scores)
            brng = np.random.default_rng(5)  # same batch for both
        np.testing.assert_allclose(
            out["bfloat16"], out["float32"], rtol=0.05, atol=0.02
        )
