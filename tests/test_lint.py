"""tffm-lint framework tests (tools/lint — the PR 10 tentpole).

Three layers, all tier-1:

* per-rule fixture snippets: a miniature repo per analyzer where a
  seeded violation must be flagged AT THE RIGHT file:line and the
  compliant twin must pass — the analyzers are heuristic, so their
  contract is pinned by example;
* framework mechanics: baseline suppression (new vs grandfathered vs
  stale), inline ``# lint: disable=`` comments, the CLI exit code;
* the live tree: ``lint.run(repo_root)`` must report no NEW findings
  and no stale baseline entries — the same gate tools/verify.sh and
  bench preflight run, so a finding introduced by any future PR fails
  here first.

Plus the lint-adjacent runtime gate: importing every package module
must raise no deprecation-class warning attributed to package files
(the ``-W error::DeprecationWarning``-style audit, run in a
subprocess so this process's import cache can't hide anything), and
the regression test for the leak TL005 caught on the shipped tree
(the tracer's rotation writer thread was started unbound and could
never be joined).
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap
import threading

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools import lint  # noqa: E402
from tools.lint.core import Context, load_baseline, run_rules  # noqa: E402
from tools.lint.donation import DonationRule  # noqa: E402
from tools.lint.knobs import KnobsRule  # noqa: E402
from tools.lint.legacy import ObsMetricsRule, Tier1Rule  # noqa: E402
from tools.lint.lifecycle import LifecycleRule  # noqa: E402
from tools.lint.locks import LocksRule  # noqa: E402
from tools.lint.records import RecordsRule  # noqa: E402


def _mini_repo(tmp_path, snippet: str, name="mod.py") -> Context:
    """A fixture repo holding one package module."""
    pkg = tmp_path / "fast_tffm_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / name).write_text(textwrap.dedent(snippet))
    return Context(str(tmp_path))


def _findings(rule, ctx):
    return rule.run(ctx)


def _by_rule(findings, rule_id):
    return [f for f in findings if f.rule == rule_id]


# ---------------------------------------------------------------------
# TL — lifecycle
# ---------------------------------------------------------------------

class TestLifecycle:
    def test_unjoined_attr_thread_flagged_at_line(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class Owner:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass
            """)
        found = _by_rule(_findings(LifecycleRule(), ctx), "TL001")
        assert len(found) == 1
        assert found[0].path == "fast_tffm_tpu/mod.py"
        assert found[0].line == 5
        assert "_t" in found[0].message

    def test_attr_thread_with_join_passes(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class Owner:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    pass

                def close(self):
                    self._t.join()
            """)
        assert not _findings(LifecycleRule(), ctx)

    def test_unbound_started_thread_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            def fire():
                threading.Thread(target=print, daemon=True).start()
            """)
        found = _by_rule(_findings(LifecycleRule(), ctx), "TL005")
        assert len(found) == 1 and found[0].line == 4

    def test_container_threads_joined_pass(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            def run(n):
                threads = [threading.Thread(target=print)]
                threads += [
                    threading.Thread(target=print) for _ in range(n)
                ]
                for t in threads:
                    t.start()
                try:
                    pass
                finally:
                    for t in threads:
                        t.join()
            """)
        assert not _findings(LifecycleRule(), ctx)

    def test_container_threads_unjoined_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            def run(n):
                threads = [threading.Thread(target=print)
                           for _ in range(n)]
                for t in threads:
                    t.start()
            """)
        assert _by_rule(_findings(LifecycleRule(), ctx), "TL001")

    def test_attr_worker_pool_unjoined_flagged(self, tmp_path):
        """TL007 (ISSUE 16): the worker-pool shape — a list of threads
        bound to a self attribute, whose teardown loop would live in
        ANOTHER method.  No loop over the attribute = pooled handler
        threads that outlive their server."""
        ctx = _mini_repo(tmp_path, """\
            import threading

            class Pool:
                def __init__(self, n):
                    self._workers = [
                        threading.Thread(target=print)
                        for _ in range(n)
                    ]
                    for t in self._workers:
                        t.start()
            """)
        found = _by_rule(_findings(LifecycleRule(), ctx), "TL007")
        assert len(found) == 1
        assert "self._workers" in found[0].message

    def test_attr_worker_pool_joined_passes(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class Pool:
                def __init__(self, n):
                    self._workers = [
                        threading.Thread(target=print)
                        for _ in range(n)
                    ]

                def close(self):
                    for t in self._workers:
                        t.join()
            """)
        assert not _findings(LifecycleRule(), ctx)

    def test_queue_shm_server_teardowns(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            from http.server import ThreadingHTTPServer
            from multiprocessing import shared_memory
            from .pipeline import _ClosableQueue

            class Owner:
                def __init__(self):
                    self._q = _ClosableQueue(4)
                    self._shm = shared_memory.SharedMemory(create=True)
                    self._httpd = ThreadingHTTPServer(("", 0), None)
            """)
        rules = {f.rule for f in _findings(LifecycleRule(), ctx)}
        assert rules == {"TL002", "TL003", "TL004"}

    def test_unreaped_popen_flagged(self, tmp_path):
        """TL006 (ISSUE 12): a subprocess.Popen replica process with
        no reachable terminate/wait on the owner's teardown path would
        outlive its router — an orphaned jax process holding a port."""
        ctx = _mini_repo(tmp_path, """\
            import subprocess

            class Manager:
                def __init__(self, cmd):
                    self.proc = subprocess.Popen(cmd)
            """)
        found = _by_rule(_findings(LifecycleRule(), ctx), "TL006")
        assert len(found) == 1
        assert found[0].line == 5
        assert "subprocess" in found[0].message

    def test_popen_with_teardown_passes(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import subprocess

            class Manager:
                def __init__(self, cmd):
                    self.proc = subprocess.Popen(cmd)

                def close(self):
                    if self.proc.poll() is None:
                        self.proc.terminate()
                    self.proc.wait()
            """)
        assert not _findings(LifecycleRule(), ctx)

    def test_local_popen_unreaped_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import subprocess

            def launch(cmd):
                proc = subprocess.Popen(cmd)
                proc.communicate()
            """)
        found = _by_rule(_findings(LifecycleRule(), ctx), "TL006")
        assert len(found) == 1

    def test_ownership_transfer_not_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            from multiprocessing import shared_memory

            class Ring:
                def __init__(self, shm):
                    self._shm = shm

                @classmethod
                def create(cls, size):
                    shm = shared_memory.SharedMemory(
                        create=True, size=size
                    )
                    return cls(shm, size)

                def close(self):
                    self._shm.close()
            """)
        assert not _findings(LifecycleRule(), ctx)


# ---------------------------------------------------------------------
# DA — donation / aliasing
# ---------------------------------------------------------------------

class TestDonation:
    def test_use_after_donate_flagged_at_line(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import jax

            step = jax.jit(lambda s, b: s, donate_argnums=0)

            def train(state, batch):
                out = step(state, batch)
                print(state)
                return out
            """)
        found = _by_rule(_findings(DonationRule(), ctx), "DA001")
        assert len(found) == 1
        assert found[0].line == 7 and "state" in found[0].message

    def test_rebind_idiom_passes(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import jax

            step = jax.jit(lambda s, b: s, donate_argnums=0)

            def train(state, batches):
                for b in batches:
                    state = step(state, b)
                return state
            """)
        assert not _findings(DonationRule(), ctx)

    def test_multiline_call_args_not_false_flagged(self, tmp_path):
        # The shipped tree's _tier_load_jit call spans lines; the
        # callee's own argument lines must not read as use-after-donate.
        ctx = _mini_repo(tmp_path, """\
            import jax

            load = jax.jit(lambda t, s, r: t, donate_argnums=0)

            def apply(tables, slots, rows):
                new_tables = load(
                    tables,
                    slots,
                    rows,
                )
                return new_tables
            """)
        assert not _findings(DonationRule(), ctx)

    def test_device_put_alias_write_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import jax
            import numpy as np

            def ship(buf, sharding):
                dev = jax.device_put(buf, sharding)
                buf[:] = 0
                return dev
            """)
        found = _by_rule(_findings(DonationRule(), ctx), "DA002")
        assert len(found) == 1 and found[0].line == 6

    def test_inline_disable_suppresses(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import jax

            def ship(buf, sharding):
                dev = jax.device_put(buf, sharding)
                buf[:] = 0  # lint: disable=DA002
                return dev
            """)
        result = run_rules([DonationRule()], ctx)
        assert not result["findings"]


# ---------------------------------------------------------------------
# LK — blocking under lock
# ---------------------------------------------------------------------

class TestLocks:
    def test_blocking_get_under_lock_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class W:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q

                def drain(self):
                    with self._lock:
                        item = self._q.get()
                    return item
            """)
        found = _by_rule(_findings(LocksRule(), ctx), "LK001")
        assert len(found) == 1 and found[0].line == 10

    def test_timeout_and_outside_lock_pass(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class W:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q

                def drain(self):
                    with self._lock:
                        item = self._q.get(timeout=1.0)
                    other = self._q.get()
                    return item, other
            """)
        assert not _findings(LocksRule(), ctx)

    def test_cv_wait_is_sanctioned(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def get(self):
                    with self._cv:
                        while True:
                            self._cv.wait()
            """)
        assert not _findings(LocksRule(), ctx)

    def test_foreign_wait_under_lock_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            class W:
                def __init__(self, ev):
                    self._lock = threading.Lock()
                    self._ev = ev

                def hold(self):
                    with self._lock:
                        self._ev.wait()
            """)
        assert _by_rule(_findings(LocksRule(), ctx), "LK001")

    def test_nested_def_under_lock_not_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            def make(q):
                lock = threading.Lock()
                with lock:
                    def later():
                        return q.get()
                return later
            """)
        assert not _findings(LocksRule(), ctx)

    def test_dict_get_and_str_join_not_flagged(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            import threading

            def fmt(d, parts, lock):
                with lock:
                    v = d.get("key")
                    s = ", ".join(parts)
                return v, s
            """)
        assert not _findings(LocksRule(), ctx)


# ---------------------------------------------------------------------
# KD — knob drift (fixture repo with its own config/cli/docs)
# ---------------------------------------------------------------------

_KNOBS_TABLE_DRIFTED = """\
## Knobs

| knob | default | effect |
|---|---|---|
| `heartbeat_secs` (`--heartbeat_secs`) | 0 | beat |
| `phantom_knob` (`--phantom`) | 0 | drifted row |
"""

_KNOBS_TABLE_CLEAN = """\
## Knobs

| knob | default | effect |
|---|---|---|
| `heartbeat_secs` (`--heartbeat_secs`) | 0 | beat |
"""


def _knobs_repo(tmp_path, *, keymap_extra="", cli_tuple, docs,
                fingerprint="blob = dataclasses.asdict(cfg)",
                obs_table=_KNOBS_TABLE_DRIFTED):
    pkg = tmp_path / "fast_tffm_tpu"
    pkg.mkdir()
    (pkg / "config.py").write_text(textwrap.dedent(f"""\
        import dataclasses

        @dataclasses.dataclass
        class FmConfig:
            batch_size: int = 1024
            heartbeat_secs: float = 0.0
            ghost_knob: int = 0

        _KEYMAP = {{
            "batch_size": ("batch_size", int),
            "heartbeat_secs": ("heartbeat_secs", float),
            {keymap_extra}
        }}
        """))
    (pkg / "cli.py").write_text(textwrap.dedent(f"""\
        import argparse

        def build():
            p = argparse.ArgumentParser()
            p.add_argument("--heartbeat_secs", type=float)
            p.add_argument("--batch_size", type=int)
            return p

        def main(args):
            overrides = {{
                k: getattr(args, k) for k in {cli_tuple}
                if getattr(args, k) is not None
            }}
            return overrides
        """))
    (pkg / "loop.py").write_text(textwrap.dedent(f"""\
        import dataclasses

        def _config_fingerprint(cfg):
            {fingerprint}
            return str(blob)
        """))
    (tmp_path / "README.md").write_text(docs)
    (tmp_path / "OBSERVABILITY.md").write_text(obs_table)
    return Context(str(tmp_path))


class TestKnobs:
    def test_drift_matrix(self, tmp_path):
        ctx = _knobs_repo(
            tmp_path,
            keymap_extra='"typo_key": ("no_such_field", int),',
            cli_tuple='("batch_size",)',  # heartbeat flag inert
            docs="batch_size heartbeat_secs\n",  # ghost_knob undocumented
        )
        by = {}
        for f in KnobsRule().run(ctx):
            by.setdefault(f.rule, []).append(f)
        # ghost_knob: no INI key + undocumented
        assert any("ghost_knob" in f.message for f in by["KD001"])
        assert any("ghost_knob" in f.message for f in by["KD005"])
        # typo'd keymap entry
        assert any("no_such_field" in f.message for f in by["KD002"])
        # --heartbeat_secs parses but is never plumbed
        assert any("--heartbeat_secs" in f.message for f in by["KD003"])
        # docs table row for a knob that does not exist + bad CLI name
        assert any("phantom_knob" in f.message for f in by["KD006"])
        assert any("--phantom" in f.message for f in by["KD006"])

    def test_clean_fixture_passes(self, tmp_path):
        ctx = _knobs_repo(
            tmp_path,
            keymap_extra='"ghost_knob": ("ghost_knob", int),',
            cli_tuple='("batch_size", "heartbeat_secs")',
            docs="batch_size heartbeat_secs ghost_knob\n",
            obs_table=_KNOBS_TABLE_CLEAN,
        )
        findings = KnobsRule().run(ctx)
        assert not findings, [f.render() for f in findings]

    def test_fingerprint_enumeration_must_be_total(self, tmp_path):
        ctx = _knobs_repo(
            tmp_path,
            keymap_extra='"ghost_knob": ("ghost_knob", int),',
            cli_tuple='("batch_size", "heartbeat_secs")',
            docs="batch_size heartbeat_secs ghost_knob\n",
            fingerprint='blob = (cfg.batch_size, cfg.heartbeat_secs)',
            obs_table=_KNOBS_TABLE_CLEAN,
        )
        found = _by_rule(KnobsRule().run(ctx), "KD007")
        assert len(found) == 1 and "ghost_knob" in found[0].message


# ---------------------------------------------------------------------
# RS — record-schema drift (fixture repo with its own schema table)
# ---------------------------------------------------------------------

_RS_TABLE = """\
## Record schema

| record | required keys | blocks | notes |
|---|---|---|---|
| `train` | `step` `loss` | — | interval |
| `status` | — | `stages` | on demand |
| `ghost` | — | `phantom_block` | emitted nowhere |
"""

_RS_TABLE_CLEAN = """\
## Record schema

| record | required keys | blocks | notes |
|---|---|---|---|
| `train` | `step` `loss` | — | interval |
| `status` | — | `stages` | on demand |
| `ghost` | — | — | builder-called |
"""


class TestRecords:
    def _repo(self, tmp_path, snippet, table=_RS_TABLE):
        ctx = _mini_repo(tmp_path, snippet)
        (tmp_path / "OBSERVABILITY.md").write_text(
            textwrap.dedent(table)
        )
        return ctx

    def test_schema_drift_matrix(self, tmp_path):
        ctx = self._repo(tmp_path, """\
            def emit(writer):
                writer.write({
                    "record": "rogue",
                    "step": 1,
                })
                writer.write({
                    "record": "train",
                    "step": 1,
                })
            """)
        by = {}
        for f in RecordsRule().run(ctx):
            by.setdefault(f.rule, []).append(f)
        # rogue emitted but undocumented, at the dict literal's line
        assert any(
            "rogue" in f.message and f.line == 2 for f in by["RS001"]
        )
        # ghost documented but never emitted
        assert any("ghost" in f.message for f in by["RS002"])
        # the train literal lacks pinned key `loss`
        assert any("loss" in f.message for f in by["RS003"])
        # phantom_block attached nowhere
        assert any("phantom_block" in f.message for f in by["RS004"])

    def test_dynamic_builder_resolution(self, tmp_path):
        # `build(kind="status")` + `build("train")` cover both
        # documented types; `stages` attaches via subscript store.
        ctx = self._repo(tmp_path, """\
            def build(kind="status"):
                rec = {
                    "record": kind,
                    "step": 1,
                    "loss": 0.5,
                }
                rec["stages"] = {}
                return rec

            def emit():
                return build("train"), build("ghost")
            """, table=_RS_TABLE_CLEAN)
        found = RecordsRule().run(ctx)
        assert not found, [f.render() for f in found]


# ---------------------------------------------------------------------
# folded-in legacy rules
# ---------------------------------------------------------------------

class TestLegacyRules:
    def test_tier1_rule_flags_all_slow_file(self, tmp_path):
        tests = tmp_path / "tests"
        tests.mkdir()
        (tests / "test_all_slow.py").write_text(textwrap.dedent("""\
            import pytest
            pytestmark = pytest.mark.slow

            def test_one():
                pass
            """))
        (tmp_path / "pytest.ini").write_text(
            "[pytest]\nmarkers =\n    slow: slow\n"
        )
        (tmp_path / "fast_tffm_tpu").mkdir()
        found = Tier1Rule().run(Context(str(tmp_path)))
        assert len(found) == 1 and found[0].rule == "T1001"
        assert found[0].path == "tests/test_all_slow.py"

    def test_obs_metrics_rule_flags_both_directions(self, tmp_path):
        ctx = _mini_repo(tmp_path, """\
            def instrument(tel):
                return tel.counter("ingest.rogue_counter")
            """)
        (tmp_path / "OBSERVABILITY.md").write_text(textwrap.dedent("""\
            ## Metric schema

            | metric | kind | stage | meaning |
            |---|---|---|---|
            | `ingest.stale_metric` | counter | x | gone |
            """))
        by = {f.rule: f for f in ObsMetricsRule().run(ctx)}
        assert "rogue_counter" in by["OB001"].message
        assert by["OB001"].path == "fast_tffm_tpu/mod.py"
        assert "stale_metric" in by["OB002"].message


# ---------------------------------------------------------------------
# framework mechanics: baseline + CLI
# ---------------------------------------------------------------------

class TestBaseline:
    def _violating_ctx(self, tmp_path):
        return _mini_repo(tmp_path, """\
            import threading

            def fire():
                threading.Thread(target=print, daemon=True).start()
            """)

    def test_baseline_suppresses_known_finding(self, tmp_path):
        ctx = self._violating_ctx(tmp_path)
        raw = run_rules([LifecycleRule()], ctx)
        assert len(raw["new"]) == 1
        key = raw["new"][0].key
        bl = tmp_path / "baseline.txt"
        bl.write_text(f"{key}  # grandfathered: fixture\n")
        result = run_rules(
            [LifecycleRule()], ctx, load_baseline(str(bl))
        )
        assert not result["new"]
        assert len(result["baselined"]) == 1
        assert not result["stale"] and not result["uncommented"]

    def test_stale_and_uncommented_entries_reported(self, tmp_path):
        ctx = self._violating_ctx(tmp_path)
        raw = run_rules([LifecycleRule()], ctx)
        key = raw["new"][0].key
        bl = tmp_path / "baseline.txt"
        bl.write_text(
            f"{key}\n"
            "TL001:gone/file.py:Ghost.t  # fixed long ago\n"
        )
        result = run_rules(
            [LifecycleRule()], ctx, load_baseline(str(bl))
        )
        assert result["stale"] == ["TL001:gone/file.py:Ghost.t"]
        assert result["uncommented"] == [key]

    def test_baseline_key_is_line_number_free(self, tmp_path):
        ctx = self._violating_ctx(tmp_path)
        key = run_rules([LifecycleRule()], ctx)["new"][0].key
        # Shift the violation down two lines; the key must not move.
        (tmp_path / "fast_tffm_tpu" / "mod.py").write_text(
            "import threading\n\n\n\n"
            "def fire():\n"
            "    threading.Thread(target=print, daemon=True).start()\n"
        )
        ctx2 = Context(str(tmp_path))
        assert run_rules([LifecycleRule()], ctx2)["new"][0].key == key

    def test_cli_exit_codes(self, tmp_path):
        ctx = self._violating_ctx(tmp_path)
        (tmp_path / "OBSERVABILITY.md").write_text(
            _RS_TABLE.replace("| `ghost` | — | `phantom_block` | "
                              "emitted nowhere |\n", "")
        )
        env = dict(os.environ, PYTHONPATH=_REPO)
        # --no-baseline: the seeded TL005 fails the run...
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--root",
             str(tmp_path), "--no-baseline", "--rules", "lifecycle"],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "TL005" in proc.stdout
        # ...and a baseline carrying it exits 0.
        key = run_rules([LifecycleRule()], ctx)["new"][0].key
        bl = tmp_path / "bl.txt"
        bl.write_text(f"{key}  # fixture\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--root",
             str(tmp_path), "--baseline", str(bl), "--rules",
             "lifecycle"],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------
# the live tree
# ---------------------------------------------------------------------

class TestLiveTree:
    def test_live_tree_clean_or_baselined(self):
        result = lint.run(root=_REPO)
        assert not result["new"], \
            "\n".join(f.render() for f in result["new"])
        assert not result["stale"], result["stale"]
        assert not result["uncommented"], result["uncommented"]

    def test_all_advertised_rules_registered(self):
        ids = set()
        for rule in lint.default_rules():
            ids.update(rule.rule_ids)
        # the five day-one analyzers + the two folded-in ancestors
        for prefix in ("TL", "DA", "LK", "KD", "RS", "T1", "OB"):
            assert any(i.startswith(prefix) for i in ids), prefix


# ---------------------------------------------------------------------
# lint-adjacent runtime gate: package imports warn-clean
# ---------------------------------------------------------------------

_IMPORT_AUDIT = """\
import os, sys, warnings, importlib

root = sys.argv[1]
mods = []
for dirpath, dirnames, files in os.walk(os.path.join(root, "fast_tffm_tpu")):
    dirnames[:] = [d for d in dirnames if d != "__pycache__"]
    for f in sorted(files):
        if f.endswith(".py"):
            rel = os.path.relpath(os.path.join(dirpath, f), root)[:-3]
            mod = rel.replace(os.sep, ".")
            mods.append(mod[:-9] if mod.endswith(".__init__") else mod)
sys.path.insert(0, root)
with warnings.catch_warnings(record=True) as caught:
    warnings.simplefilter("always")
    for m in sorted(set(mods)):
        importlib.import_module(m)
bad = [
    w for w in caught
    if issubclass(w.category, (DeprecationWarning, FutureWarning,
                               PendingDeprecationWarning))
    and ("fast_tffm_tpu" + os.sep) in (w.filename or "")
]
for w in bad:
    print(f"{w.filename}:{w.lineno}: {w.category.__name__}: {w.message}")
sys.exit(1 if bad else 0)
"""


def test_package_imports_raise_no_deprecation_warnings(tmp_path):
    """Importing every package module must trigger no deprecation-class
    warning ATTRIBUTED TO package files (third-party warnings from
    jax's own internals don't count; a deprecated jax API *we* call
    does — the warning's stacklevel lands on our line).  Subprocess:
    this process's import cache would otherwise hide everything."""
    script = tmp_path / "audit.py"
    script.write_text(_IMPORT_AUDIT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(script), _REPO],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert proc.returncode == 0, (
        "package imports raised deprecation-class warnings:\n"
        + proc.stdout + proc.stderr
    )


# ---------------------------------------------------------------------
# regression: the TL005 finding on the shipped tree (trace rotation
# writer thread was started unbound — leaked one daemon thread per
# rotating Tracer for the life of the process)
# ---------------------------------------------------------------------

class TestTracerRotateThreadLifecycle:
    def _rotating_tracer(self, tmp_path):
        from fast_tffm_tpu.obs.trace import Tracer

        return Tracer(
            enabled=True, rotate_events=10,
            rotate_path=str(tmp_path / "trace.json"),
        )

    def test_close_joins_writer_thread(self, tmp_path):
        tracer = self._rotating_tracer(tmp_path)
        assert any(
            th.name == "trace-rotate" for th in threading.enumerate()
        )
        for i in range(25):  # cross the watermark twice
            tracer.emit("ev", 0.0, 0.001, args={"i": i})
        tracer.dump(str(tmp_path / "trace.json"))
        tracer.close()
        assert not any(
            th.name == "trace-rotate" and th.is_alive()
            for th in threading.enumerate()
        )
        # every rotated window landed before close returned
        wins = sorted(p.name for p in tmp_path.glob("trace.*.json"))
        assert len(wins) >= 2

    def test_close_is_idempotent_and_safe_after(self, tmp_path):
        tracer = self._rotating_tracer(tmp_path)
        tracer.close()
        tracer.close()
        # post-close emits fall back to the capped buffer, never hang
        tracer.emit("late", 0.0, 0.001)
        out = tmp_path / "late.json"
        tracer.dump(str(out))
        assert out.exists()

    def test_null_tracer_close_is_noop(self):
        from fast_tffm_tpu.obs.trace import NULL_TRACER

        NULL_TRACER.close()  # must not raise (no rotation machinery)

    def test_reset_rearms_rotation_after_close(self, tmp_path):
        """A warm owner's second run must rotate exactly like the
        first: close() stops run 1's writer thread, reset() re-arms
        (review finding — rotation used to die permanently)."""
        tracer = self._rotating_tracer(tmp_path)
        for i in range(15):
            tracer.emit("ev", 0.0, 0.001, args={"i": i})
        tracer.dump(str(tmp_path / "trace.json"))
        tracer.close()
        run1 = set(p.name for p in tmp_path.glob("trace.*.json"))
        assert run1
        tracer.reset()  # run 2 begins
        assert any(
            th.name == "trace-rotate" and th.is_alive()
            for th in threading.enumerate()
        )
        for i in range(15):
            tracer.emit("ev2", 0.0, 0.001, args={"i": i})
        tracer.dump(str(tmp_path / "trace.json"))
        tracer.close()
        run2 = set(p.name for p in tmp_path.glob("trace.*.json"))
        # run 2 rewrote the same window family from index 0
        assert run2 >= run1 and "trace.0.json" in run2


def test_cli_rules_subset_ignores_other_rules_baseline(tmp_path):
    """`--rules locks` must not report a TL baseline entry as stale
    (review finding: a subset run can't see other rules' findings, so
    their baseline entries are invisible, not fixed)."""
    pkg = tmp_path / "fast_tffm_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1\n")
    bl = tmp_path / "bl.txt"
    bl.write_text("TL001:fast_tffm_tpu/gone.py:Ghost.t  # debt\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--root", str(tmp_path),
         "--baseline", str(bl), "--rules", "locks"],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=_REPO), cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stale baseline entry" not in proc.stdout
