"""C++ parser vs Python oracle: bit-exact agreement (SURVEY.md §2 #1)."""

import numpy as np
import pytest

from fast_tffm_tpu.data import libsvm

native = pytest.importorskip("fast_tffm_tpu.data.native")


@pytest.fixture(scope="module")
def built():
    try:
        native._load()
    except Exception as e:
        pytest.skip(f"native parser build failed: {e}")
    return True


def test_murmur_matches_python(built):
    for token in [b"", b"a", b"abcdefg", b"abcdefgh", b"abcdefghi",
                  b"userid_12345", "féature".encode("utf-8"), b"x" * 1000]:
        assert native.murmur64_native(token) == libsvm.murmur64(token), token


def _random_lines(rng, n, vocab, ffm=False, hash_ids=False):
    lines = []
    for _ in range(n):
        label = rng.choice(["1", "0", "-1"])
        nf = rng.integers(1, 12)
        toks = []
        for _ in range(nf):
            if hash_ids:
                fid = "feat_" + str(rng.integers(0, 10**9))
            else:
                fid = str(rng.integers(0, vocab * 2))  # exercise mod wrap
            val = f"{rng.uniform(-2, 2):.4f}"
            if ffm:
                toks.append(f"{rng.integers(0, 99)}:{fid}:{val}")
            elif rng.uniform() < 0.1:
                toks.append(fid)  # bare feature
            else:
                toks.append(f"{fid}:{val}")
        lines.append(f"{label} {' '.join(toks)}")
    return lines


@pytest.mark.parametrize("ffm,hash_ids", [(False, False), (False, True),
                                          (True, False), (True, True)])
def test_native_matches_oracle(built, rng, ffm, hash_ids):
    vocab, max_features, field_num = 1000, 16, 7
    lines = _random_lines(rng, 64, vocab, ffm, hash_ids)
    parser = native.NativeParser(
        vocab, max_features, hash_feature_id=hash_ids,
        field_num=field_num if ffm else 0, num_threads=4,
    )
    got = parser.parse_batch(lines, batch_size=64)
    exs = libsvm.parse_lines(lines, vocab, hash_ids, field_num if ffm else 0)
    want = libsvm.make_batch(exs, 64, max_features)
    np.testing.assert_array_equal(got.labels, want.labels)
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_array_equal(got.vals, want.vals)
    np.testing.assert_array_equal(got.fields, want.fields)
    np.testing.assert_array_equal(got.weights, want.weights)


def test_fuzz_native_matches_oracle_on_adversarial_tokens(built):
    """Seeded fuzz over pathological tokens: both parsers must agree on
    ACCEPT vs REJECT for every line, and bit-exactly on accepted values.
    Found in round 4: Python accepted underscore literals ("1_0") and
    strtof accepted hex floats ("0x10") / nan payloads — both sides now
    pin to the strict ASCII grammar."""
    frags = [
        "1", "0", "-1", "2.5", ".5", "+.5", "-0.25", "1e5", "1E-3", "nan",
        "inf", "-inf", "infinity", "0x1p3", "1_000", "00123", "", "abc",
        "1.2.3", "1..2", "+", "-", ":", "::", "1:", ":1", "1:2:3:4", "%",
        "123456789012345678901234567890", "1:+2", "1:-2e-2", "1:nan",
        "1:0x10", "1:1_0", "007:1", "1.", "5:.5", "3:1e", "2:1.5e+2",
        # Double-rounding traps: >15 significant digits near f32 tie
        # midpoints — native must strtod-then-cast like Python+numpy,
        # not single-round with strtof.
        "1:16777217.0000000000000001", "1:0.10000000000000000555",
        "2:33554433.0000000000000001",
    ]
    rng = np.random.default_rng(42)
    parser = native.NativeParser(1000, 8, num_threads=1)
    for _ in range(2000):
        n = int(rng.integers(1, 5))
        line = " ".join(rng.choice(frags) for _ in range(n))
        if not line.strip() or line.lstrip().startswith("#"):
            continue  # blank/comment conventions tested separately
        try:
            want = libsvm.make_batch(
                libsvm.parse_lines([line], 1000, False, 0), 1, 8
            )
            oracle_ok = True
        except ValueError:
            oracle_ok = False
        try:
            got = parser.parse_batch([line], 1)
            native_ok = True
        except ValueError:
            native_ok = False
        assert oracle_ok == native_ok, (
            f"accept/reject mismatch (oracle={oracle_ok}) on {line!r}"
        )
        if oracle_ok:
            for f in ("labels", "ids", "vals", "fields", "weights"):
                np.testing.assert_array_equal(
                    getattr(got, f), getattr(want, f),
                    err_msg=f"{f} mismatch on {line!r}",
                )


def test_parse_batch_blank_and_comment_weight_zero(built):
    """parse_batch keeps row alignment: blank/comment lines become
    weight-0 rows (a weight-1 empty row would train on a phantom
    example)."""
    got = native.NativeParser(100, 4, num_threads=1).parse_batch(
        ["1 5:1.0", "", "# note", "0 7:2.0"], 4
    )
    np.testing.assert_array_equal(got.weights, [1, 0, 0, 1])
    assert got.ids[0, 0] == 5 and got.ids[3, 0] == 7


def test_native_truncation_counted(built):
    parser = native.NativeParser(100, 2, num_threads=1)
    parser.parse_batch(["1 1:1 2:1 3:1 4:1"], batch_size=1)
    assert parser.truncated_features == 2


def test_native_weights(built):
    parser = native.NativeParser(100, 4, num_threads=1)
    b = parser.parse_batch(["1 1:1", "0 2:1"], batch_size=4, weights=[0.5, 2.0])
    np.testing.assert_array_equal(b.weights, [0.5, 2.0, 0, 0])


def test_native_malformed_raises(built):
    parser = native.NativeParser(100, 4, num_threads=1)
    for bad in [
        "1 a:b:c:d",      # too many colons
        "notalabel 1:1",  # non-numeric label
        "1x 1:1",         # partially-numeric label (float('1x') raises)
        "1 :2",           # empty integer id (int('') raises)
        "1 3:",           # empty value (float('') raises)
        "1 :5:0.5",       # empty field (int('') raises)
    ]:
        with pytest.raises(ValueError):
            parser.parse_batch([bad], batch_size=1)
    # Error message names the offending line.
    with pytest.raises(ValueError, match="batch line 1"):
        parser.parse_batch(["1 1:1", "0 bad::x"], batch_size=2)


def test_native_malformed_beyond_truncation_still_raises(built):
    """A malformed token past max_features must error (like the oracle),
    not be silently dropped by truncation."""
    parser = native.NativeParser(100, 2, num_threads=1)
    with pytest.raises(ValueError):
        parser.parse_batch(["1 1:1 2:1 3:1 bad:"], batch_size=1)


def test_native_long_ids_match_python_int_semantics(built):
    """Ids longer than int64 must still mod like Python's unbounded int."""
    parser = native.NativeParser(1000, 4, num_threads=1)
    cases = [
        "1 9223372036854775806:1.0",        # near int64 max
        "1 99999999999999999999999999:1.0",  # way past int64
        "1 -7:1.0",                          # negative id, Python-mod
    ]
    got = parser.parse_batch(cases, batch_size=3)
    exs = libsvm.parse_lines(cases, 1000)
    want = libsvm.make_batch(exs, 3, 4)
    np.testing.assert_array_equal(got.ids, want.ids)


def test_native_zero_padded_tokens_match_oracle(built):
    """Leading zeros must not count toward the digit cap: Python's int()
    accepts '000...0123' so the native parser must too (labels, fields,
    and ids alike)."""
    pad = "0" * 25
    cases = [
        f"{pad}1 {pad}42:1.5",            # padded label + padded id
        f"1 {pad}7:{pad}2:1.0",           # padded field (ffm form)
        f"0 {'0' * 30}:1.0",              # all-zero id of absurd length
    ]
    parser = native.NativeParser(1000, 4, field_num=3, num_threads=1)
    got = parser.parse_batch(cases, batch_size=3)
    exs = libsvm.parse_lines(cases, 1000, field_num=3)
    want = libsvm.make_batch(exs, 3, 4)
    np.testing.assert_array_equal(got.labels, want.labels)
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_array_equal(got.fields, want.fields)
    np.testing.assert_array_equal(got.vals, want.vals)


def test_native_vocab_size_bounds(built):
    with pytest.raises(ValueError, match="out of range"):
        native.NativeParser(1 << 60, 4)


def test_native_empty_hash_id_matches_oracle(built):
    """Hash mode hashes the empty string (Python murmur64(b'') is valid)."""
    parser = native.NativeParser(100, 4, hash_feature_id=True, num_threads=1)
    got = parser.parse_batch(["1 :2.0"], batch_size=1)
    exs = libsvm.parse_lines(["1 :2.0"], 100, hash_feature_id=True)
    want = libsvm.make_batch(exs, 1, 4)
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_array_equal(got.vals, want.vals)


def test_native_multithreaded_large_batch(built, rng):
    vocab = 5000
    lines = _random_lines(rng, 2048, vocab)
    parser = native.NativeParser(vocab, 16, num_threads=8)
    got = parser.parse_batch(lines, batch_size=2048)
    exs = libsvm.parse_lines(lines, vocab)
    want = libsvm.make_batch(exs, 2048, 16)
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_array_equal(got.vals, want.vals)
