"""Telemetry layer (obs/): instrument correctness under concurrency,
heartbeat/metrics JSONL schema, starvation-vs-dispatch wall-clock
accounting, and zero behavior change with telemetry disabled."""

import json
import os
import threading
import time

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import libsvm
from fast_tffm_tpu.data.pipeline import DevicePrefetcher, EpochEnd


# ---------------------------------------------------------------------------
# Instruments
# ---------------------------------------------------------------------------


class TestInstruments:
    def test_counter_concurrent_writers(self):
        c = obs.Telemetry().counter("c")
        n_threads, n_each = 8, 5000

        def work():
            for _ in range(n_each):
                c.add(1)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * n_each

    def test_timer_concurrent_writers(self):
        t = obs.Telemetry().timer("t")
        n_threads, n_each = 6, 2000

        def work():
            for _ in range(n_each):
                t.observe(0.001)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert t.count == n_threads * n_each
        np.testing.assert_allclose(t.total_s, 0.001 * t.count, rtol=1e-6)

    def test_timer_percentiles(self):
        t = obs.Telemetry().timer("t")
        for ms in range(1, 101):  # 1..100 ms
            t.observe(ms / 1e3)
        snap = t.snapshot()
        assert snap["count"] == 100
        assert 45 <= snap["p50_ms"] <= 55
        assert 90 <= snap["p95_ms"] <= 100
        assert snap["max_ms"] == pytest.approx(100.0)
        np.testing.assert_allclose(snap["total_s"], 5.05, rtol=1e-6)

    def test_timer_ring_reports_recent_window(self):
        """Percentiles describe the RECENT window; count/total stay
        exact over the whole run."""
        t = obs.Telemetry().timer("t")
        for _ in range(1000):
            t.observe(0.001)
        for _ in range(600):  # > ring size: only these remain visible
            t.observe(0.1)
        snap = t.snapshot()
        assert snap["count"] == 1600
        np.testing.assert_allclose(snap["total_s"], 1.0 + 60.0, rtol=1e-6)
        assert snap["p50_ms"] == pytest.approx(100.0)

    def test_timer_context_manager(self):
        t = obs.Telemetry().timer("t")
        with t.time():
            time.sleep(0.01)
        assert t.count == 1
        assert 0.005 < t.total_s < 1.0

    def test_gauge_and_snapshot_samples(self):
        tel = obs.Telemetry()
        tel.gauge("g").set(7.5)
        tel.sample("depth", lambda: 3)
        tel.sample("broken", lambda: 1 // 0)
        snap = tel.snapshot()
        assert snap["gauges"]["g"] == 7.5
        assert snap["gauges"]["depth"] == 3
        assert snap["gauges"]["broken"] == -1  # raising sample degrades

    def test_depth_hist_buckets_and_stats(self):
        """Power-of-two buckets: every observed depth lands in its band;
        mean/max/count summarize the full event stream (what a point-
        sampled gauge cannot see between heartbeats)."""
        h = obs.Telemetry().depth_hist("q")
        for d in (0, 0, 1, 2, 3, 5, 9, 70):
            h.observe(d)
        h.observe(-1)  # degraded mp.Queue qsize: ignored
        snap = h.snapshot()
        assert snap["count"] == 8
        assert snap["max"] == 70
        assert snap["mean"] == pytest.approx(90 / 8)
        assert snap["buckets"] == {
            "0": 2, "1": 1, "2-3": 2, "4-7": 1, "8-15": 1, "64-127": 1,
        }

    def test_depth_hist_concurrent_writers(self):
        h = obs.Telemetry().depth_hist("q")
        n_threads, n_each = 6, 3000

        def work():
            for i in range(n_each):
                h.observe(i % 7)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.snapshot()["count"] == n_threads * n_each

    def test_depth_hist_in_snapshot(self):
        tel = obs.Telemetry()
        tel.depth_hist("ingest.work_q_depth").observe(3)
        snap = tel.snapshot()
        assert snap["depths"]["ingest.work_q_depth"]["count"] == 1
        assert tel.depth_hist("x").snapshot() == {"count": 0}

    def test_registry_idempotent_by_name(self):
        tel = obs.Telemetry()
        assert tel.counter("a") is tel.counter("a")
        assert tel.timer("b") is tel.timer("b")
        assert tel.gauge("c") is tel.gauge("c")
        assert tel.depth_hist("d") is tel.depth_hist("d")

    def test_disabled_registry_is_noop(self):
        tel = obs.Telemetry(enabled=False)
        c, g, t = tel.counter("a"), tel.gauge("b"), tel.timer("c")
        h = tel.depth_hist("d")
        c.add(5)
        g.set(1.0)
        t.observe(1.0)
        h.observe(4)
        with t.time():
            pass
        tel.sample("d", lambda: 1)
        assert c.value == 0 and g.value == 0.0 and t.count == 0
        assert h.count == 0
        assert tel.snapshot() == {}
        assert obs.NULL.snapshot() == {}

    def test_trace_span_is_context_manager(self):
        with obs.trace_span("tffm:test"):
            pass


class TestJsonlWriter:
    def test_concurrent_writers_produce_valid_lines(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        w = obs.JsonlWriter(path)
        n_threads, n_each = 4, 200

        def work(i):
            for j in range(n_each):
                w.write({"thread": i, "j": j})

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        w.close()
        records = [json.loads(line) for line in open(path)]
        assert len(records) == n_threads * n_each

    def test_heartbeat_emits_and_skips_none(self, tmp_path):
        path = str(tmp_path / "hb.jsonl")
        w = obs.JsonlWriter(path)
        beats = []

        def build():
            beats.append(1)
            if len(beats) == 1:
                return None  # nothing to report yet -> no record
            return {"record": "heartbeat", "step": len(beats)}

        hb = obs.Heartbeat(10.0, build, writer=w)
        hb.beat()
        hb.beat()
        hb.close()
        hb.close()  # idempotent
        w.close()
        records = [json.loads(line) for line in open(path)]
        assert [r["step"] for r in records] == [2]


# ---------------------------------------------------------------------------
# Wall-clock accounting on a synthetic slow pipeline
# ---------------------------------------------------------------------------


def _batch(n=8, f=3):
    return libsvm.Batch(
        labels=np.zeros((n,), np.float32),
        ids=np.zeros((n, f), np.int32),
        vals=np.ones((n, f), np.float32),
        fields=np.zeros((n, f), np.int32),
        weights=np.ones((n,), np.float32),
    )


class TestAccounting:
    def test_starvation_plus_dispatch_accounts_for_wall(self):
        """A deliberately slow source starves the consumer: the
        wait_input + dispatch totals must account for the loop's wall
        time, and the split must say ingest-bound."""
        tel = obs.Telemetry()
        parse_sleep, dispatch_sleep, n_items = 0.01, 0.001, 12

        def slow_source():
            for _ in range(n_items):
                time.sleep(parse_sleep)  # synthetic slow parse
                yield _batch()
            yield EpochEnd(0)

        pf = DevicePrefetcher(
            slow_source(), 2, lambda b: b, depth=2, telemetry=tel
        )
        t_wait = tel.timer("train.wait_input")
        t_disp = tel.timer("train.dispatch")
        it = iter(pf)
        t0 = time.perf_counter()
        try:
            while True:
                with t_wait.time():
                    item = next(it, None)
                if item is None:
                    break
                if isinstance(item, EpochEnd):
                    continue
                with t_disp.time():
                    time.sleep(dispatch_sleep)  # synthetic dispatch
        finally:
            pf.close()
        wall = time.perf_counter() - t0
        accounted = t_wait.total_s + t_disp.total_s
        # Everything the loop did was wait or "dispatch": the two
        # components must explain (nearly) all of the measured wall.
        assert accounted <= wall * 1.02
        assert accounted >= wall * 0.85
        # And the breakdown must finger ingest as the bottleneck.
        assert t_wait.total_s > 3 * t_disp.total_s
        snap = tel.snapshot()
        assert snap["counters"]["prefetch.super_batches"] == n_items // 2


# ---------------------------------------------------------------------------
# End-to-end: trainer heartbeat/metrics schema + disabled == identical
# ---------------------------------------------------------------------------


def _write_libsvm(path, n_lines, vocab=50, n_feat=3, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.choice(vocab, size=n_feat, replace=False)
            toks = " ".join(f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats)
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


def _train_cfg(data, tmp_path, tag, **kw):
    defaults = dict(
        vocabulary_size=50,
        factor_num=4,
        model_file=str(tmp_path / f"model_{tag}"),
        train_files=[data],
        epoch_num=2,
        batch_size=32,
        max_features=4,
        log_steps=4,
        thread_num=2,
        steps_per_dispatch=2,
        seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.fixture(scope="module")
def train_file(tmp_path_factory):
    out = tmp_path_factory.mktemp("tele_data")
    return _write_libsvm(out / "train.libsvm", 320)


class TestTrainerTelemetry:
    def test_metrics_stream_schema_and_final_accounting(
        self, train_file, tmp_path
    ):
        from fast_tffm_tpu.train.loop import Trainer

        mf = str(tmp_path / "metrics.jsonl")
        cfg = _train_cfg(
            train_file, tmp_path, "hb",
            validation_files=[train_file], validation_steps=8,
            metrics_file=mf, heartbeat_secs=0.05,
        )
        result = Trainer(cfg).train()

        records = [json.loads(line) for line in open(mf)]
        kinds = [r.get("record") for r in records]
        assert all(k is not None for k in kinds), "untyped record emitted"

        # Run header: first record, self-describing identity.
        assert kinds[0] == "run_header"
        header = records[0]
        for key in ("config_fingerprint", "steps_per_dispatch",
                    "ingest_mode", "jax_version", "backend", "mesh",
                    "batch_size", "resume_step"):
            assert key in header, key
        assert header["ingest_mode"] == "threads"

        # Train and validation records share the progression fields.
        trains = [r for r in records if r["record"] == "train"]
        valids = [r for r in records if r["record"] == "validation"]
        assert trains and valids
        for r in trains + valids:
            for key in ("step", "examples", "loss", "auc", "elapsed"):
                assert key in r, key

        # Heartbeats (0.05 s cadence over a multi-second jit+train run).
        beats = [r for r in records if r["record"] == "heartbeat"]
        assert beats
        for key in ("step", "elapsed", "ingest_wait_frac", "wait_input_s",
                    "dispatch_s", "other_s", "stages",
                    "truncated_features", "out_of_range_batches",
                    "ingest_cache"):
            assert key in beats[-1], key

        # Final record: exact end-of-run accounting — the starvation +
        # dispatch (+ other) components must sum to measured wall time.
        finals = [r for r in records if r["record"] == "final"]
        assert len(finals) == 1
        final = finals[0]
        total = (final["wait_input_s"] + final["dispatch_s"]
                 + final["other_s"])
        assert total == pytest.approx(final["elapsed"], abs=0.02)
        assert 0.0 <= final["ingest_wait_frac"] <= 1.0
        timers = final["stages"]["timers"]
        for stage in ("ingest.parse", "prefetch.stack",
                      "prefetch.device_put", "train.wait_input",
                      "train.dispatch"):
            assert stage in timers, stage
            assert timers[stage]["count"] > 0
        counters = final["stages"]["counters"]
        assert counters["ingest.batches"] == 20  # 10 batches x 2 epochs
        assert counters["ingest.examples"] == 640
        assert counters["prefetch.super_batches"] == 10
        # Queue occupancy is a per-put/get histogram now, not a
        # heartbeat-time point sample: every queue logged its events.
        depths = final["stages"]["depths"]
        for q in ("ingest.work_q_depth", "ingest.out_q_depth",
                  "prefetch.out_q_depth"):
            assert depths[q]["count"] > 0, q
            assert "buckets" in depths[q], q

        # Adopted counters ride the returned results dict too.
        tm = result["train"]
        for key in ("truncated_features", "out_of_range_batches",
                    "ingest_cache", "ingest_wait_frac", "wait_input_s",
                    "dispatch_s"):
            assert key in tm, key
        assert tm["truncated_features"] == 0
        assert tm["out_of_range_batches"] == 0

    def test_truncation_counter_in_results(self, tmp_path):
        """max_features smaller than the widest line: the drop count
        must surface in train results, not just a log warning."""
        from fast_tffm_tpu.train.loop import Trainer

        data = _write_libsvm(tmp_path / "wide.libsvm", 64, n_feat=4)
        cfg = _train_cfg(
            data, tmp_path, "trunc", max_features=2, epoch_num=1,
        )
        result = Trainer(cfg).train()
        # 64 lines x (4 features - 2 kept) dropped.
        assert result["train"]["truncated_features"] == 128

    def test_disabled_telemetry_changes_nothing(self, train_file, tmp_path):
        """Telemetry off must be bit-identical training: same stream,
        same losses; instruments all no-op; stream still typed."""
        from fast_tffm_tpu.train.loop import Trainer

        results = {}
        for tag, enabled in (("on", True), ("off", False)):
            mf = str(tmp_path / f"m_{tag}.jsonl")
            cfg = _train_cfg(
                train_file, tmp_path, tag,
                telemetry=enabled, metrics_file=mf, heartbeat_secs=0.05,
            )
            trainer = Trainer(cfg)
            results[tag] = (trainer.train(), trainer, mf)

        on, off = results["on"][0], results["off"][0]
        assert on["train"]["loss"] == off["train"]["loss"]
        assert on["train"]["auc"] == off["train"]["auc"]
        assert on["train"]["examples"] == off["train"]["examples"]

        off_trainer = results["off"][1]
        assert off_trainer.telemetry.snapshot() == {}
        off_records = [
            json.loads(line) for line in open(results["off"][2])
        ]
        # Liveness beats survive telemetry-off: the skip-until-first-
        # dispatch guard must not key on a no-op instrument (whose count
        # is a permanent 0) or a --no_telemetry run never heartbeats.
        assert any(r.get("record") == "heartbeat" for r in off_records)
        final = off_records[-1]
        assert final["record"] == "final"
        assert final["stages"] == {}  # no-op instruments report nothing
        # The accounting split is unavailable when disabled — but
        # honestly zero, never fabricated.
        assert final["wait_input_s"] == 0.0
        assert final["dispatch_s"] == 0.0

    def test_heartbeat_skips_until_first_dispatch(
        self, train_file, tmp_path, monkeypatch
    ):
        """First-heartbeat ingest_wait_frac over-count fix: before the
        first dispatch the wait timer has been running with NOTHING to
        attribute it against (jit compile; a resume inside a cached
        replay epoch re-parsing epoch 0 for the rebuild), so a beat in
        that window used to report ingest_wait_frac ≈ 1 and a spurious
        INGEST-BOUND verdict.  Heartbeat.build's None contract now
        actually engages: beats are skipped until the first dispatch
        timer sample exists."""
        import fast_tffm_tpu.train.loop as loop_mod

        real_pipeline = loop_mod.BatchPipeline

        class SlowFirstPipeline(real_pipeline):
            # Models the long pre-dispatch window (cache rebuild /
            # first-window parse) deterministically.
            def __iter__(self):
                time.sleep(0.4)
                yield from super().__iter__()

        monkeypatch.setattr(loop_mod, "BatchPipeline", SlowFirstPipeline)
        mf = str(tmp_path / "skip.jsonl")
        cfg = _train_cfg(
            train_file, tmp_path, "hb_skip", epoch_num=1,
            metrics_file=mf, heartbeat_secs=0.05,
        )
        Trainer = loop_mod.Trainer
        Trainer(cfg).train()
        records = [json.loads(line) for line in open(mf)]
        beats = [r for r in records if r.get("record") == "heartbeat"]
        # ~8 beat opportunities elapsed during the 0.4 s pre-dispatch
        # sleep alone; NONE may have produced a dispatch-less record.
        for r in beats:
            count = (
                r["stages"].get("timers", {})
                .get("train.dispatch", {}).get("count", 0)
            )
            assert count > 0, "heartbeat emitted before first dispatch"
            assert r["ingest_wait_frac"] < 1.0
        # The final record still always emits, dispatches or not.
        assert [r for r in records if r.get("record") == "final"]

    def test_first_interval_rate_seeded_from_restored_metrics(
        self, train_file, tmp_path, caplog
    ):
        """A second train() on a warm trainer carries prior examples in
        the metric state; the first interval's ex/s must not be inflated
        by them (last_log_ex seeds from the restored count)."""
        import logging

        from fast_tffm_tpu.train.loop import Trainer

        cfg = _train_cfg(train_file, tmp_path, "resume", epoch_num=1)
        trainer = Trainer(cfg)
        trainer.train()
        with caplog.at_level(logging.INFO, "fast_tffm_tpu.train.loop"):
            result2 = trainer.train()
        # Per-RUN accounting: the second run's telemetry must not carry
        # the first run's totals (ingest_wait_frac would exceed 1 and
        # the stage counters would double).
        assert 0.0 <= result2["train"]["ingest_wait_frac"] <= 1.0
        snap = trainer.telemetry.snapshot()
        assert snap["counters"]["ingest.batches"] == 10  # run 2 only
        assert snap["counters"]["ingest.examples"] == 320
        rates = []
        for rec in caplog.records:
            if rec.msg.startswith("step %d examples"):
                rates.append(float(rec.args[-1]))
        assert rates, "no interval log lines captured"
        # 320 examples in well under 60s of interval -> a sane rate is
        # bounded; the pre-fix bias added the FIRST run's 320 examples
        # to the first interval, roughly doubling it.  Check the first
        # interval is not wildly larger than the later ones.
        if len(rates) > 1:
            assert rates[0] <= 3 * max(rates[1:])
