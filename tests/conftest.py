"""Test environment: force an 8-device virtual CPU mesh.

This is the TPU-world analogue of the reference's "localhost PS cluster"
smoke tests (SURVEY.md §4): multi-chip sharding paths run on 8 fake CPU
devices so the full mesh logic is exercised without TPU hardware.

Note on this machine's TPU tunnel: a global sitecustomize registers an
'axon' PJRT plugin and sets ``jax_platforms="axon,cpu"`` via jax.config
(which overrides the JAX_PLATFORMS env var), and initializing that backend
dials a remote TPU. Tests must stay CPU-only and leave the tunnel alone, so
we set the XLA flag before importing jax, then force the platform list back
to "cpu" through jax.config.
"""

from fast_tffm_tpu.platform import pin_cpu

# Must happen before jax initializes its CPU client.
pin_cpu(8)

import jax  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def set_data_state(model_file: str, **fields) -> None:
    """Rewrite checkpointed input-pipeline position fields, preserving the
    saved stream fingerprint — the shared way tests simulate a mid-epoch
    interruption (tests that deliberately write a raw/fingerprint-less
    data_state.json to exercise back-compat keep doing so inline)."""
    import json

    from fast_tffm_tpu.train import checkpoint

    ds = checkpoint.restore_data_state(model_file)
    assert ds is not None, f"no data_state in {model_file}"
    ds.update(fields)
    with open(f"{model_file}/data_state.json", "w") as f:
        json.dump(ds, f)
