"""Parser oracle tests: golden libsvm lines -> ids/vals (SURVEY.md §4 item 1)."""

import numpy as np
import pytest

from fast_tffm_tpu.data import libsvm


def test_murmur64_golden():
    # Golden values for MurmurHash64A(seed=0), fixed forever; the C++
    # extension must reproduce these exactly.
    assert libsvm.murmur64(b"") == 0
    cases = {
        b"a": libsvm.murmur64(b"a"),
        b"abcdefgh": libsvm.murmur64(b"abcdefgh"),
        b"abcdefghi": libsvm.murmur64(b"abcdefghi"),
    }
    for data, h in cases.items():
        assert 0 <= h < 2**64
        assert libsvm.murmur64(data) == h  # deterministic
    # Distinct inputs hash distinctly (sanity, not a proof).
    assert len(set(cases.values())) == len(cases)


def test_parse_line_libsvm():
    ex = libsvm.parse_line("1 3:0.5 7:1.25 2:1", vocabulary_size=100)
    assert ex.label == 1.0
    assert ex.ids == [3, 7, 2]
    assert ex.vals == [0.5, 1.25, 1.0]
    assert ex.fields == [0, 0, 0]


def test_parse_line_label_conventions():
    assert libsvm.parse_line("-1 1:1", 10).label == 0.0
    assert libsvm.parse_line("0 1:1", 10).label == 0.0
    assert libsvm.parse_line("1 1:1", 10).label == 1.0


def test_parse_line_ffm_format():
    ex = libsvm.parse_line("0 2:13:0.5 1:4:2.0", vocabulary_size=100, field_num=4)
    assert ex.fields == [2, 1]
    assert ex.ids == [13, 4]
    assert ex.vals == [0.5, 2.0]


def test_parse_line_bare_feature():
    ex = libsvm.parse_line("1 5 9", vocabulary_size=100)
    assert ex.ids == [5, 9]
    assert ex.vals == [1.0, 1.0]


def test_parse_line_hashing():
    ex = libsvm.parse_line(
        "1 userid_12345:1 cat:0.5", vocabulary_size=1000, hash_feature_id=True
    )
    assert all(0 <= i < 1000 for i in ex.ids)
    assert ex.ids[0] == libsvm.murmur64(b"userid_12345") % 1000


def test_parse_line_id_mod_vocab():
    ex = libsvm.parse_line("1 1003:1", vocabulary_size=1000)
    assert ex.ids == [3]


def test_parse_skips_blank_and_comment():
    assert libsvm.parse_line("", 10) is None
    assert libsvm.parse_line("# comment", 10) is None


def test_make_batch_padding():
    exs = libsvm.parse_lines(["1 1:1 2:2", "0 3:3"], vocabulary_size=10)
    b = libsvm.make_batch(exs, batch_size=4, max_features=3)
    assert b.ids.shape == (4, 3)
    np.testing.assert_array_equal(b.labels, [1, 0, 0, 0])
    np.testing.assert_array_equal(b.ids[0], [1, 2, 0])
    np.testing.assert_array_equal(b.vals[1], [3, 0, 0])
    # Padded examples have weight 0; real ones weight 1.
    np.testing.assert_array_equal(b.weights, [1, 1, 0, 0])


def test_make_batch_truncates():
    exs = libsvm.parse_lines(["1 1:1 2:2 3:3 4:4"], vocabulary_size=10)
    b = libsvm.make_batch(exs, batch_size=1, max_features=2)
    np.testing.assert_array_equal(b.ids[0], [1, 2])


def test_make_batch_weights():
    exs = libsvm.parse_lines(["1 1:1", "0 2:1"], vocabulary_size=10)
    b = libsvm.make_batch(exs, batch_size=2, max_features=2, weights=[0.5, 2.0])
    np.testing.assert_array_equal(b.weights, [0.5, 2.0])


def test_make_batch_overflow_raises():
    exs = libsvm.parse_lines(["1 1:1", "0 2:1"], vocabulary_size=10)
    with pytest.raises(ValueError):
        libsvm.make_batch(exs, batch_size=1, max_features=2)
