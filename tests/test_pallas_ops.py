"""Pallas FmScorer/FmGrad kernels vs the jnp oracle (interpret mode on CPU).

SURVEY.md §4 "do better" item 2: kernel tests against a pure-jnp reference
FM with gradient checks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import fm_pallas, interaction


@pytest.fixture
def problem(rng):
    b, f, k = 64, 13, 8
    rows = rng.normal(size=(b, f, 1 + k)).astype(np.float32) * 0.3
    vals = rng.normal(size=(b, f)).astype(np.float32)
    # Some padded slots, like real batches.
    vals[:, -3:] = 0.0
    return jnp.asarray(rows), jnp.asarray(vals)


def test_pallas_forward_matches_oracle(problem):
    rows, vals = problem
    scores_p, s1_p = fm_pallas.fm_scores_pallas(rows, vals, interpret=True)
    scores_o, s1_o = interaction._scores_jnp(rows, vals)
    np.testing.assert_allclose(np.asarray(scores_p), np.asarray(scores_o),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(s1_p), np.asarray(s1_o),
                               rtol=1e-5, atol=1e-6)


def test_pallas_backward_matches_closed_form(problem, rng):
    rows, vals = problem
    _, s1 = interaction._scores_jnp(rows, vals)
    g = jnp.asarray(rng.normal(size=(rows.shape[0],)).astype(np.float32))
    drows_p = fm_pallas.fm_grad_pallas(rows, vals, s1, g, interpret=True)
    drows_o = interaction._grads_jnp(rows, vals, s1, g)
    np.testing.assert_allclose(np.asarray(drows_p), np.asarray(drows_o),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("use_pallas", [False, True, "flat"])
def test_interaction_custom_vjp_matches_autodiff(problem, use_pallas):
    """The closed-form FmGrad must equal autodiff through the oracle."""
    rows, vals = problem

    def loss_custom(rows):
        return jnp.sum(jnp.sin(interaction.fm_interaction(rows, vals,
                                                          use_pallas)))

    def loss_auto(rows):
        scores, _ = interaction._scores_jnp(rows, vals)
        return jnp.sum(jnp.sin(scores))

    v_c, g_c = jax.value_and_grad(loss_custom)(rows)
    v_a, g_a = jax.value_and_grad(loss_auto)(rows)
    np.testing.assert_allclose(float(v_c), float(v_a), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_a),
                               rtol=1e-4, atol=1e-5)


def test_interaction_matches_model_scores(problem):
    """fm_interaction + w0 == fm.fm_scores on the same gather."""
    rows, vals = problem
    k = rows.shape[-1] - 1
    vocab = 64
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.normal(size=(vocab, 1 + k)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, vocab, size=vals.shape), jnp.int32)
    params = fm.FmParams(w0=jnp.float32(0.2), table=table)
    want = fm.fm_scores(params, ids, vals, factor_num=k)
    got = 0.2 + interaction.fm_interaction(table[ids], vals, False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_block_b_divides():
    for b in (8, 64, 100, 256, 1000, 16384):
        for f, d in ((39, 9), (64, 17)):
            bytes_per_row = 4 * (2 * fm_pallas._pad128(f * d)
                                 + fm_pallas._pad128(f))
            tb = fm_pallas._block_b(b, bytes_per_row)
            assert b % tb == 0
            # double-buffered blocks stay under the VMEM budget
            assert 2 * 3 * tb * bytes_per_row <= 6 * 1024 * 1024 or tb <= 8


@pytest.mark.parametrize("b", [7, 1000, 1009])
def test_pallas_kernels_odd_batch_sizes(rng, b):
    """Prime / non-8-multiple batches pad to sane block sizes instead of
    degenerating to 1-row blocks — and still match the oracle exactly."""
    f, k = 13, 8
    rows = jnp.asarray(rng.normal(size=(b, f, 1 + k)).astype(np.float32) * 0.3)
    vals = jnp.asarray(rng.normal(size=(b, f)).astype(np.float32))
    # The padded batch keeps sublane-aligned tiles.
    bp = fm_pallas._pad_batch(b)
    assert bp % 128 == 0
    tb = fm_pallas._block_b(bp, 4 * (2 * fm_pallas._pad128(f * (1 + k))
                                     + fm_pallas._pad128(f)))
    assert tb % 8 == 0

    scores_p, s1_p = fm_pallas.fm_scores_pallas(rows, vals, interpret=True)
    scores_o, s1_o = interaction._scores_jnp(rows, vals)
    assert scores_p.shape == (b,)
    np.testing.assert_allclose(np.asarray(scores_p), np.asarray(scores_o),
                               rtol=1e-5, atol=1e-6)
    g = jnp.asarray(rng.normal(size=(b,)).astype(np.float32))
    drows_p = fm_pallas.fm_grad_pallas(rows, vals, s1_p, g, interpret=True)
    drows_o = interaction._grads_jnp(rows, vals, s1_o, g)
    assert drows_p.shape == (b, f, 1 + k)
    np.testing.assert_allclose(np.asarray(drows_p), np.asarray(drows_o),
                               rtol=1e-4, atol=1e-5)


def test_flat_forward_matches_oracle(problem):
    rows, vals = problem
    scores_f, s1_f = interaction._scores_flat(rows, vals)
    scores_o, s1_o = interaction._scores_jnp(rows, vals)
    np.testing.assert_allclose(np.asarray(scores_f), np.asarray(scores_o),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(s1_f), np.asarray(s1_o),
                               rtol=1e-5, atol=1e-6)


def test_flat_backward_matches_closed_form(problem, rng):
    rows, vals = problem
    _, s1 = interaction._scores_jnp(rows, vals)
    g = jnp.asarray(rng.normal(size=(rows.shape[0],)).astype(np.float32))
    drows_f = interaction._grads_flat(rows, vals, s1, g)
    drows_o = interaction._grads_jnp(rows, vals, s1, g)
    np.testing.assert_allclose(np.asarray(drows_f), np.asarray(drows_o),
                               rtol=1e-5, atol=1e-6)


def test_flat_bf16_keeps_cotangent_dtype(problem, rng):
    rows, vals = problem
    rows16 = rows.astype(jnp.bfloat16)
    vals16 = vals.astype(jnp.bfloat16)
    scores, s1 = interaction._scores_flat(rows16, vals16)
    assert scores.dtype == jnp.float32 and s1.dtype == jnp.float32
    g = jnp.asarray(rng.normal(size=(rows.shape[0],)).astype(np.float32))
    drows = interaction._grads_flat(rows16, vals16, s1, g)
    assert drows.dtype == jnp.bfloat16


def test_interaction_impl_name_rejects_unknown():
    with pytest.raises(ValueError, match="unknown interaction impl"):
        interaction._impl_name("cuda")


def test_interaction_check_grads(problem):
    """SURVEY.md §4 item 2: gradient-check the interaction op numerically
    (second-order finite differences), not just against the closed form."""
    from jax.test_util import check_grads

    rows, vals = problem
    for impl in (False, "flat", True):  # True = pallas (interpret on CPU)
        check_grads(
            lambda r: interaction.fm_interaction(r, vals, impl),
            (rows,), order=1, modes=("rev",), atol=5e-2, rtol=5e-2,
        )
