"""Metrics correctness: the histogram AUC must match an exact pairwise
AUC computation (the project is judged on AUC parity — BASELINE.md — so a
binning bug that shifts AUC by a point must not survive the suite)."""

import jax.numpy as jnp
import numpy as np

from fast_tffm_tpu.train import metrics as metrics_lib


def _exact_pairwise_auc(scores, labels, weights):
    """Brute-force weighted AUC: P(score_pos > score_neg) + 0.5 ties,
    weighted by w_pos * w_neg."""
    p, wp = scores[labels == 1], weights[labels == 1]
    n, wn = scores[labels == 0], weights[labels == 0]
    cmp = (p[:, None] > n[None, :]).astype(np.float64)
    cmp += 0.5 * (p[:, None] == n[None, :])
    return float(
        (wp[:, None] * wn[None, :] * cmp).sum() / (wp.sum() * wn.sum())
    )


def _stream_auc(scores, labels, weights, chunk=1000):
    st = metrics_lib.auc_init()
    for i in range(0, len(scores), chunk):
        st = metrics_lib.auc_update(
            st,
            jnp.asarray(scores[i:i + chunk], jnp.float32),
            jnp.asarray(labels[i:i + chunk], jnp.float32),
            jnp.asarray(weights[i:i + chunk], jnp.float32),
        )
    return float(metrics_lib.auc_finalize(st))


def test_auc_matches_exact_pairwise(rng):
    for trial in range(3):
        b = 4000
        scores = rng.normal(0, 1.5, b)
        # Labels correlated with scores so AUC is far from 0.5.
        prob = 1.0 / (1.0 + np.exp(-0.8 * scores))
        labels = (rng.uniform(size=b) < prob).astype(np.float32)
        weights = rng.uniform(0.2, 2.0, b).astype(np.float32)
        got = _stream_auc(scores, labels, weights)
        want = _exact_pairwise_auc(scores, labels, weights)
        # 1024 sigmoid bins: discretization error only.
        assert abs(got - want) < 2e-3, (trial, got, want)


def test_auc_weight_zero_rows_ignored(rng):
    b = 1000
    scores = rng.normal(0, 1, b)
    labels = (rng.uniform(size=b) < 0.4).astype(np.float32)
    weights = np.ones(b, np.float32)
    base = _stream_auc(scores, labels, weights)
    # Append adversarial rows with weight 0 (padded examples).
    scores2 = np.concatenate([scores, np.full(200, 5.0)])
    labels2 = np.concatenate([labels, np.zeros(200, np.float32)])
    weights2 = np.concatenate([weights, np.zeros(200, np.float32)])
    np.testing.assert_allclose(
        _stream_auc(scores2, labels2, weights2), base, atol=1e-6
    )


def test_auc_degenerate_single_class():
    """All-positive / all-negative streams must not produce NaN."""
    scores = np.linspace(-1, 1, 100)
    ones = np.ones(100, np.float32)
    for labels in (np.ones(100, np.float32), np.zeros(100, np.float32)):
        got = _stream_auc(scores, labels, ones)
        assert np.isfinite(got) and 0.0 <= got <= 1.0


def test_auc_perfect_and_antiperfect_separation():
    scores = np.concatenate([np.full(50, -4.0), np.full(50, 4.0)])
    labels = np.concatenate([np.zeros(50), np.ones(50)]).astype(np.float32)
    ones = np.ones(100, np.float32)
    assert _stream_auc(scores, labels, ones) > 0.999
    assert _stream_auc(scores, 1.0 - labels, ones) < 0.001
