"""ops.interaction.ffm_interaction (closed-form VJP) vs the autodiff oracle.

The op's backward implements the shardmap inversion's closed form
``dv_i^q = g x_i (S[q, f_i] - [q = f_i] v_i^{f_i} x_i)``; it must match
jax.grad through models.fm.ffm_scores_from_rows to float tolerance, and
the forward must match exactly (same einsum sequence).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import interaction

B, F, P, K = 32, 8, 3, 4
D = 1 + P * K


def _data(seed):
    rng = np.random.default_rng(seed)
    rows = jnp.asarray(rng.uniform(-0.5, 0.5, (B, F, D)), jnp.float32)
    vals = jnp.asarray(rng.uniform(0.1, 1.0, (B, F)), jnp.float32)
    vals = vals.at[:, -2:].set(0.0)  # padded feature slots
    fields = jnp.asarray(rng.integers(0, P, (B, F)), jnp.int32)
    g = jnp.asarray(rng.uniform(-1, 1, (B,)), jnp.float32)
    return rows, vals, fields, g


def test_ffm_forward_matches_oracle():
    rows, vals, fields, _ = _data(0)
    got = interaction.ffm_interaction(rows, vals, fields, K, P)
    want = fm.ffm_scores_from_rows(
        jnp.zeros(()), rows, vals, fields, K, P
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_ffm_closed_form_grad_matches_autodiff():
    rows, vals, fields, g = _data(1)

    def via_op(r):
        return jnp.sum(
            g * interaction.ffm_interaction(r, vals, fields, K, P)
        )

    def via_oracle(r):
        return jnp.sum(
            g * fm.ffm_scores_from_rows(jnp.zeros(()), r, vals, fields, K, P)
        )

    d_op = jax.grad(via_op)(rows)
    d_or = jax.grad(via_oracle)(rows)
    np.testing.assert_allclose(
        np.asarray(d_op), np.asarray(d_or), rtol=1e-5, atol=1e-6
    )


def test_ffm_grad_zero_on_padded_slots():
    """Padded features (val == 0) must receive zero row gradients."""
    rows, vals, fields, g = _data(2)
    d = jax.grad(
        lambda r: jnp.sum(
            g * interaction.ffm_interaction(r, vals, fields, K, P)
        )
    )(rows)
    np.testing.assert_array_equal(np.asarray(d[:, -2:, :]), 0.0)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_ffm_op_bf16_mode_runs_and_tracks_f32(dtype):
    """bf16 compute rounds operands but accumulates f32; scores must stay
    within bf16 rounding of the f32 scores, and the cotangent dtype must
    match the primal's."""
    rows, vals, fields, g = _data(3)
    cd = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    rows_c = rows.astype(cd)
    got = interaction.ffm_interaction(rows_c, vals, fields, K, P, cd)
    assert got.dtype == jnp.float32
    ref = interaction.ffm_interaction(rows, vals, fields, K, P)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-2, atol=2e-2
    )
    d = jax.grad(
        lambda r: jnp.sum(
            g * interaction.ffm_interaction(r, vals, fields, K, P, cd)
        )
    )(rows_c)
    assert d.dtype == cd


def test_ffm_op_matches_oracle_same_compute_dtype():
    """At the SAME compute_dtype the op must track the oracle to
    accumulation order — including which products see the bf16-rounded
    operands (the self-term/cross diagonal cancellation is where an
    operand-rounding mismatch shows up).  Off-TPU both gates fall back
    to f32 via platform.ffm_compute_dtype, so this pins the shared
    operand plumbing; the bf16-vs-bf16 comparison reruns on chip via
    tpu_validate's FFM combos."""
    rows, vals, fields, g = _data(4)
    cd = jnp.bfloat16
    rows_c = rows.astype(cd)
    got = interaction.ffm_interaction(rows_c, vals, fields, K, P, cd)
    want = fm.ffm_scores_from_rows(
        jnp.zeros(()), rows_c, vals, fields, K, P, cd
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    d_op = jax.grad(
        lambda r: jnp.sum(
            g * interaction.ffm_interaction(r, vals, fields, K, P, cd)
        )
    )(rows_c)
    d_or = jax.grad(
        lambda r: jnp.sum(g * fm.ffm_scores_from_rows(
            jnp.zeros(()), r, vals, fields, K, P, cd
        ))
    )(rows_c)
    assert d_op.dtype == d_or.dtype == cd
    np.testing.assert_allclose(
        np.asarray(d_op, dtype=np.float32), np.asarray(d_or, np.float32),
        rtol=1e-4, atol=1e-4,
    )
