"""Optimizer tests: Adagrad config mapping and FTRL-proximal behavior."""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train import optimizers


def test_make_optimizer_variants():
    for name in ("adagrad", "ftrl", "sgd", "adam"):
        cfg = FmConfig(optimizer=name)
        opt = optimizers.make_optimizer(cfg)
        params = {"w": jnp.ones((3,))}
        state = opt.init(params)
        grads = {"w": jnp.ones((3,))}
        updates, _ = opt.update(grads, state, params)
        assert updates["w"].shape == (3,)


def test_ftrl_reference_implementation():
    """Step-by-step FTRL-proximal recursion vs a numpy re-derivation."""
    lr, l1, l2, beta, init_acc = 0.1, 0.01, 0.02, 1.0, 0.0
    opt = optimizers.ftrl(lr, l1, l2, beta, initial_accumulator=init_acc)
    w = jnp.array([0.0, 0.0, 0.0])
    state = opt.init(w)
    rng = np.random.default_rng(1)

    z = np.zeros(3)
    n = np.zeros(3)
    w_np = np.zeros(3)
    for _ in range(5):
        g = rng.normal(size=3).astype(np.float32)
        updates, state = opt.update(jnp.asarray(g), state, w)
        w = optax.apply_updates(w, updates)
        # numpy reference
        n_new = n + g * g
        sigma = (np.sqrt(n_new) - np.sqrt(n)) / lr
        z = z + g - sigma * w_np
        n = n_new
        w_np = np.where(
            np.abs(z) <= l1,
            0.0,
            -(z - np.sign(z) * l1) / ((beta + np.sqrt(n)) / lr + l2),
        )
        np.testing.assert_allclose(np.asarray(w), w_np, rtol=1e-5, atol=1e-6)


def test_ftrl_zero_grad_preserves_warm_started_params():
    """Regression: z must be initialized from the incoming params, so a
    warm start into FTRL (Adagrad->FTRL sweep) doesn't discard the model."""
    for l1, l2 in [(0.0, 0.0), (0.01, 0.02)]:
        opt = optimizers.ftrl(0.1, l1=l1, l2=l2, initial_accumulator=0.1)
        w = jnp.array([0.7, -1.3, 0.0, 0.05])
        state = opt.init(w)
        updates, _ = opt.update(jnp.zeros_like(w), state, w)
        w2 = optax.apply_updates(w, updates)
        np.testing.assert_allclose(np.asarray(w2), np.asarray(w),
                                   rtol=1e-5, atol=1e-6)


def test_ftrl_l1_produces_sparsity():
    # Huge l1: small gradients never push |z| past l1, weights stay 0.
    opt = optimizers.ftrl(0.1, l1=10.0)
    w = jnp.array([0.0, 0.0])
    state = opt.init(w)
    for _ in range(5):
        updates, state = opt.update(jnp.array([0.01, -0.01]), state, w)
        w = optax.apply_updates(w, updates)
    np.testing.assert_allclose(np.asarray(w), [0.0, 0.0], atol=1e-7)


def test_adagrad_initial_accumulator_used():
    cfg = FmConfig(optimizer="adagrad", adagrad_initial_accumulator=123.0,
                   learning_rate=1.0)
    opt = optimizers.make_optimizer(cfg)
    w = jnp.array([0.0])
    state = opt.init(w)
    updates, _ = opt.update(jnp.array([1.0]), state, w)
    # Adagrad: u = -lr * g / sqrt(acc + g^2); acc starts at 123.
    np.testing.assert_allclose(
        np.asarray(updates), -1.0 / np.sqrt(124.0), rtol=1e-5
    )


def test_optimizer_state_tree_matches_params():
    """State must mirror the param tree so table sharding propagates."""
    from fast_tffm_tpu.models import fm

    cfg = FmConfig(vocabulary_size=64, factor_num=4, optimizer="ftrl")
    params = fm.init_params(jax.random.PRNGKey(0), cfg)
    opt = optimizers.make_optimizer(cfg)
    state = opt.init(params)
    assert state.z.table.shape == params.table.shape
    assert state.n.table.shape == params.table.shape
