"""End-to-end: train on planted-structure data to a logloss threshold,
checkpoint/warm-start, predict (SURVEY.md §4 "do better" items 3-4)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig, load_config
from fast_tffm_tpu.train.loop import Trainer, predict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sample_data(tmp_path_factory):
    out = tmp_path_factory.mktemp("sample_data")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "gen_sample_data.py"),
         "--out", str(out), "--train", "4000", "--valid", "500",
         "--vocab", "300", "--n_feat", "8"],
        check=True,
    )
    return out


def _cfg(sample_data, tmp_path, **kw):
    defaults = dict(
        vocabulary_size=300,
        factor_num=4,
        model_file=str(tmp_path / "model"),
        train_files=[str(sample_data / "train.libsvm")],
        validation_files=[str(sample_data / "valid.libsvm")],
        predict_files=[str(sample_data / "valid.libsvm")],
        score_path=str(tmp_path / "scores.txt"),
        epoch_num=10,
        batch_size=256,
        max_features=8,
        learning_rate=1.0,
        adagrad_initial_accumulator=0.01,
        init_value_range=0.05,
        log_steps=0,
        thread_num=2,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.mark.slow
def test_train_reduces_logloss_and_checkpoints(sample_data, tmp_path):
    cfg = _cfg(sample_data, tmp_path)
    trainer = Trainer(cfg)
    result = trainer.train()
    # Planted FM structure (Bayes logloss ~0.41): must decisively beat the
    # trivial 0.693 and reach decent AUC.
    assert result["validation"]["logloss"] < 0.55
    assert result["validation"]["auc"] > 0.72
    assert os.path.isdir(os.path.join(cfg.model_file, "params"))

    # Warm start must resume from the checkpoint, not from scratch.
    trainer2 = Trainer(cfg)
    assert trainer2._restored_step == result["train"]["steps"]
    ev = trainer2.evaluate(cfg.validation_files)
    np.testing.assert_allclose(
        ev["logloss"], result["validation"]["logloss"], rtol=1e-5
    )


@pytest.mark.slow
def test_flat_interaction_trains_multi_device(sample_data, tmp_path):
    """interaction=flat is plain XLA and must train under the 8-virtual-
    device GSPMD mesh (the Pallas path needs shard_map there); same
    convergence bar as the default path."""
    cfg = _cfg(sample_data, tmp_path, interaction="flat")
    result = Trainer(cfg).train()
    assert result["validation"]["logloss"] < 0.55
    assert result["validation"]["auc"] > 0.72


@pytest.mark.slow
def test_sorted_data_converges_with_line_shuffle(sample_data, tmp_path):
    """Convergence on a LABEL-SORTED file (the norm for CTR logs): fast
    ingest's line-level shuffle must recover most of the loss an
    unshuffled pass gives up — group-granularity shuffling (batches of
    contiguous lines reordered) cannot mix labels within batches and
    trained visibly worse on sorted data (VERDICT r3 missing #2)."""
    src = sample_data / "train.libsvm"
    lines = open(src).read().splitlines()
    lines.sort(key=lambda ln: ln.split(" ", 1)[0])  # all 0s then all 1s
    sorted_path = tmp_path / "sorted.libsvm"
    sorted_path.write_text("\n".join(lines) + "\n")

    results = {}
    for shuffle in (True, False):
        cfg = _cfg(
            sample_data, tmp_path,
            train_files=[str(sorted_path)],
            model_file=str(tmp_path / f"model_{shuffle}"),
            epoch_num=3, shuffle_buffer=2000,
        )
        assert cfg.fast_ingest
        trainer = Trainer(cfg)
        if not shuffle:
            # Force the unshuffled stream through the same trainer path.
            import unittest.mock as mock

            from fast_tffm_tpu.data.pipeline import BatchPipeline as BP

            orig_init = BP.__init__

            def no_shuffle_init(self, files, cfg_, **kw):
                kw["shuffle"] = False
                orig_init(self, files, cfg_, **kw)

            with mock.patch.object(BP, "__init__", no_shuffle_init):
                results[shuffle] = trainer.train()
        else:
            results[shuffle] = trainer.train()
    # Shuffled training on sorted data must clearly beat unshuffled.
    assert (
        results[True]["validation"]["logloss"]
        < results[False]["validation"]["logloss"] - 0.01
    )
    assert results[True]["validation"]["auc"] > 0.72


@pytest.mark.slow
def test_predict_writes_scores(sample_data, tmp_path):
    cfg = _cfg(sample_data, tmp_path, epoch_num=1)
    Trainer(cfg).train()
    n = predict(cfg)
    assert n == 500
    scores = np.loadtxt(cfg.score_path)
    assert scores.shape == (500,)
    assert np.all((scores >= 0) & (scores <= 1))  # sigmoid probabilities
    # Predictions must correlate with labels.
    labels = np.array(
        [float(line.split()[0])
         for line in open(sample_data / "valid.libsvm")]
    )
    assert np.mean(scores[labels == 1]) > np.mean(scores[labels == 0])


@pytest.mark.slow
def test_ftrl_optimizer_trains(sample_data, tmp_path):
    cfg = _cfg(sample_data, tmp_path, optimizer="ftrl", epoch_num=5,
               ftrl_l1=0.001, ftrl_l2=0.001)
    result = Trainer(cfg).train()
    assert result["validation"]["logloss"] < 0.65


@pytest.mark.slow
def test_warm_start_across_optimizers(sample_data, tmp_path):
    """Adagrad-vs-FTRL sweep warm start (BASELINE config 3)."""
    cfg = _cfg(sample_data, tmp_path, epoch_num=1)
    Trainer(cfg).train()
    cfg2 = _cfg(sample_data, tmp_path, optimizer="ftrl", epoch_num=1)
    trainer2 = Trainer(cfg2)  # must not crash on incompatible opt state
    assert trainer2._restored_step > 0


@pytest.mark.slow
def test_cli_train_and_predict(sample_data, tmp_path):
    cfg_path = tmp_path / "sample.cfg"
    cfg_path.write_text(f"""
[General]
vocabulary_size = 300
factor_num = 4
model_file = {tmp_path}/model_cli

[Train]
train_files = {sample_data}/train.libsvm
validation_files = {sample_data}/valid.libsvm
epoch_num = 1
batch_size = 256
learning_rate = 0.1
log_steps = 0

[Predict]
predict_files = {sample_data}/valid.libsvm
score_path = {tmp_path}/scores_cli.txt

[Tpu]
max_features = 8
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "train",
         str(cfg_path)],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "validation logloss" in r.stdout
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "predict",
         str(cfg_path)],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert os.path.exists(tmp_path / "scores_cli.txt")


@pytest.mark.slow
def test_kitchen_sink_ffm_bf16_weights_resume_predict(tmp_path, rng):
    """Every subsystem at once: field-aware FM + bf16 compute + weight
    files (line ingest path) + periodic validation/save + metrics JSONL +
    mid-epoch resume + predict.  Interaction bugs between features hide
    from single-feature tests."""
    import json

    n, p_num = 512, 3
    train = tmp_path / "train.libsvm"
    with open(train, "w") as f:
        for i in range(n):
            toks = " ".join(
                f"{rng.integers(0, p_num)}:{rng.integers(0, 200)}:"
                f"{rng.uniform(0.1, 1):.4f}"
                for _ in range(6)
            )
            f.write(f"{i % 2} {toks}\n")
    wf = tmp_path / "w.txt"
    wf.write_text("1.5\n" * n)

    cfg = FmConfig(
        vocabulary_size=256, factor_num=4, field_num=p_num, max_features=8,
        batch_size=64, epoch_num=2, learning_rate=0.1,
        compute_dtype="bfloat16",
        train_files=[str(train)], weight_files=[str(wf)],
        validation_files=[str(train)], validation_steps=5,
        predict_files=[str(train)], score_path=str(tmp_path / "scores.txt"),
        model_file=str(tmp_path / "model"),
        metrics_file=str(tmp_path / "metrics.jsonl"),
        save_steps=6, log_steps=4, thread_num=2, seed=1,
    )
    r1 = Trainer(cfg).train()
    assert r1["train"]["steps"] == 16  # 8 batches x 2 epochs
    assert r1["train"]["examples"] == 1024.0  # unweighted count
    assert abs(r1["train"]["weight_sum"] - 1024 * 1.5) < 1e-3
    assert np.isfinite(r1["validation"]["logloss"])
    recs = [json.loads(line) for line in open(cfg.metrics_file)]
    assert any("validation_loss" in r for r in recs)

    # Simulate an interruption at epoch 1, batch 3; resume finishes the
    # remaining 5 batches of that epoch (+ nothing else).
    from conftest import set_data_state

    set_data_state(cfg.model_file, epoch=1, batches_done=3)
    r2 = Trainer(cfg).train()
    assert r2["train"]["steps"] == 5

    n_scores = predict(cfg)
    assert n_scores == n
    scores = [float(s) for s in open(cfg.score_path)]
    assert all(0.0 <= s <= 1.0 for s in scores)


def test_metrics_file_and_profiler(tmp_path, rng):
    """Observability: metrics JSONL stream + jax.profiler trace dir."""
    import json

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.train.loop import Trainer

    data = tmp_path / "train.libsvm"
    with open(data, "w") as f:
        for i in range(256):
            f.write(f"{i % 2} {rng.integers(0, 64)}:1 {rng.integers(0, 64)}:0.5\n")
    cfg = FmConfig(
        vocabulary_size=64, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(data)], epoch_num=2, log_steps=4,
        model_file=str(tmp_path / "model"),
        metrics_file=str(tmp_path / "metrics.jsonl"),
        profile_dir=str(tmp_path / "trace"),
        profile_start_step=2, profile_steps=2,
    )
    Trainer(cfg).train()
    lines = open(tmp_path / "metrics.jsonl").read().splitlines()
    assert lines, "metrics stream empty"
    recs = [json.loads(line) for line in lines]
    # Self-describing stream: header first, exact final report last.
    assert recs[0]["record"] == "run_header"
    assert recs[-1]["record"] == "final"
    trains = [r for r in recs if r["record"] == "train"]
    assert trains, "no train interval records"
    rec = trains[-1]
    assert {"step", "examples", "loss", "auc", "examples_per_sec",
            "elapsed"} <= set(rec)
    assert rec["examples"] == 512
    assert any(os.scandir(tmp_path / "trace")), "no profiler trace written"
