"""Scale-out serving router (ISSUE 12 tentpole): P2C dispatch, health
eviction/readmission, deadline-budget load shedding, canary promotion.

The router logic tests run against FAKE replicas — tiny stdlib HTTP
servers speaking exactly the replica surface the router uses
(``/score``, ``/score_bin``, ``/healthz``, ``/reload``/``/promote``/
``/rollback``) — so dispatch/eviction/canary semantics are pinned
without spawning jax subprocesses.  The real-scorer integration (a
router over a live serve stack, binary==text bitwise parity) lives at
the bottom and in tests/test_serving.py.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.status import ObsHTTPServer, QuietHandler
from fast_tffm_tpu import obs
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.serve.router import Replica, ServeRouter
from fast_tffm_tpu.train import checkpoint


class FakeReplica:
    """A stdlib stand-in for one replica serve process.

    Scores every example ``self.score`` (so which table "version" a
    response came from is readable off the wire), counts requests and
    distinct connections, and implements the admin swap surface with
    the same keep-prev/rollback semantics as the real scorer.
    """

    def __init__(self, score=0.5, delay_s=0.0):
        self.score = score
        self.delay_s = delay_s
        self.healthy = True
        self.step = 0
        self.pending = None      # (score, step) the next /reload installs
        self.prev = None         # what /rollback restores
        self.reload_calls = 0
        self.promote_calls = 0
        self.rollback_calls = 0
        self.requests = 0
        self.connections = 0
        self.reload_status = 200
        self.rollback_status = 200
        fake = self

        class Handler(QuietHandler):
            def setup(self) -> None:
                fake.connections += 1
                super().setup()

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz" and fake.healthy:
                    self._send(200, b"ok\n", "text/plain")
                else:
                    self._send(503, b"unhealthy\n", "text/plain")

            def do_POST(self) -> None:  # noqa: N802
                body = self._read_body(wire.MAX_BODY_BYTES)
                if body is None:
                    return
                fake.requests += 1
                if fake.delay_s:
                    time.sleep(fake.delay_s)
                path, _, query = self.path.partition("?")
                self.path = path
                if self.path == "/score":
                    n = len([
                        l for l in body.decode().splitlines()
                        if l.strip()
                    ])
                    out = "".join(f"{fake.score:.6f}\n" for _ in
                                  range(n))
                    self._send(200, out.encode(), "text/plain")
                elif self.path == "/score_bin":
                    _ids, _vals, _f, n, _tr = wire.decode_bin_request(
                        body, FakeReplica._CFG
                    )
                    self._send(
                        200,
                        wire.encode_bin_response(
                            np.full((n,), fake.score, np.float32)
                        ),
                        "application/octet-stream",
                    )
                elif self.path == "/reload":
                    fake.reload_calls += 1
                    if fake.reload_status != 200:
                        self._send(
                            fake.reload_status, b"refused\n",
                            "text/plain",
                        )
                        return
                    if fake.pending is not None:
                        # Same contract as the real scorer: only a
                        # keep_prev reload opens (or anchors) the
                        # rollback window.
                        if "keep_prev=1" in query:
                            if fake.prev is None:
                                fake.prev = (fake.score, fake.step)
                        else:
                            fake.prev = None
                        fake.score, fake.step = fake.pending
                    self._send(
                        200,
                        (json.dumps({"step": fake.step}) + "\n"
                         ).encode(),
                        "application/json",
                    )
                elif self.path == "/promote":
                    fake.promote_calls += 1
                    fake.prev = None
                    self._send(
                        200,
                        (json.dumps({"step": fake.step}) + "\n"
                         ).encode(),
                        "application/json",
                    )
                elif self.path == "/rollback":
                    fake.rollback_calls += 1
                    if fake.rollback_status != 200:
                        self._send(
                            fake.rollback_status, b"broken\n",
                            "text/plain",
                        )
                        return
                    if fake.prev is None:
                        self._send(409, b"nothing to roll back\n",
                                   "text/plain")
                        return
                    fake.score, fake.step = fake.prev
                    fake.prev = None
                    self._send(
                        200,
                        (json.dumps({"step": fake.step}) + "\n"
                         ).encode(),
                        "application/json",
                    )
                else:
                    self._send(404, b"not found\n", "text/plain")

        self._httpd = ObsHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
        )
        self._thread.start()

    _CFG = FmConfig(vocabulary_size=256, factor_num=4, max_features=4)

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()


def _mk_router(fakes, tmp_path, health_secs=10.0, **cfg_kw):
    """A router over fakes.  health_secs defaults high so dispatch
    tests control health state themselves."""
    defaults = dict(
        vocabulary_size=256, factor_num=4, max_features=4,
        model_file=str(tmp_path / "model"),
        serve_replicas=max(2, len(fakes)),
    )
    defaults.update(cfg_kw)
    cfg = FmConfig(**defaults)
    replicas = [
        Replica(i, "127.0.0.1", f.port) for i, f in enumerate(fakes)
    ]
    tel = obs.Telemetry()
    router = ServeRouter(
        0, replicas, cfg, telemetry=tel, health_secs=health_secs,
    )
    return router, replicas, tel


def _post(port, path, body, timeout=30):
    """(status, body bytes); HTTPError codes return instead of raising."""
    try:
        resp = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        ), timeout=timeout)
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestDispatch:
    def test_p2c_picks_the_less_loaded_of_two(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(fakes, tmp_path)
        try:
            # Load replica 0 far beyond what 10 admissions can close:
            # every admission must pick replica 1 (P2C with two
            # replicas compares both).
            reps[0].inflight = 20
            picks = []
            for _ in range(10):
                rep, why = router._admit()
                assert why is None
                picks.append(rep.index)
            assert picks == [1] * 10
            # Flip the imbalance: admission follows the load.
            with router._lock:
                reps[1].inflight = 50
            rep, _ = router._admit()
            assert rep.index == 0
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_routes_score_and_counts(self, tmp_path):
        fakes = [FakeReplica(score=0.25), FakeReplica(score=0.25)]
        router, reps, tel = _mk_router(fakes, tmp_path)
        try:
            status, body = _post(router.port, "/score", b"1 3:1\n")
            assert status == 200
            assert body.decode().strip() == "0.250000"
            blk = router._build()["serve"]
            assert blk["requests"] == 1
            assert blk["replicas_healthy"] == 2
            assert sum(p["routed"] for p in blk["per_replica"]) == 1
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_binary_transport_proxies(self, tmp_path):
        fakes = [FakeReplica(score=0.75), FakeReplica(score=0.75)]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            ids = np.zeros((3, 4), np.int32)
            vals = np.ones((3, 4), np.float32)
            status, raw = _post(
                router.port, "/score_bin",
                wire.encode_bin_request(ids, vals),
            )
            assert status == 200
            np.testing.assert_array_equal(
                wire.decode_bin_response(raw),
                np.full((3,), 0.75, np.float32),
            )
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_transport_knob_gates_routes(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(
            fakes, tmp_path, serve_transport="bin"
        )
        try:
            status, body = _post(router.port, "/score", b"1 3:1\n")
            assert status == 404
            assert b"disabled" in body
            ids = np.zeros((1, 4), np.int32)
            status, _ = _post(
                router.port, "/score_bin",
                wire.encode_bin_request(ids, np.ones((1, 4),
                                                     np.float32)),
            )
            assert status == 200
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_keepalive_through_the_router(self, tmp_path):
        """One client connection carries many requests (HTTP/1.1
        keep-alive on the front), and the router reuses its replica
        connections (far fewer backend connections than requests)."""
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", router.port, timeout=10
            )
            for _ in range(10):
                conn.request("POST", "/score", body=b"1 3:1\n",
                             headers={"Content-Type": "text/plain"})
                resp = conn.getresponse()
                assert resp.status == 200
                resp.read()
                assert not resp.will_close  # front keep-alive held
            conn.close()
            backend_conns = sum(f.connections for f in fakes)
            backend_requests = sum(f.requests for f in fakes)
            assert backend_requests == 10
            # Health probes are off (health_secs high): every backend
            # connection here is a proxy connection, and pooling must
            # keep them well below one per request.
            assert backend_conns <= 4
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestHealth:
    def test_eviction_and_readmission(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, tel = _mk_router(
            fakes, tmp_path, health_secs=0.05
        )
        try:
            fakes[0].healthy = False
            deadline = time.time() + 10
            while reps[0].healthy and time.time() < deadline:
                time.sleep(0.02)
            assert not reps[0].healthy, "replica never evicted"
            # Traffic keeps flowing on the survivor.
            for _ in range(5):
                status, _ = _post(router.port, "/score", b"1 3:1\n")
                assert status == 200
            assert fakes[1].requests >= 5
            assert fakes[0].requests == 0
            # Recovery: the health loop readmits it.
            fakes[0].healthy = True
            deadline = time.time() + 10
            while not reps[0].healthy and time.time() < deadline:
                time.sleep(0.02)
            assert reps[0].healthy, "replica never readmitted"
            counters = tel.snapshot()["counters"]
            assert counters["serve.evictions"] == 1
            assert counters["serve.readmissions"] == 1
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_dead_replica_request_retries_transparently(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, tel = _mk_router(fakes, tmp_path)
        try:
            # Kill replica 0's server outright; the router only learns
            # at proxy time (health probes are off at this cadence).
            fakes[0].close()
            ok = 0
            for _ in range(10):
                status, _ = _post(router.port, "/score", b"1 3:1\n")
                ok += 1 if status == 200 else 0
            assert ok == 10, "requests were lost on the dead replica"
            counters = tel.snapshot()["counters"]
            assert counters["serve.evictions"] == 1
            assert counters.get("serve.retries", 0) >= 1
            assert not reps[0].healthy
        finally:
            router.close()
            fakes[1].close()

    def test_no_healthy_replica_is_503(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(fakes, tmp_path)
        try:
            with router._lock:
                for r in reps:
                    r.healthy = False
            status, body = _post(router.port, "/score", b"1 3:1\n")
            assert status == 503
            assert b"no healthy replica" in body
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestShedding:
    def test_admit_sheds_past_the_deadline_budget(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=10.0
        )
        try:
            # 6 in flight across 2 healthy replicas (>= the 2-per-
            # replica floor) completing at ~100/s: projected delay
            # 7/100 = 70 ms > 10 ms -> shed.
            now = time.perf_counter()
            with router._lock:
                reps[0].inflight = 3
                reps[1].inflight = 3
                for i in range(100):
                    router._completions.append(now - i * 0.01)
            rep, why = router._admit()
            assert rep is None and why == "shed"
            # Below the concurrency floor admission always passes,
            # whatever the rate says.
            with router._lock:
                reps[0].inflight = 1
                reps[1].inflight = 1
            rep, why = router._admit()
            assert rep is not None and why is None
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_shed_is_fast_429_with_retry_after(self, tmp_path):
        fakes = [FakeReplica(delay_s=0.3), FakeReplica(delay_s=0.3)]
        router, _, tel = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=5.0
        )
        try:
            results = []
            lock = threading.Lock()

            def client():
                end = time.perf_counter() + 2.0
                while time.perf_counter() < end:
                    try:
                        resp = urllib.request.urlopen(
                            urllib.request.Request(
                                f"http://127.0.0.1:{router.port}"
                                "/score", data=b"1 3:1\n",
                                method="POST",
                            ), timeout=10,
                        )
                        resp.read()
                        with lock:
                            results.append((resp.status, None))
                    except urllib.error.HTTPError as e:
                        e.read()
                        with lock:
                            results.append(
                                (e.code, e.headers.get("Retry-After"))
                            )
                        time.sleep(0.02)

            threads = [
                threading.Thread(target=client) for _ in range(10)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            codes = [c for c, _ in results]
            assert codes.count(200) >= 1
            assert codes.count(429) >= 1, (
                "overload never shed — admission control is inert"
            )
            assert all(c in (200, 429) for c in codes)
            retry_after = next(h for c, h in results if c == 429)
            assert retry_after == "1"
            blk = router._build()["serve"]
            assert blk["shed"] == codes.count(429)
            assert blk["shed_frac"] > 0
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_zero_deadline_disables_shedding(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=0.0
        )
        try:
            now = time.perf_counter()
            with router._lock:
                reps[0].inflight = 50
                reps[1].inflight = 50
                for i in range(100):
                    router._completions.append(now - i * 0.005)
            rep, why = router._admit()
            assert rep is not None and why is None
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestCanary:
    def _canary_router(self, fakes, tmp_path, **cfg_kw):
        model = tmp_path / "model"
        model.mkdir(exist_ok=True)
        defaults = dict(
            serve_canary=True, serve_replicas=2, serve_poll_secs=0.05,
            model_file=str(model),
        )
        defaults.update(cfg_kw)
        router, reps, tel = _mk_router(fakes, tmp_path, **defaults)
        return router, reps, tel, str(model)

    def _publish(self, model, step):
        checkpoint._publish_manifest(model, step, "dense")

    def _traffic(self, port, n=6):
        for _ in range(n):
            status, _ = _post(port, "/score", b"1 3:1\n1 5:1\n")
            assert status == 200

    def test_promotion_rolls_the_fleet(self, tmp_path):
        # The new checkpoint scores the SAME distribution: the shadow
        # compare passes and every replica reloads + promotes.
        fakes = [FakeReplica(score=0.5), FakeReplica(score=0.5)]
        for f in fakes:
            f.pending = (0.5000001, 7)  # new step, same distribution
        router, reps, tel, model = self._canary_router(fakes, tmp_path)
        try:
            self._traffic(router.port)
            self._publish(model, 7)
            deadline = time.time() + 20
            while router.step != 7 and time.time() < deadline:
                time.sleep(0.05)
            assert router.step == 7, "promotion never completed"
            assert all(f.reload_calls == 1 for f in fakes)
            assert all(f.promote_calls == 1 for f in fakes)
            assert all(f.rollback_calls == 0 for f in fakes)
            counters = tel.snapshot()["counters"]
            assert counters["serve.canary_promotions"] == 1
            assert counters.get("serve.canary_rollbacks", 0) == 0
            # The compare artifacts are on disk for the operator.
            compare_dir = tmp_path / "model" / "canary_compare" / \
                "step_7"
            assert (compare_dir / "baseline.json").exists()
            assert (compare_dir / "canary.json").exists()
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_drifted_canary_rolls_back(self, tmp_path):
        # The canary's post-reload scores drift far from the baseline
        # replica's: report.py --compare flags, the canary rolls back,
        # the rest of the fleet never reloads, and the bad manifest is
        # baselined (no retry storm).
        fakes = [FakeReplica(score=0.5), FakeReplica(score=0.5)]
        fakes[0].pending = (0.9, 9)  # the canary would drift
        fakes[1].pending = (0.9, 9)
        router, reps, tel, model = self._canary_router(fakes, tmp_path)
        try:
            self._traffic(router.port)
            self._publish(model, 9)
            deadline = time.time() + 20
            while fakes[0].rollback_calls == 0 and \
                    time.time() < deadline:
                time.sleep(0.05)
            assert fakes[0].rollback_calls == 1, "canary never rolled back"
            assert fakes[0].score == 0.5  # restored
            assert fakes[1].reload_calls == 0  # fleet never touched
            assert router.step != 9
            counters = tel.snapshot()["counters"]
            assert counters["serve.canary_rollbacks"] == 1
            assert counters.get("serve.canary_promotions", 0) == 0
            # Baselined: three more polls must not retry the reload.
            calls = fakes[0].reload_calls
            time.sleep(0.3)
            assert fakes[0].reload_calls == calls
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_refused_reload_baselines_the_manifest(self, tmp_path):
        fakes = [FakeReplica(score=0.5), FakeReplica(score=0.5)]
        fakes[0].reload_status = 409  # unservable checkpoint
        router, reps, tel, model = self._canary_router(fakes, tmp_path)
        try:
            self._publish(model, 11)
            deadline = time.time() + 20
            while fakes[0].reload_calls == 0 and \
                    time.time() < deadline:
                time.sleep(0.05)
            assert fakes[0].reload_calls == 1
            time.sleep(0.3)  # several polls
            assert fakes[0].reload_calls == 1, (
                "refused checkpoint retried every poll (the unbounded "
                "reload loop the watcher baseline exists to prevent)"
            )
            assert router.step != 11
        finally:
            router.close()
            for f in fakes:
                f.close()


    def test_failed_rollback_quarantines_until_next_promotion(
        self, tmp_path
    ):
        """A rejected canary whose /rollback FAILS serves unvetted
        params: it must be quarantined — alive is not enough for the
        health loop to readmit it — until a later successful promotion
        reloads it onto a vetted checkpoint."""
        fakes = [FakeReplica(score=0.5) for _ in range(3)]
        fakes[0].pending = (0.9, 13)      # the canary drifts...
        fakes[0].rollback_status = 500    # ...and cannot roll back
        router, reps, tel, model = self._canary_router(
            fakes, tmp_path, serve_replicas=3, health_secs=0.05,
        )
        try:
            self._traffic(router.port)
            self._publish(model, 13)
            deadline = time.time() + 20
            while not reps[0].quarantined and time.time() < deadline:
                time.sleep(0.05)
            assert reps[0].quarantined, "failed rollback never quarantined"
            assert not reps[0].healthy
            # The replica still answers /healthz, but quarantine must
            # hold it out of routing across many health cycles.
            time.sleep(0.3)
            assert not reps[0].healthy, (
                "health loop readmitted a quarantined replica — it "
                "would be serving the rejected table"
            )
            # A good checkpoint promotes through the remaining pair
            # and recovers the quarantined replica onto it.
            fakes[0].rollback_status = 200
            for f in fakes:
                f.pending = (0.5000001, 14)
            self._traffic(router.port)
            self._publish(model, 14)
            deadline = time.time() + 20
            while (
                reps[0].quarantined or not reps[0].healthy
            ) and time.time() < deadline:
                time.sleep(0.05)
            assert not reps[0].quarantined
            assert reps[0].healthy, "recovered replica never readmitted"
            assert fakes[0].score == pytest.approx(0.5000001)
            assert router.step == 14
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestFleetLaunch:
    def test_replica_command_neutralizes_fleet_knobs(self, tmp_path):
        """ISSUE-12 review find: an INI-configured canary fleet used to
        crash every child at startup — the replica re-read
        serve_canary=true from the same cfg file while the launcher
        forced --replicas 0, tripping the child's own
        canary-requires-a-fleet validation.  The replica command must
        neutralize every fleet-level knob, and the CHILD's config
        parse (same cfg file + those flags) must succeed."""
        from fast_tffm_tpu import cli
        from fast_tffm_tpu.config import load_config
        from fast_tffm_tpu.serve.router import _replica_command

        cfg_path = tmp_path / "fleet.cfg"
        cfg_path.write_text(
            "[General]\nvocabulary_size = 64\nfactor_num = 4\n"
            f"model_file = {tmp_path}/model\n"
            "[Predict]\nserve_replicas = 2\nserve_canary = true\n"
            "serve_poll_secs = 1.0\n"
        )
        cfg = load_config(str(cfg_path))
        cmd = _replica_command(cfg, str(cfg_path), 0, {})
        assert "--no_serve_canary" in cmd
        assert cmd[cmd.index("--replicas") + 1] == "0"
        assert cmd[cmd.index("--serve_poll_secs") + 1] == "0"
        # Reproduce the child's own parse: argparse over the replica
        # flags, then main()'s override assembly, then load_config.
        args = cli.build_argparser().parse_args(cmd[3:])
        overrides = {
            key: getattr(args, key)
            for key in ("serve_replicas", "serve_port", "serve_host",
                        "serve_poll_secs")
            if getattr(args, key) is not None
        }
        assert args.no_serve_canary
        overrides["serve_canary"] = False
        child = load_config(str(cfg_path), overrides)  # must not raise
        assert child.serve_replicas == 0
        assert child.serve_canary is False
        assert child.serve_poll_secs == 0

    def test_router_process_is_jax_free(self):
        """The router front door must not pay a jax import (docstring +
        SERVING.md pin it): wire/manifest/router import through lazy
        package __init__s.  Probed in a clean subprocess — this test
        process imported jax long ago."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__
        )))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "import fast_tffm_tpu.serve.router\n"
             "import fast_tffm_tpu.serve.wire\n"
             "import fast_tffm_tpu.train.manifest\n"
             "heavy = [m for m in ('jax', 'orbax', 'optax')\n"
             "         if m in sys.modules]\n"
             "assert not heavy, f'router import pulled {heavy}'\n"],
            capture_output=True, timeout=120, env=env,
        )
        assert proc.returncode == 0, proc.stderr.decode()


class TestConfig:
    def test_canary_requires_a_fleet(self):
        with pytest.raises(ValueError, match="serve_replicas"):
            FmConfig(serve_canary=True, serve_replicas=1)
        with pytest.raises(ValueError, match="serve_poll_secs"):
            FmConfig(serve_canary=True, serve_replicas=2,
                     serve_poll_secs=0)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="serve_transport"):
            FmConfig(serve_transport="grpc")
        with pytest.raises(ValueError, match="serve_replicas"):
            FmConfig(serve_replicas=-1)
        with pytest.raises(ValueError, match="serve_shed_deadline_ms"):
            FmConfig(serve_shed_deadline_ms=-1)
