"""Scale-out serving router (ISSUE 12 tentpole): P2C dispatch, health
eviction/readmission, deadline-budget load shedding, canary promotion.

The router logic tests run against FAKE replicas — tiny stdlib HTTP
servers speaking exactly the replica surface the router uses
(``/score``, ``/score_bin``, ``/healthz``, ``/reload``/``/promote``/
``/rollback``) — so dispatch/eviction/canary semantics are pinned
without spawning jax subprocesses.  The real-scorer integration (a
router over a live serve stack, binary==text bitwise parity) lives at
the bottom and in tests/test_serving.py.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.status import ObsHTTPServer, QuietHandler
from fast_tffm_tpu.obs.trace import Tracer
from fast_tffm_tpu import obs
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.serve.router import Replica, ServeRouter
from fast_tffm_tpu.serve.slo import SloTracker
from fast_tffm_tpu.train import checkpoint


class FakeReplica:
    """A stdlib stand-in for one replica serve process.

    Scores every example ``self.score`` (so which table "version" a
    response came from is readable off the wire), counts requests and
    distinct connections, and implements the admin swap surface with
    the same keep-prev/rollback semantics as the real scorer.
    """

    def __init__(self, score=0.5, delay_s=0.0, status_block=None):
        self.score = score
        self.delay_s = delay_s
        self.healthy = True
        self.step = 0
        self.pending = None      # (score, step) the next /reload installs
        self.prev = None         # what /rollback restores
        self.reload_calls = 0
        self.promote_calls = 0
        self.rollback_calls = 0
        self.requests = 0
        self.connections = 0
        self.reload_status = 200
        self.rollback_status = 200
        # The serve block /status answers (None = 503, the historical
        # fake with no observability surface); the router's fleet
        # scraper consumes it.
        self.status_block = status_block
        self.last_body = None     # raw bytes of the last scoring POST
        self.last_headers = None  # its headers (dict)
        fake = self

        class Handler(QuietHandler):
            def setup(self) -> None:
                fake.connections += 1
                super().setup()

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz" and fake.healthy:
                    self._send(200, b"ok\n", "text/plain")
                elif (
                    self.path == "/status"
                    and fake.status_block is not None
                    and fake.healthy
                ):
                    doc = {"record": "status",
                           "serve": dict(fake.status_block)}
                    self._send(
                        200, (json.dumps(doc) + "\n").encode(),
                        "application/json",
                    )
                else:
                    self._send(503, b"unhealthy\n", "text/plain")

            def do_POST(self) -> None:  # noqa: N802
                body = self._read_body(wire.MAX_BODY_BYTES)
                if body is None:
                    return
                fake.requests += 1
                if self.path.partition("?")[0] in ("/score",
                                                   "/score_bin"):
                    fake.last_body = body
                    fake.last_headers = dict(self.headers)
                if fake.delay_s:
                    time.sleep(fake.delay_s)
                path, _, query = self.path.partition("?")
                self.path = path
                if self.path == "/score":
                    n = len([
                        l for l in body.decode().splitlines()
                        if l.strip()
                    ])
                    out = "".join(f"{fake.score:.6f}\n" for _ in
                                  range(n))
                    self._send(200, out.encode(), "text/plain")
                elif self.path == "/score_bin":
                    (_ids, _vals, _f, n, _tr,
                     _rid) = wire.decode_bin_request(
                        body, FakeReplica._CFG
                    )
                    self._send(
                        200,
                        wire.encode_bin_response(
                            np.full((n,), fake.score, np.float32)
                        ),
                        "application/octet-stream",
                    )
                elif self.path == "/reload":
                    fake.reload_calls += 1
                    if fake.reload_status != 200:
                        self._send(
                            fake.reload_status, b"refused\n",
                            "text/plain",
                        )
                        return
                    if fake.pending is not None:
                        # Same contract as the real scorer: only a
                        # keep_prev reload opens (or anchors) the
                        # rollback window.
                        if "keep_prev=1" in query:
                            if fake.prev is None:
                                fake.prev = (fake.score, fake.step)
                        else:
                            fake.prev = None
                        fake.score, fake.step = fake.pending
                    self._send(
                        200,
                        (json.dumps({"step": fake.step}) + "\n"
                         ).encode(),
                        "application/json",
                    )
                elif self.path == "/promote":
                    fake.promote_calls += 1
                    fake.prev = None
                    self._send(
                        200,
                        (json.dumps({"step": fake.step}) + "\n"
                         ).encode(),
                        "application/json",
                    )
                elif self.path == "/rollback":
                    fake.rollback_calls += 1
                    if fake.rollback_status != 200:
                        self._send(
                            fake.rollback_status, b"broken\n",
                            "text/plain",
                        )
                        return
                    if fake.prev is None:
                        self._send(409, b"nothing to roll back\n",
                                   "text/plain")
                        return
                    fake.score, fake.step = fake.prev
                    fake.prev = None
                    self._send(
                        200,
                        (json.dumps({"step": fake.step}) + "\n"
                         ).encode(),
                        "application/json",
                    )
                else:
                    self._send(404, b"not found\n", "text/plain")

        self._httpd = ObsHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
        )
        self._thread.start()

    _CFG = FmConfig(vocabulary_size=256, factor_num=4, max_features=4)

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()


def _mk_router(fakes, tmp_path, health_secs=10.0, tracer=None,
               sampler=None, respawner=None, **cfg_kw):
    """A router over fakes.  health_secs defaults high so dispatch
    tests control health state themselves."""
    defaults = dict(
        vocabulary_size=256, factor_num=4, max_features=4,
        model_file=str(tmp_path / "model"),
        serve_replicas=max(2, len(fakes)),
    )
    defaults.update(cfg_kw)
    cfg = FmConfig(**defaults)
    replicas = [
        Replica(i, "127.0.0.1", f.port) for i, f in enumerate(fakes)
    ]
    tel = obs.Telemetry()
    router = ServeRouter(
        0, replicas, cfg, telemetry=tel, health_secs=health_secs,
        tracer=tracer, sampler=sampler, respawner=respawner,
    )
    return router, replicas, tel


def _post(port, path, body, timeout=30):
    """(status, body bytes); HTTPError codes return instead of raising."""
    try:
        resp = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        ), timeout=timeout)
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestDispatch:
    def test_p2c_picks_the_less_loaded_of_two(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(fakes, tmp_path)
        try:
            # Load replica 0 far beyond what 10 admissions can close:
            # every admission must pick replica 1 (P2C with two
            # replicas compares both).
            reps[0].inflight = 20
            picks = []
            for _ in range(10):
                rep, why = router._admit()
                assert why is None
                picks.append(rep.index)
            assert picks == [1] * 10
            # Flip the imbalance: admission follows the load.
            with router._lock:
                reps[1].inflight = 50
            rep, _ = router._admit()
            assert rep.index == 0
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_routes_score_and_counts(self, tmp_path):
        fakes = [FakeReplica(score=0.25), FakeReplica(score=0.25)]
        router, reps, tel = _mk_router(fakes, tmp_path)
        try:
            status, body = _post(router.port, "/score", b"1 3:1\n")
            assert status == 200
            assert body.decode().strip() == "0.250000"
            blk = router._build()["serve"]
            assert blk["requests"] == 1
            assert blk["replicas_healthy"] == 2
            assert sum(p["routed"] for p in blk["per_replica"]) == 1
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_binary_transport_proxies(self, tmp_path):
        fakes = [FakeReplica(score=0.75), FakeReplica(score=0.75)]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            ids = np.zeros((3, 4), np.int32)
            vals = np.ones((3, 4), np.float32)
            status, raw = _post(
                router.port, "/score_bin",
                wire.encode_bin_request(ids, vals),
            )
            assert status == 200
            np.testing.assert_array_equal(
                wire.decode_bin_response(raw),
                np.full((3,), 0.75, np.float32),
            )
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_transport_knob_gates_routes(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(
            fakes, tmp_path, serve_transport="bin"
        )
        try:
            status, body = _post(router.port, "/score", b"1 3:1\n")
            assert status == 404
            assert b"disabled" in body
            ids = np.zeros((1, 4), np.int32)
            status, _ = _post(
                router.port, "/score_bin",
                wire.encode_bin_request(ids, np.ones((1, 4),
                                                     np.float32)),
            )
            assert status == 200
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_keepalive_through_the_router(self, tmp_path):
        """One client connection carries many requests (HTTP/1.1
        keep-alive on the front), and the router reuses its replica
        connections (far fewer backend connections than requests)."""
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", router.port, timeout=10
            )
            for _ in range(10):
                conn.request("POST", "/score", body=b"1 3:1\n",
                             headers={"Content-Type": "text/plain"})
                resp = conn.getresponse()
                assert resp.status == 200
                resp.read()
                assert not resp.will_close  # front keep-alive held
            conn.close()
            backend_conns = sum(f.connections for f in fakes)
            backend_requests = sum(f.requests for f in fakes)
            assert backend_requests == 10
            # Health probes are off (health_secs high): every backend
            # connection here is a proxy connection, and pooling must
            # keep them well below one per request.
            assert backend_conns <= 4
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestHealth:
    def test_eviction_and_readmission(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, tel = _mk_router(
            fakes, tmp_path, health_secs=0.05
        )
        try:
            fakes[0].healthy = False
            deadline = time.time() + 10
            while reps[0].healthy and time.time() < deadline:
                time.sleep(0.02)
            assert not reps[0].healthy, "replica never evicted"
            # Traffic keeps flowing on the survivor.
            for _ in range(5):
                status, _ = _post(router.port, "/score", b"1 3:1\n")
                assert status == 200
            assert fakes[1].requests >= 5
            assert fakes[0].requests == 0
            # Recovery: the health loop readmits it.
            fakes[0].healthy = True
            deadline = time.time() + 10
            while not reps[0].healthy and time.time() < deadline:
                time.sleep(0.02)
            assert reps[0].healthy, "replica never readmitted"
            counters = tel.snapshot()["counters"]
            assert counters["serve.evictions"] == 1
            assert counters["serve.readmissions"] == 1
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_dead_replica_request_retries_transparently(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, tel = _mk_router(fakes, tmp_path)
        try:
            # Kill replica 0's server outright; the router only learns
            # at proxy time (health probes are off at this cadence).
            fakes[0].close()
            ok = 0
            for _ in range(10):
                status, _ = _post(router.port, "/score", b"1 3:1\n")
                ok += 1 if status == 200 else 0
            assert ok == 10, "requests were lost on the dead replica"
            counters = tel.snapshot()["counters"]
            assert counters["serve.evictions"] == 1
            assert counters.get("serve.retries", 0) >= 1
            assert not reps[0].healthy
        finally:
            router.close()
            fakes[1].close()

    def test_no_healthy_replica_is_503(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(fakes, tmp_path)
        try:
            with router._lock:
                for r in reps:
                    r.healthy = False
            status, body = _post(router.port, "/score", b"1 3:1\n")
            assert status == 503
            assert b"no healthy replica" in body
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestShedding:
    def test_admit_sheds_past_the_deadline_budget(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=10.0
        )
        try:
            # 6 in flight across 2 healthy replicas (>= the 2-per-
            # replica floor) completing at ~100/s: projected delay
            # 7/100 = 70 ms > 10 ms -> shed.
            now = time.perf_counter()
            with router._lock:
                reps[0].inflight = 3
                reps[1].inflight = 3
                for i in range(100):
                    router._completions.append(now - i * 0.01)
            rep, why = router._admit()
            assert rep is None and why == "shed"
            # Below the concurrency floor admission always passes,
            # whatever the rate says.
            with router._lock:
                reps[0].inflight = 1
                reps[1].inflight = 1
            rep, why = router._admit()
            assert rep is not None and why is None
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_shed_is_fast_429_with_retry_after(self, tmp_path):
        fakes = [FakeReplica(delay_s=0.3), FakeReplica(delay_s=0.3)]
        router, _, tel = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=5.0
        )
        try:
            results = []
            lock = threading.Lock()

            def client():
                end = time.perf_counter() + 2.0
                while time.perf_counter() < end:
                    try:
                        resp = urllib.request.urlopen(
                            urllib.request.Request(
                                f"http://127.0.0.1:{router.port}"
                                "/score", data=b"1 3:1\n",
                                method="POST",
                            ), timeout=10,
                        )
                        resp.read()
                        with lock:
                            results.append((resp.status, None))
                    except urllib.error.HTTPError as e:
                        e.read()
                        with lock:
                            results.append(
                                (e.code, e.headers.get("Retry-After"))
                            )
                        time.sleep(0.02)

            threads = [
                threading.Thread(target=client) for _ in range(10)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            codes = [c for c, _ in results]
            assert codes.count(200) >= 1
            assert codes.count(429) >= 1, (
                "overload never shed — admission control is inert"
            )
            assert all(c in (200, 429) for c in codes)
            retry_after = next(h for c, h in results if c == 429)
            assert retry_after == "1"
            blk = router._build()["serve"]
            assert blk["shed"] == codes.count(429)
            assert blk["shed_frac"] > 0
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_zero_deadline_disables_shedding(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=0.0
        )
        try:
            now = time.perf_counter()
            with router._lock:
                reps[0].inflight = 50
                reps[1].inflight = 50
                for i in range(100):
                    router._completions.append(now - i * 0.005)
            rep, why = router._admit()
            assert rep is not None and why is None
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestCanary:
    def _canary_router(self, fakes, tmp_path, **cfg_kw):
        model = tmp_path / "model"
        model.mkdir(exist_ok=True)
        defaults = dict(
            serve_canary=True, serve_replicas=2, serve_poll_secs=0.05,
            model_file=str(model),
        )
        defaults.update(cfg_kw)
        router, reps, tel = _mk_router(fakes, tmp_path, **defaults)
        return router, reps, tel, str(model)

    def _publish(self, model, step):
        checkpoint._publish_manifest(model, step, "dense")

    def _traffic(self, port, n=6):
        for _ in range(n):
            status, _ = _post(port, "/score", b"1 3:1\n1 5:1\n")
            assert status == 200

    def test_promotion_rolls_the_fleet(self, tmp_path):
        # The new checkpoint scores the SAME distribution: the shadow
        # compare passes and every replica reloads + promotes.
        fakes = [FakeReplica(score=0.5), FakeReplica(score=0.5)]
        for f in fakes:
            f.pending = (0.5000001, 7)  # new step, same distribution
        router, reps, tel, model = self._canary_router(fakes, tmp_path)
        try:
            self._traffic(router.port)
            self._publish(model, 7)
            deadline = time.time() + 20
            while router.step != 7 and time.time() < deadline:
                time.sleep(0.05)
            assert router.step == 7, "promotion never completed"
            assert all(f.reload_calls == 1 for f in fakes)
            assert all(f.promote_calls == 1 for f in fakes)
            assert all(f.rollback_calls == 0 for f in fakes)
            counters = tel.snapshot()["counters"]
            assert counters["serve.canary_promotions"] == 1
            assert counters.get("serve.canary_rollbacks", 0) == 0
            # The compare artifacts are on disk for the operator.
            compare_dir = tmp_path / "model" / "canary_compare" / \
                "step_7"
            assert (compare_dir / "baseline.json").exists()
            assert (compare_dir / "canary.json").exists()
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_drifted_canary_rolls_back(self, tmp_path):
        # The canary's post-reload scores drift far from the baseline
        # replica's: report.py --compare flags, the canary rolls back,
        # the rest of the fleet never reloads, and the bad manifest is
        # baselined (no retry storm).
        fakes = [FakeReplica(score=0.5), FakeReplica(score=0.5)]
        fakes[0].pending = (0.9, 9)  # the canary would drift
        fakes[1].pending = (0.9, 9)
        router, reps, tel, model = self._canary_router(fakes, tmp_path)
        try:
            self._traffic(router.port)
            self._publish(model, 9)
            deadline = time.time() + 20
            while fakes[0].rollback_calls == 0 and \
                    time.time() < deadline:
                time.sleep(0.05)
            assert fakes[0].rollback_calls == 1, "canary never rolled back"
            assert fakes[0].score == 0.5  # restored
            assert fakes[1].reload_calls == 0  # fleet never touched
            assert router.step != 9
            counters = tel.snapshot()["counters"]
            assert counters["serve.canary_rollbacks"] == 1
            assert counters.get("serve.canary_promotions", 0) == 0
            # Baselined: three more polls must not retry the reload.
            calls = fakes[0].reload_calls
            time.sleep(0.3)
            assert fakes[0].reload_calls == calls
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_refused_reload_baselines_the_manifest(self, tmp_path):
        fakes = [FakeReplica(score=0.5), FakeReplica(score=0.5)]
        fakes[0].reload_status = 409  # unservable checkpoint
        router, reps, tel, model = self._canary_router(fakes, tmp_path)
        try:
            self._publish(model, 11)
            deadline = time.time() + 20
            while fakes[0].reload_calls == 0 and \
                    time.time() < deadline:
                time.sleep(0.05)
            assert fakes[0].reload_calls == 1
            time.sleep(0.3)  # several polls
            assert fakes[0].reload_calls == 1, (
                "refused checkpoint retried every poll (the unbounded "
                "reload loop the watcher baseline exists to prevent)"
            )
            assert router.step != 11
        finally:
            router.close()
            for f in fakes:
                f.close()


    def test_failed_rollback_quarantines_until_next_promotion(
        self, tmp_path
    ):
        """A rejected canary whose /rollback FAILS serves unvetted
        params: it must be quarantined — alive is not enough for the
        health loop to readmit it — until a later successful promotion
        reloads it onto a vetted checkpoint."""
        fakes = [FakeReplica(score=0.5) for _ in range(3)]
        fakes[0].pending = (0.9, 13)      # the canary drifts...
        fakes[0].rollback_status = 500    # ...and cannot roll back
        router, reps, tel, model = self._canary_router(
            fakes, tmp_path, serve_replicas=3, health_secs=0.05,
        )
        try:
            self._traffic(router.port)
            self._publish(model, 13)
            deadline = time.time() + 20
            while not reps[0].quarantined and time.time() < deadline:
                time.sleep(0.05)
            assert reps[0].quarantined, "failed rollback never quarantined"
            assert not reps[0].healthy
            # The replica still answers /healthz, but quarantine must
            # hold it out of routing across many health cycles.
            time.sleep(0.3)
            assert not reps[0].healthy, (
                "health loop readmitted a quarantined replica — it "
                "would be serving the rejected table"
            )
            # A good checkpoint promotes through the remaining pair
            # and recovers the quarantined replica onto it.
            fakes[0].rollback_status = 200
            for f in fakes:
                f.pending = (0.5000001, 14)
            self._traffic(router.port)
            self._publish(model, 14)
            deadline = time.time() + 20
            while (
                reps[0].quarantined or not reps[0].healthy
            ) and time.time() < deadline:
                time.sleep(0.05)
            assert not reps[0].quarantined
            assert reps[0].healthy, "recovered replica never readmitted"
            assert fakes[0].score == pytest.approx(0.5000001)
            assert router.step == 14
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestFleetLaunch:
    def test_replica_command_neutralizes_fleet_knobs(self, tmp_path):
        """ISSUE-12 review find: an INI-configured canary fleet used to
        crash every child at startup — the replica re-read
        serve_canary=true from the same cfg file while the launcher
        forced --replicas 0, tripping the child's own
        canary-requires-a-fleet validation.  The replica command must
        neutralize every fleet-level knob, and the CHILD's config
        parse (same cfg file + those flags) must succeed."""
        from fast_tffm_tpu import cli
        from fast_tffm_tpu.config import load_config
        from fast_tffm_tpu.serve.router import _replica_command

        cfg_path = tmp_path / "fleet.cfg"
        cfg_path.write_text(
            "[General]\nvocabulary_size = 64\nfactor_num = 4\n"
            f"model_file = {tmp_path}/model\n"
            "[Predict]\nserve_replicas = 2\nserve_canary = true\n"
            "serve_poll_secs = 1.0\n"
        )
        cfg = load_config(str(cfg_path))
        cmd = _replica_command(cfg, str(cfg_path), 0, {})
        assert "--no_serve_canary" in cmd
        assert cmd[cmd.index("--replicas") + 1] == "0"
        assert cmd[cmd.index("--serve_poll_secs") + 1] == "0"
        # Reproduce the child's own parse: argparse over the replica
        # flags, then main()'s override assembly, then load_config.
        args = cli.build_argparser().parse_args(cmd[3:])
        overrides = {
            key: getattr(args, key)
            for key in ("serve_replicas", "serve_port", "serve_host",
                        "serve_poll_secs")
            if getattr(args, key) is not None
        }
        assert args.no_serve_canary
        overrides["serve_canary"] = False
        child = load_config(str(cfg_path), overrides)  # must not raise
        assert child.serve_replicas == 0
        assert child.serve_canary is False
        assert child.serve_poll_secs == 0

    def test_router_process_is_jax_free(self):
        """The router front door must not pay a jax import (docstring +
        SERVING.md pin it): wire/manifest/router import through lazy
        package __init__s.  Probed in a clean subprocess — this test
        process imported jax long ago."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__
        )))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "import fast_tffm_tpu.serve.router\n"
             "import fast_tffm_tpu.serve.wire\n"
             "import fast_tffm_tpu.train.manifest\n"
             "heavy = [m for m in ('jax', 'orbax', 'optax')\n"
             "         if m in sys.modules]\n"
             "assert not heavy, f'router import pulled {heavy}'\n"],
            capture_output=True, timeout=120, env=env,
        )
        assert proc.returncode == 0, proc.stderr.decode()


def _post_with_headers(port, path, body, headers=None, timeout=30):
    """(status, body, response headers); HTTPError codes return."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        headers=headers or {},
    )
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


class TestRequestId:
    """ISSUE 14 tentpole: the request-id contract through the router
    (SERVING.md "Request ids & distributed tracing")."""

    def test_client_id_echoes_through_both_transports(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            status, _, hdrs = _post_with_headers(
                router.port, "/score", b"1 3:1\n",
                headers={"X-Request-Id": "client-abc-1"},
            )
            assert status == 200
            assert hdrs.get("X-Request-Id") == "client-abc-1"
            # The id propagated to the replica as a header.
            fake = next(f for f in fakes if f.last_headers)
            assert fake.last_headers.get("X-Request-Id") == \
                "client-abc-1"
            ids = np.zeros((1, 4), np.int32)
            status, _, hdrs = _post_with_headers(
                router.port, "/score_bin",
                wire.encode_bin_request(ids, np.ones((1, 4),
                                                     np.float32)),
                headers={"X-Request-Id": "client-abc-2"},
            )
            assert status == 200
            assert hdrs.get("X-Request-Id") == "client-abc-2"
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_invalid_client_id_is_ignored_not_fatal(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            status, _, hdrs = _post_with_headers(
                router.port, "/score", b"1 3:1\n",
                headers={"X-Request-Id": "x" * 300},  # over the cap
            )
            assert status == 200
            assert "X-Request-Id" not in hdrs
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_minted_ids_unique_under_concurrency(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        tracer = Tracer(enabled=True)
        router, _, _ = _mk_router(
            fakes, tmp_path, tracer=tracer,
            sampler=wire.RequestSampler(1.0, enabled=True, tag="t"),
        )
        try:
            seen = []
            lock = threading.Lock()

            def client():
                for _ in range(10):
                    status, _, hdrs = _post_with_headers(
                        router.port, "/score", b"1 3:1\n"
                    )
                    assert status == 200
                    with lock:
                        seen.append(hdrs.get("X-Request-Id"))

            threads = [
                threading.Thread(target=client) for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(rid for rid in seen), "a sampled response " \
                "lost its X-Request-Id echo"
            assert len(set(seen)) == len(seen) == 40, (
                "minted request ids collided under concurrency"
            )
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_sampling_off_proxies_byte_identical(self, tmp_path):
        """The no-id-work contract: with sampling off and no client
        id, the proxied body is EXACTLY what the client sent (no frame
        trailer, no header) and the response carries no echo."""
        fakes = [FakeReplica(), FakeReplica()]
        router, _, _ = _mk_router(fakes, tmp_path)
        try:
            ids = np.arange(8, dtype=np.int32).reshape(2, 4)
            vals = np.ones((2, 4), np.float32)
            frame = wire.encode_bin_request(ids, vals)
            status, _, hdrs = _post_with_headers(
                router.port, "/score_bin", frame
            )
            assert status == 200
            assert "X-Request-Id" not in hdrs
            fake = next(f for f in fakes if f.last_body is not None)
            assert fake.last_body == frame, (
                "unsampled binary frame was rewritten in transit"
            )
            assert "X-Request-Id" not in fake.last_headers
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_sampled_bin_frame_carries_the_trailer(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        tracer = Tracer(enabled=True)
        router, _, _ = _mk_router(
            fakes, tmp_path, tracer=tracer,
            sampler=wire.RequestSampler(1.0, enabled=True, tag="t"),
        )
        try:
            ids = np.zeros((1, 4), np.int32)
            frame = wire.encode_bin_request(
                ids, np.ones((1, 4), np.float32)
            )
            status, _, hdrs = _post_with_headers(
                router.port, "/score_bin", frame
            )
            assert status == 200
            rid = hdrs.get("X-Request-Id")
            assert rid
            fake = next(f for f in fakes if f.last_body is not None)
            assert fake.last_body != frame  # trailer appended
            assert wire.peek_bin_request_id(fake.last_body) == rid
            # ... and the replica-side decode agrees.
            out = wire.decode_bin_request(
                fake.last_body, FakeReplica._CFG
            )
            assert out[5] == rid
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_router_spans_cover_admit_and_proxy(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        tracer = Tracer(enabled=True)
        router, _, _ = _mk_router(
            fakes, tmp_path, tracer=tracer,
            sampler=wire.RequestSampler(1.0, enabled=True, tag="t"),
        )
        try:
            status, _, hdrs = _post_with_headers(
                router.port, "/score", b"1 3:1\n"
            )
            assert status == 200
            rid = hdrs["X-Request-Id"]
            events = tracer.take()
            spans = {
                ev["name"]: ev for ev in events
                if ev.get("ph") == "X"
                and (ev.get("args") or {}).get("rid") == rid
            }
            assert "serve.admit" in spans
            assert "serve.proxy" in spans
            assert spans["serve.admit"]["args"]["decision"] == "admit"
            assert spans["serve.proxy"]["args"]["replica"] in (0, 1)
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestWireTrailer:
    def test_trailer_roundtrip(self):
        cfg = FakeReplica._CFG
        ids = np.arange(8, dtype=np.int32).reshape(2, 4)
        vals = np.full((2, 4), 0.5, np.float32)
        frame = wire.encode_bin_request(ids, vals, request_id="rid-9")
        out = wire.decode_bin_request(frame, cfg)
        np.testing.assert_array_equal(out[0], ids)
        assert out[5] == "rid-9"
        assert wire.peek_bin_request_id(frame) == "rid-9"
        # Arrays are untouched by the trailer: the rid-less prefix is
        # bitwise the rid-less frame (minus the flags bit).
        bare = wire.encode_bin_request(ids, vals)
        assert wire.peek_bin_request_id(bare) is None
        stamped = wire.with_bin_request_id(bare, "rid-10")
        assert wire.peek_bin_request_id(stamped) == "rid-10"
        assert stamped[13:13 + len(bare) - 13] == bare[13:]
        # An existing trailer wins (client precedence).
        again = wire.with_bin_request_id(stamped, "other")
        assert wire.peek_bin_request_id(again) == "rid-10"

    def test_malformed_trailers_are_rejected(self):
        cfg = FakeReplica._CFG
        ids = np.zeros((1, 4), np.int32)
        vals = np.ones((1, 4), np.float32)
        frame = wire.encode_bin_request(ids, vals, request_id="abc")
        with pytest.raises(ValueError):
            wire.decode_bin_request(frame[:-1], cfg)  # short trailer
        with pytest.raises(ValueError):
            wire.decode_bin_request(frame + b"x", cfg)  # long
        # flags bit set but no trailer bytes at all
        import struct
        bare = wire.encode_bin_request(ids, vals)
        lying = struct.pack("<4sIIB", b"TFB1", 1, 4, 2) + bare[13:]
        with pytest.raises(ValueError):
            wire.decode_bin_request(lying, cfg)

    def test_valid_request_id_screens_header_hazards(self):
        assert wire.valid_request_id("req-1.a_b")
        # Reflected into a response header: CR/LF is response
        # splitting, non-ASCII breaks http.server's latin-1-strict
        # header write mid-stream, empty/oversized are junk.
        assert not wire.valid_request_id("evil\r\nX-Injected: 1")
        assert not wire.valid_request_id("café")
        assert not wire.valid_request_id("")
        assert not wire.valid_request_id(None)
        assert not wire.valid_request_id("x" * 200)

    def test_fields_and_trailer_compose(self):
        cfg = FmConfig(vocabulary_size=256, factor_num=4,
                       max_features=4, field_num=3)
        ids = np.zeros((2, 4), np.int32)
        vals = np.ones((2, 4), np.float32)
        fields = np.ones((2, 4), np.int32)
        frame = wire.encode_bin_request(
            ids, vals, fields, request_id="both-1"
        )
        out = wire.decode_bin_request(frame, cfg)
        assert out[2] is not None and out[5] == "both-1"
        assert wire.peek_bin_request_id(frame) == "both-1"


class TestFleetScrape:
    _BLOCK = {
        "requests": 5, "examples": 10, "batches": 2, "qps": 2.5,
        "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0, "max_ms": 4.0,
        "batch_fill": 0.5, "steady_compiles": 0,
    }

    def test_health_loop_scrapes_and_aggregates(self, tmp_path):
        fakes = [
            FakeReplica(status_block=dict(self._BLOCK)),
            FakeReplica(status_block=dict(self._BLOCK, qps=7.5,
                                          p99_ms=9.0)),
        ]
        router, _, _ = _mk_router(fakes, tmp_path, health_secs=0.05)
        try:
            deadline = time.time() + 10
            while len(router._scrapes) < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert len(router._scrapes) == 2, "fleet scrape never ran"
            blk = router._build()["serve"]
            assert blk["replicas_scraped"] == 2
            assert blk["fleet_requests"] == 10
            assert blk["fleet_examples"] == 20
            assert blk["fleet_qps"] == 10.0
            assert blk["fleet_p99_ms"] == 9.0  # max-merge
            assert blk["fleet_scrape_age_max_s"] >= 0
            # Per-replica detail rides /status...
            per = {p["index"]: p for p in blk["per_replica"]}
            assert per[1]["qps"] == 7.5
            assert "scrape_age_s" in per[0]
            # ...and /metrics exposes the labeled series.
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{router.port}/metrics", timeout=10
            ).read().decode()
            assert 'tffm_serve_replica_qps{replica="1"} 7.5' in text
            assert "tffm_serve_fleet_requests 10" in text
            assert "tffm_serve_fleet_p99_ms 9.0" in text
            assert 'tffm_serve_replica_scrape_age_s{replica="0"}' \
                in text
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_statusless_replicas_degrade_to_no_aggregates(
        self, tmp_path
    ):
        fakes = [FakeReplica(), FakeReplica()]  # no /status surface
        router, _, tel = _mk_router(fakes, tmp_path, health_secs=0.05)
        try:
            deadline = time.time() + 2
            while time.time() < deadline and not tel.snapshot()[
                "counters"
            ].get("serve.scrape_errors"):
                time.sleep(0.05)
            blk = router._build()["serve"]
            assert blk["replicas_scraped"] == 0
            assert "fleet_requests" not in blk
            assert tel.snapshot()["counters"][
                "serve.scrape_errors"
            ] >= 1
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestSlo:
    def test_tracker_burn_rate_math(self):
        tr = SloTracker(50.0, 0.9)  # budget = 0.1
        now = 1000.0
        for _ in range(8):
            tr.observe(True, 0.001, now=now)     # good
        tr.observe(True, 0.2, now=now)           # over the 50ms SLO
        tr.observe(False, now=now)               # shed
        snap = tr.snapshot(now=now)
        assert snap["slo_good"] == 8 and snap["slo_bad"] == 2
        assert snap["slo_bad_frac"] == pytest.approx(0.2)
        assert snap["burn_rate"] == pytest.approx(2.0)  # 0.2 / 0.1
        # The window slides: outcomes age out.
        snap = tr.snapshot(now=now + 120.0)
        assert snap["slo_good"] == 0 and snap["slo_bad"] == 0
        assert snap["burn_rate"] == 0.0

    def test_tracker_disabled_without_knobs(self):
        tr = SloTracker(0.0, 0.0)
        tr.observe(True, 0.001)
        assert tr.snapshot() == {}

    def test_router_burn_rate_counts_sheds(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        router, reps, _ = _mk_router(
            fakes, tmp_path, serve_shed_deadline_ms=10.0,
            serve_slo_p99_ms=10_000.0, serve_slo_availability=0.9,
        )
        try:
            for _ in range(8):
                status, _ = _post(router.port, "/score", b"1 3:1\n")
                assert status == 200
            # Force the admission ledger into shed territory.
            now = time.perf_counter()
            with router._lock:
                reps[0].inflight = 3
                reps[1].inflight = 3
                for i in range(100):
                    router._completions.append(now - i * 0.01)
            status, _ = _post(router.port, "/score", b"1 3:1\n")
            assert status == 429
            blk = router._build()["serve"]
            assert blk["slo_bad"] >= 1
            assert blk["burn_rate"] > 0
            assert blk["slo_availability"] == 0.9
            # The alert plane reads it through the serve-signal alias.
            engine = obs.AlertEngine(
                obs.parse_rules("burn_rate > 0.1 : warn")
            )
            fired = engine.observe(router._build("heartbeat"))
            assert len(fired) == 1
            assert fired[0]["signal"] == "burn_rate"
        finally:
            router.close()
            for f in fakes:
                f.close()


class _FakePendingProc:
    """A _ReplicaProc-shaped handle for the respawn state machine."""

    def __init__(self, index):
        self.index = index
        self.port = None
        self.ready = threading.Event()
        self.proc = _FakePopen()

    def announce(self, port):
        self.port = port
        self.proc._alive = True
        self.ready.set()

    def die(self):
        self.proc._alive = False
        self.ready.set()


class _FakePopen:
    def __init__(self, alive=False):
        self._alive = alive
        self.pid = 4242

    def poll(self):
        return None if self._alive else 1


class TestRespawn:
    def _dead_managed_router(self, fakes, tmp_path, respawner):
        router, reps, tel = _mk_router(
            fakes, tmp_path, respawner=respawner
        )
        # Managed replica whose process has died.
        reps[0].proc = _FakePopen(alive=False)
        return router, reps, tel

    def test_dead_managed_replica_respawns_and_readopts(
        self, tmp_path
    ):
        fakes = [FakeReplica(), FakeReplica()]
        spawned = []

        def respawner(index):
            p = _FakePendingProc(index)
            spawned.append(p)
            return p

        router, reps, tel = self._dead_managed_router(
            fakes, tmp_path, respawner
        )
        try:
            rep = reps[0]
            router._evict(rep, "test: process died")
            router._respawn_step(rep)
            assert len(spawned) == 1
            assert rep.respawn_pending is spawned[0]
            assert tel.snapshot()["counters"]["serve.respawns"] == 1
            # Not ready yet: polling is a no-op.
            router._respawn_poll(rep)
            assert rep.respawn_pending is spawned[0]
            # Port announced -> adopted; health loop may readmit.
            spawned[0].announce(fakes[0].port)
            router._respawn_poll(rep)
            assert rep.respawn_pending is None
            assert rep.port == fakes[0].port
            assert rep.proc is spawned[0].proc
            # The real (fake) replica answers /healthz -> readmission
            # resets the backoff counter.
            assert router._probe_health(rep)
            router._readmit(rep)
            assert rep.healthy and rep.respawn_fails == 0
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_respawn_backoff_doubles_and_caps(self, tmp_path):
        from fast_tffm_tpu.serve import router as router_mod

        fakes = [FakeReplica(), FakeReplica()]
        spawned = []

        def respawner(index):
            p = _FakePendingProc(index)
            spawned.append(p)
            return p

        router, reps, _ = self._dead_managed_router(
            fakes, tmp_path, respawner
        )
        try:
            rep = reps[0]
            delays = []
            for k in range(7):
                rep.next_respawn_t = 0.0  # due now
                t0 = time.monotonic()
                router._respawn_step(rep)
                assert len(spawned) == k + 1
                delays.append(rep.next_respawn_t - t0)
                # This attempt dies before announcing a port.
                spawned[-1].die()
                router._respawn_poll(rep)
                assert rep.respawn_pending is None
            base = router_mod._RESPAWN_BASE_S
            cap = router_mod._RESPAWN_CAP_S
            for k, d in enumerate(delays):
                assert d == pytest.approx(
                    min(cap, base * 2 ** k), abs=0.25
                )
            # While the backoff clock hasn't expired, no new attempt.
            rep.next_respawn_t = time.monotonic() + 60
            router._respawn_step(rep)
            assert len(spawned) == 7
        finally:
            router.close()
            for f in fakes:
                f.close()

    def test_unmanaged_replica_keeps_evict_only(self, tmp_path):
        fakes = [FakeReplica(), FakeReplica()]
        spawned = []
        router, reps, _ = _mk_router(
            fakes, tmp_path,
            respawner=lambda i: spawned.append(i),
        )
        try:
            rep = reps[0]  # proc is None: unmanaged host:port replica
            router._respawn_step(rep)
            assert not spawned
            assert rep.respawn_pending is None
        finally:
            router.close()
            for f in fakes:
                f.close()


class TestConfig:
    def test_canary_requires_a_fleet(self):
        with pytest.raises(ValueError, match="serve_replicas"):
            FmConfig(serve_canary=True, serve_replicas=1)
        with pytest.raises(ValueError, match="serve_poll_secs"):
            FmConfig(serve_canary=True, serve_replicas=2,
                     serve_poll_secs=0)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="serve_transport"):
            FmConfig(serve_transport="grpc")
        with pytest.raises(ValueError, match="serve_replicas"):
            FmConfig(serve_replicas=-1)
        with pytest.raises(ValueError, match="serve_shed_deadline_ms"):
            FmConfig(serve_shed_deadline_ms=-1)

    def test_observability_knob_validation(self):
        # Silently-inert-knob discipline: sampling needs a trace file.
        with pytest.raises(ValueError, match="serve_trace_sample"):
            FmConfig(serve_trace_sample=0.5)
        with pytest.raises(ValueError, match="serve_trace_sample"):
            FmConfig(serve_trace_sample=1.5, trace_file="/tmp/t.json")
        FmConfig(serve_trace_sample=0.5, trace_file="/tmp/t.json")
        with pytest.raises(ValueError, match="serve_slo_availability"):
            FmConfig(serve_slo_availability=1.0)
        with pytest.raises(ValueError, match="serve_slo_p99_ms"):
            FmConfig(serve_slo_p99_ms=-1)
        FmConfig(serve_slo_p99_ms=50.0, serve_slo_availability=0.999)

    def test_replica_command_neutralizes_trace_sampling(self, tmp_path):
        """An INI fleet with serve_trace_sample set must not let the
        children self-sample (router-less partial chains) — and each
        replica gets its own suffixed trace path."""
        from fast_tffm_tpu.config import load_config
        from fast_tffm_tpu.serve.router import _replica_command

        cfg_path = tmp_path / "fleet.cfg"
        cfg_path.write_text(
            "[General]\nvocabulary_size = 64\nfactor_num = 4\n"
            f"model_file = {tmp_path}/model\n"
            "[Predict]\nserve_replicas = 2\n"
            f"[Train]\ntrace_file = {tmp_path}/trace.json\n"
            "serve_trace_sample = 0.5\n"
            "heartbeat_secs = 1\n"
            "alert_rules = burn_rate > 10 : halt\n"
        )
        cfg = load_config(str(cfg_path))
        cmd = _replica_command(cfg, str(cfg_path), 1, {})
        assert cmd[cmd.index("--serve_trace_sample") + 1] == "0"
        assert cmd[cmd.index("--trace") + 1] == \
            f"{tmp_path}/trace.json.replica1"
        # The router owns the watchdog: an INI halt rule leaking into
        # a replica would self-halt it and the respawn policy would
        # relaunch it forever.
        assert cmd[cmd.index("--alert_rules") + 1] == ""
