"""Alert-rule watchdog (ISSUE 7 tentpole, layer 2).

Pins:

  * the ``alert_rules`` grammar (ops, ``for N`` sustain, actions,
    loud parse errors — a typo'd rule must fail config construction,
    never silently watch nothing);
  * engine semantics on synthetic heartbeat streams: fire/hold,
    consecutive-breach sustain with reset on recovery AND on
    non-evaluable beats, one fire per breach episode, the derived
    signals (``grad_norm_drift`` rolling baseline, ``beat_gap_s``
    staleness, queue-empty fractions);
  * the pinned ``record: alert`` JSONL schema;
  * integration: a warn rule fires during a real heartbeat'd training
    run and lands in the metrics stream where ``tools/report.py``
    summarizes it and ``--compare`` regression-gates it (alerts_total
    and per-rule keys, per-key ``--threshold`` overrides);
  * a halt rule stops a real run via ``AlertHaltError`` raised from
    the dispatch loop, with the crash-truthful final record naming it
    and no checkpoint written.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.alerts import (
    AlertEngine, AlertHaltError, AlertRule, BASELINE_MIN, parse_rules,
)
from fast_tffm_tpu.train.loop import Trainer

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import report  # noqa: E402


class TestParseRules:
    def test_full_grammar(self):
        rules = parse_rules(
            "ingest_wait_frac > 0.5 for 3 : warn ;\n"
            "tiered.hot_hit_frac < 0.9 : halt"
        )
        assert rules == [
            AlertRule("ingest_wait_frac", ">", 0.5, 3, "warn"),
            AlertRule("tiered.hot_hit_frac", "<", 0.9, 1, "halt"),
        ]
        assert rules[0].name == "ingest_wait_frac>0.5"

    def test_empty_and_blank_rules_skipped(self):
        assert parse_rules("") == []
        assert parse_rules(" ; ; ") == []

    @pytest.mark.parametrize("bad", [
        "no_action > 1",
        "x > 1 : explode",
        "x >= 1 : warn",
        "x > nan_ish_word : warn",
        "x > 1 for zero : warn",
        "x > 1 for 0 : warn",
        "> 1 : warn",
    ])
    def test_grammar_errors_are_loud(self, bad):
        with pytest.raises(ValueError, match="alert rule"):
            parse_rules(bad)

    def test_config_validates_rules_at_construction(self):
        with pytest.raises(ValueError, match="alert rule"):
            FmConfig(alert_rules="bogus rule")
        FmConfig(
            alert_rules="ingest_wait_frac > 0.5 : warn",
            heartbeat_secs=30,
        )  # ok

    def test_resource_rules_refused_when_plane_off(self):
        """A rule watching the `resource` block can never evaluate with
        resource_metrics=off — same silently-inert hazard the
        heartbeat_secs check closes, so config refuses it at startup."""
        for sig in ("recompiles_unexpected > 0 : halt",
                    "rss_mb > 4000 : warn",
                    "resource.compile_s > 10 : warn"):
            with pytest.raises(ValueError, match="resource-plane"):
                FmConfig(alert_rules=sig, heartbeat_secs=30,
                         resource_metrics=False)
        # With the plane on (the default) the same rules are fine.
        FmConfig(alert_rules="recompiles_unexpected > 0 : halt",
                 heartbeat_secs=30)


def _rec(**kw) -> dict:
    rec = {"record": "heartbeat", "step": kw.pop("step", 1)}
    rec.update(kw)
    return rec


class TestEngineSemantics:
    def test_fires_on_breach_and_holds_below(self):
        eng = AlertEngine(parse_rules("ingest_wait_frac > 0.5 : warn"))
        assert eng.observe(_rec(ingest_wait_frac=0.2)) == []
        fired = eng.observe(_rec(ingest_wait_frac=0.8, step=4))
        assert len(fired) == 1
        a = fired[0]
        # The pinned alert-record schema.
        assert a == {
            "record": "alert", "time": a["time"], "step": 4,
            "rule": "ingest_wait_frac>0.5",
            "signal": "ingest_wait_frac", "value": 0.8,
            "threshold": 0.5, "op": ">", "sustain": 1,
            "action": "warn",
        }
        assert eng.fired_total == 1 and eng.halted is None

    def test_sustain_requires_consecutive_breaches(self):
        eng = AlertEngine(
            parse_rules("ingest_wait_frac > 0.5 for 3 : warn")
        )
        assert eng.observe(_rec(ingest_wait_frac=0.9)) == []
        assert eng.observe(_rec(ingest_wait_frac=0.9)) == []
        # Recovery resets the streak.
        assert eng.observe(_rec(ingest_wait_frac=0.1)) == []
        assert eng.observe(_rec(ingest_wait_frac=0.9)) == []
        assert eng.observe(_rec(ingest_wait_frac=0.9)) == []
        assert len(eng.observe(_rec(ingest_wait_frac=0.9))) == 1

    def test_one_fire_per_breach_episode(self):
        eng = AlertEngine(parse_rules("ingest_wait_frac > 0.5 : warn"))
        assert len(eng.observe(_rec(ingest_wait_frac=0.9))) == 1
        # Still breaching: no re-fire spam.
        assert eng.observe(_rec(ingest_wait_frac=0.9)) == []
        # Recover, breach again: a NEW episode fires.
        assert eng.observe(_rec(ingest_wait_frac=0.1)) == []
        assert len(eng.observe(_rec(ingest_wait_frac=0.9))) == 1
        assert eng.fired_total == 2

    def test_missing_signal_resets_streak(self):
        eng = AlertEngine(
            parse_rules("tiered.hot_hit_frac < 0.9 for 2 : warn")
        )
        assert eng.observe(_rec(tiered={"hot_hit_frac": 0.5})) == []
        # A beat without the tiered block (e.g. tiering off) must not
        # count toward the streak.
        assert eng.observe(_rec()) == []
        assert eng.observe(_rec(tiered={"hot_hit_frac": 0.5})) == []
        fired = eng.observe(_rec(tiered={"hot_hit_frac": 0.5}))
        assert len(fired) == 1

    def test_less_than_op_and_aliases(self):
        eng = AlertEngine(parse_rules(
            "hot_hit_frac < 0.9 : warn ; nonfinite_steps > 0 : warn"
        ))
        fired = eng.observe(_rec(
            tiered={"hot_hit_frac": 0.5},
            health={"nonfinite_steps": 2},
        ))
        assert {a["signal"] for a in fired} == {
            "hot_hit_frac", "nonfinite_steps"
        }

    def test_dotted_instrument_names_resolve(self):
        eng = AlertEngine(parse_rules(
            "stages.gauges.ingest.oor_batches > 0 : warn"
        ))
        fired = eng.observe(_rec(
            stages={"gauges": {"ingest.oor_batches": 3}}
        ))
        assert len(fired) == 1 and fired[0]["value"] == 3.0

    def test_escalation_pair_sharing_a_name_both_fire(self):
        """Two rules may differ only in sustain/action (warn early,
        halt if sustained) and therefore share AlertRule.name; state
        keyed per RULE must let both fire independently — name-keyed
        state used to let the warn rule swallow the halt forever."""
        eng = AlertEngine(parse_rules(
            "ingest_wait_frac > 0.5 : warn ; "
            "ingest_wait_frac > 0.5 for 3 : halt"
        ))
        fired = eng.observe(_rec(ingest_wait_frac=0.9))
        assert [a["action"] for a in fired] == ["warn"]
        assert eng.observe(_rec(ingest_wait_frac=0.9)) == []
        fired = eng.observe(_rec(ingest_wait_frac=0.9))
        assert [a["action"] for a in fired] == ["halt"]
        assert eng.halted is not None

    def test_halt_arms_halted_flag(self):
        eng = AlertEngine(parse_rules("step > 5 : halt"))
        assert eng.observe(_rec(step=3)) == []
        assert eng.halted is None
        eng.observe(_rec(step=8))
        assert eng.halted is not None
        assert eng.halted["action"] == "halt"

    def test_writer_receives_jsonl(self, tmp_path):
        path = str(tmp_path / "alerts.jsonl")
        writer = obs.JsonlWriter(path)
        eng = AlertEngine(
            parse_rules("ingest_wait_frac > 0.5 : warn"), writer=writer
        )
        eng.observe(_rec(ingest_wait_frac=0.9))
        writer.close()
        recs = [json.loads(l) for l in open(path)]
        assert len(recs) == 1 and recs[0]["record"] == "alert"

    def test_warn_logs(self, caplog):
        eng = AlertEngine(parse_rules("ingest_wait_frac > 0.5 : warn"))
        with caplog.at_level("WARNING", logger="fast_tffm_tpu.obs.alerts"):
            eng.observe(_rec(ingest_wait_frac=0.9))
        assert any("ALERT" in r.message for r in caplog.records)


class TestDerivedSignals:
    def test_grad_norm_drift_needs_baseline_then_fires(self):
        eng = AlertEngine(parse_rules("grad_norm_drift > 5 : warn"))
        # Stable grad norms build the baseline; none may fire (the
        # baseline excludes the current beat, so drift stays ~1).
        for i in range(BASELINE_MIN):
            assert eng.observe(
                _rec(health={"grad_norm": 1.0}, step=i)
            ) == []
        # A 10x spike against the rolling baseline fires.
        fired = eng.observe(_rec(health={"grad_norm": 10.0}, step=99))
        assert len(fired) == 1
        assert fired[0]["value"] == pytest.approx(10.0)

    def test_grad_norm_drift_not_evaluable_without_history(self):
        eng = AlertEngine(parse_rules("grad_norm_drift > 0.0001 : warn"))
        # Even a "fire on anything" drift rule holds until the
        # baseline exists.
        assert eng.observe(_rec(health={"grad_norm": 100.0})) == []

    def test_beat_gap_staleness(self):
        clock = {"t": 1000.0}
        eng = AlertEngine(
            parse_rules("beat_gap_s > 10 : warn"),
            clock=lambda: clock["t"],
        )
        assert eng.observe(_rec()) == []  # no previous beat yet
        clock["t"] += 5
        assert eng.observe(_rec()) == []
        clock["t"] += 60  # the loop stalled
        fired = eng.observe(_rec())
        assert len(fired) == 1 and fired[0]["value"] == 60.0

    def test_queue_empty_frac(self):
        eng = AlertEngine(
            parse_rules("prefetch_out_empty_frac > 0.5 : warn")
        )
        busy = {"count": 10, "buckets": {"1": 10}}
        starved = {"count": 10, "buckets": {"0": 8, "1": 2}}
        assert eng.observe(_rec(
            stages={"depths": {"prefetch.out_q_depth": busy}}
        )) == []
        fired = eng.observe(_rec(
            stages={"depths": {"prefetch.out_q_depth": starved}}
        ))
        assert len(fired) == 1 and fired[0]["value"] == 0.8


# ---------------------------------------------------------------------------
# Integration: rules riding a real run's heartbeat
# ---------------------------------------------------------------------------


def _write_libsvm(path, n_lines, vocab=50, n_feat=3, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.choice(vocab, size=n_feat, replace=False)
            toks = " ".join(f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats)
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


def _cfg(data, tmp_path, tag, **kw):
    defaults = dict(
        vocabulary_size=50,
        factor_num=4,
        model_file=str(tmp_path / f"model_{tag}"),
        train_files=[data],
        epoch_num=1,
        batch_size=32,
        max_features=4,
        log_steps=0,
        thread_num=2,
        steps_per_dispatch=4,
        seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.fixture(scope="module")
def train_file(tmp_path_factory):
    out = tmp_path_factory.mktemp("alert_data")
    return _write_libsvm(out / "train.libsvm", 640)


def _throttle(trainer, delay_s: float):
    """Slow each dispatch so heartbeats (and the rules riding them)
    get a deterministic number of chances to fire mid-run."""
    real = trainer._scan_train_step

    def slow(state, batches):
        time.sleep(delay_s)
        return real(state, batches)

    trainer._scan_train_step = slow


class TestAlertIntegration:
    def test_warn_rule_fires_into_metrics_stream(self, train_file,
                                                 tmp_path, capsys):
        mf = str(tmp_path / "warn.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "warnrule",
            heartbeat_secs=0.05, metrics_file=mf,
            # step is always >= 4 at the first post-dispatch beat.
            alert_rules="step > 0 : warn",
        )
        trainer = Trainer(cfg)
        _throttle(trainer, 0.05)
        result = trainer.train()  # must complete under warn
        assert result["train"]["steps"] == 20
        recs = [json.loads(l) for l in open(mf)]
        alerts = [r for r in recs if r.get("record") == "alert"]
        assert len(alerts) == 1  # one breach episode, one record
        assert alerts[0]["rule"] == "step>0"
        assert alerts[0]["action"] == "warn"
        # The run header names the rule set (stream self-description).
        header = [r for r in recs if r.get("record") == "run_header"][0]
        assert header["alert_rules"] == "step > 0 : warn"
        # The documented rule signals are LIVE on the heartbeat path:
        # grad_norm_rms rides the same delayed readback as grad_norm
        # (a rule on it must not be silently inert at log_steps=0).
        hb = [r for r in recs if r.get("record") == "heartbeat"][-1]
        assert "grad_norm_rms" in hb["health"]
        assert "grad_norm" in hb["health"]
        # report.py surfaces the alert section.
        assert report.main([mf]) == 0
        out = capsys.readouterr().out
        assert "alerts (1 fired)" in out
        assert "step>0" in out

    def test_halt_rule_stops_run_without_checkpoint(self, train_file,
                                                    tmp_path):
        from fast_tffm_tpu.train import checkpoint

        mf = str(tmp_path / "halt.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "haltrule",
            heartbeat_secs=0.05, metrics_file=mf,
            alert_rules="step > 0 : halt",
        )
        trainer = Trainer(cfg)
        _throttle(trainer, 0.05)
        with pytest.raises(AlertHaltError, match="step>0"):
            trainer.train()
        # Halted mid-run: nothing like the full 20 steps trained, and
        # no checkpoint was written on the way down.
        assert int(trainer.state.step) < 20
        assert not checkpoint.exists(cfg.model_file)
        recs = [json.loads(l) for l in open(mf)]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert final["exception"] == "AlertHaltError"
        assert any(r.get("record") == "alert" and r["action"] == "halt"
                   for r in recs)

    def test_compare_gates_alerting_run(self, train_file, tmp_path,
                                        capsys):
        """A clean run vs the same run alerting: alerts_total (present
        as 0 on the clean side) flags as a regression."""
        clean = str(tmp_path / "clean.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "clean",
            heartbeat_secs=0.05, metrics_file=clean,
        )
        t = Trainer(cfg)
        _throttle(t, 0.05)
        t.train()
        alerting = str(tmp_path / "alerting.jsonl")
        cfg2 = _cfg(
            train_file, tmp_path, "alerting",
            heartbeat_secs=0.05, metrics_file=alerting,
            alert_rules="step > 0 : warn",
        )
        t2 = Trainer(cfg2)
        _throttle(t2, 0.05)
        t2.train()
        rc = report.main(["--compare", clean, alerting])
        out = capsys.readouterr().out
        assert rc == 2
        assert "alerts_total" in out
        # Per-key threshold overrides share the same vocabulary: an
        # absurdly loose override on alerts_total (inf never exceeds a
        # ratio check... use the elapsed key instead) — here, verify a
        # per-key override changes the verdict for a real key.
        rc2 = report.main([
            "--compare", clean, clean,
            "--threshold", "default=0.05",
        ])
        assert rc2 == 0

    def test_rules_without_heartbeat_fail_at_startup(self):
        """Rules with no heartbeat to ride would never evaluate — for
        a halt rule that is a silently inert safety mechanism, so the
        config refuses it at construction."""
        with pytest.raises(ValueError, match="heartbeat_secs"):
            FmConfig(alert_rules="step > 0 : halt")  # heartbeat off


class TestThresholdOverrides:
    def test_parse_thresholds_forms(self):
        assert report.parse_thresholds(None) == {"default": 0.05}
        assert report.parse_thresholds(["0.07"]) == {"default": 0.07}
        assert report.parse_thresholds(
            ["ingest_wait_frac=0.10", "default=0.02"]
        ) == {"default": 0.02, "ingest_wait_frac": 0.10}
        with pytest.raises(SystemExit):
            report.parse_thresholds(["ingest_wait_frac=abc"])

    def test_per_key_override_changes_verdict(self, tmp_path, capsys):
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(
            {"metric": "x", "value": 100.0, "ingest_wait_frac": 0.10}
        ))
        b.write_text(json.dumps(
            {"metric": "x", "value": 100.0, "ingest_wait_frac": 0.108}
        ))
        # 8% worse wait: flagged at the default 5%...
        assert report.main(["--compare", str(a), str(b)]) == 2
        capsys.readouterr()
        # ...but passes with a 10% per-key override while the default
        # stays tight for everything else.
        rc = report.main([
            "--compare", str(a), str(b),
            "--threshold", "ingest_wait_frac=0.10",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "per-key override" in out
