"""Online serving path (ISSUE 9 tentpole): compiled fixed-shape scorer
+ request-batching inference server.

The pinned guarantees:

  * parity — served scores are BITWISE-IDENTICAL to offline
    ``predict()`` output for the same examples: both route through the
    same fixed-shape ladder, and per-example scores are independent of
    the batch shape they pad into (pad/bucket parity);
  * zero compiles — after :meth:`warmup`, steady-state serving never
    compiles (every request shape pads into a precompiled rung); a
    shape OUTSIDE the ladder flags ``serve.recompiles_unexpected``;
  * batching — the batcher coalesces concurrent requests into one
    microbatch, honors the ``max_batch_wait_ms`` deadline for lone
    requests, and carries overflow into the next dispatch;
  * hot swap — mid-traffic checkpoint swaps return only old-table or
    new-table scores (never torn), with zero recompiles and no failed
    requests; the manifest watcher picks up a republished checkpoint;
  * overlay — a huge-V ``tiered.npz`` checkpoint predicts/serves via
    the compact per-chunk remap, exactly matching full-table scoring.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.serve.batcher import ServeBatcher
from fast_tffm_tpu.serve.scorer import (
    FixedShapeScorer, OverlayScorer, load_model, make_scorer,
)
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.serve.router import Replica, ServeRouter
from fast_tffm_tpu.serve.server import (
    CheckpointWatcher, parse_request, serve,
)
from fast_tffm_tpu.train import checkpoint, tiered
from fast_tffm_tpu.train.loop import Trainer, predict

V = 256
F = 4


def _cfg(tmp_path, model="model", **kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=F, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        predict_files=[str(tmp_path / "train.libsvm")],
        score_path=str(tmp_path / "scores.txt"),
        model_file=str(tmp_path / model),
        epoch_num=1, log_steps=0, thread_num=1, seed=3,
        serve_batch_sizes="32,64", max_batch_wait_ms=1.0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _write_data(path, rng, lines=256, vocab=V):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5 "
                f"{rng.integers(0, vocab)}:0.25\n"
            )


def _params(cfg, seed=0):
    return jax.jit(lambda k: fm.init_params(k, cfg=cfg))(
        jax.random.PRNGKey(seed)
    )


def _examples(rng, n, vocab=V, feat=F):
    ids = rng.integers(0, vocab, (n, feat)).astype(np.int32)
    vals = rng.uniform(0.1, 1.0, (n, feat)).astype(np.float32)
    return ids, vals


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One trained dense checkpoint shared by the e2e tests."""
    tmp_path = tmp_path_factory.mktemp("serving")
    _write_data(tmp_path / "train.libsvm", np.random.default_rng(0))
    cfg = _cfg(tmp_path)
    Trainer(cfg).train()
    return tmp_path, cfg


# ----------------------------------------------------------------------
# scorer: ladder, padding parity, compile accounting
# ----------------------------------------------------------------------


class TestScorer:
    def test_pad_and_bucket_parity_bitwise(self, rng):
        """The acceptance property: per-example scores are identical
        whatever rung the example pads into — so batching/padding can
        never change an answer."""
        cfg = _cfg_mem()
        sc = FixedShapeScorer(cfg, _params(cfg))
        ids, vals = _examples(rng, 70)
        full = sc.score(ids, vals)  # 64-rung chunk + padded tail
        assert full.shape == (70,)
        one = sc.score(ids[:1], vals[:1])  # 32-rung, 31 pad rows
        np.testing.assert_array_equal(full[:1], one)
        mid = sc.score(ids[10:40], vals[10:40])
        np.testing.assert_array_equal(full[10:40], mid)

    def test_chunking_large_request(self, rng):
        cfg = _cfg_mem()
        sc = FixedShapeScorer(cfg, _params(cfg))
        ids, vals = _examples(rng, 300)  # >> max rung 64
        full = sc.score(ids, vals)
        parts = np.concatenate([
            sc.score(ids[i:i + 50], vals[i:i + 50])
            for i in range(0, 300, 50)
        ])
        np.testing.assert_array_equal(full, parts)

    def test_zero_compiles_after_warmup(self, rng):
        tel = obs.Telemetry()
        cfg = _cfg_mem()
        sc = FixedShapeScorer(cfg, _params(cfg), telemetry=tel)
        n = sc.warmup()
        assert n == len(sc.ladder) == 2
        for size in (1, 7, 31, 32, 33, 64, 200):
            ids, vals = _examples(rng, size)
            sc.score(ids, vals)
        assert sc.steady_compiles == 0
        snap = tel.snapshot()
        assert snap["timers"]["serve.compile"]["count"] == n
        assert snap["counters"].get(
            "serve.recompiles_unexpected", 0
        ) == 0

    def test_off_ladder_rung_flags_unexpected(self, rng):
        tel = obs.Telemetry()
        cfg = _cfg_mem()
        sc = FixedShapeScorer(cfg, _params(cfg), telemetry=tel)
        sc.warmup()
        b = 48  # not a ladder rung (multiple of the 8-device data axis)
        ids, vals = _examples(rng, b)
        sc.score_rung(ids, vals, None, b)
        assert sc.steady_compiles == 1
        assert tel.snapshot()["counters"][
            "serve.recompiles_unexpected"
        ] == 1

    def test_ladder_rounds_to_data_axis(self):
        # 8 virtual devices: a rung of 10 must round to a multiple of 8.
        cfg = _cfg_mem(serve_batch_sizes="10,60")
        sc = FixedShapeScorer(cfg, _params(cfg))
        data_n = sc.mesh.shape["data"]
        assert all(b % data_n == 0 for b in sc.ladder)

    def test_compile_records_written(self, rng, tmp_path):
        path = tmp_path / "m.jsonl"
        writer = obs.JsonlWriter(str(path))
        cfg = _cfg_mem()
        sc = FixedShapeScorer(cfg, _params(cfg), writer=writer)
        sc.warmup()
        writer.close()
        recs = [json.loads(l) for l in open(path)]
        assert len(recs) == len(sc.ladder)
        for r in recs:
            assert r["record"] == "compile"
            assert r["where"] == "serve"
            assert r["expected"] is True
            assert r["compile_s"] > 0


def _cfg_mem(**kw):
    """A config never touching disk (in-memory params scorer tests)."""
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=F, batch_size=32,
        serve_batch_sizes="32,64", max_batch_wait_ms=1.0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


# ----------------------------------------------------------------------
# batcher: coalescing, deadline, overflow carry
# ----------------------------------------------------------------------


class _FakeScorer:
    """Batcher-facing scorer stub: deterministic scores (sum of vals
    per row), records every dispatched rung."""

    def __init__(self, ladder=(32, 64), delay_s=0.0):
        self.ladder = tuple(ladder)
        self.max_rung = self.ladder[-1]
        self.cfg = _cfg_mem()  # the batcher sizes its pools from this
        self.dispatches: list = []
        self._delay = delay_s

    def rung_for(self, n):
        for b in self.ladder:
            if n <= b:
                return b
        return self.max_rung

    def slots_for(self, n):
        return n

    def score_rung(self, ids, vals, fields, b):
        if self._delay:
            time.sleep(self._delay)
        self.dispatches.append(b)
        return vals.sum(axis=1)

    def score(self, ids, vals, fields=None):
        self.dispatches.append(len(ids))
        return vals.sum(axis=1)


class TestBatcher:
    def test_coalesces_concurrent_requests(self, rng):
        fake = _FakeScorer(delay_s=0.005)
        bat = ServeBatcher(fake, max_batch_wait_ms=20.0)
        try:
            ids, vals = _examples(rng, 4)
            results = [None] * 10
            def go(i):
                results[i] = bat.score(ids, vals, timeout=10)
            threads = [
                threading.Thread(target=go, args=(i,))
                for i in range(10)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for r in results:
                np.testing.assert_allclose(r, vals.sum(axis=1))
            # 10 requests x 4 examples coalesced into FEWER dispatches
            # (the first may go alone; the rest pile up behind it).
            assert 1 <= len(fake.dispatches) < 10
            assert all(b <= fake.max_rung for b in fake.dispatches)
        finally:
            bat.close()

    def test_lone_request_honors_deadline(self, rng):
        fake = _FakeScorer()
        bat = ServeBatcher(fake, max_batch_wait_ms=30.0)
        try:
            ids, vals = _examples(rng, 2)
            t0 = time.perf_counter()
            bat.score(ids, vals, timeout=10)
            elapsed = time.perf_counter() - t0
            # Must wait ~the deadline for company, then dispatch —
            # never hang for a full rung that will not arrive.
            assert 0.02 <= elapsed < 5.0
        finally:
            bat.close()

    def test_zero_wait_dispatches_immediately(self, rng):
        fake = _FakeScorer()
        bat = ServeBatcher(fake, max_batch_wait_ms=0.0)
        try:
            ids, vals = _examples(rng, 2)
            t0 = time.perf_counter()
            bat.score(ids, vals, timeout=10)
            assert time.perf_counter() - t0 < 1.0
        finally:
            bat.close()

    def test_overflow_carries_to_next_dispatch(self, rng):
        fake = _FakeScorer(delay_s=0.02)
        bat = ServeBatcher(fake, max_batch_wait_ms=50.0)
        try:
            ids, vals = _examples(rng, 40)
            reqs = [bat.submit(ids, vals) for _ in range(3)]  # 120 > 64
            outs = [bat.result(r, timeout=10) for r in reqs]
            for out in outs:
                np.testing.assert_allclose(out, vals.sum(axis=1))
            # 3 x 40 cannot share a 64-rung: every dispatch stays
            # within the max rung (no torn request across dispatches).
            assert all(b <= fake.max_rung for b in fake.dispatches)
            assert len(fake.dispatches) >= 2
        finally:
            bat.close()

    def test_oversized_request_chunks(self, rng):
        fake = _FakeScorer()
        bat = ServeBatcher(fake, max_batch_wait_ms=1.0)
        try:
            ids, vals = _examples(rng, 200)  # > max rung
            out = bat.score(ids, vals, timeout=10)
            np.testing.assert_allclose(out, vals.sum(axis=1))
        finally:
            bat.close()

    def test_closed_batcher_rejects_and_fails_pending(self, rng):
        fake = _FakeScorer()
        bat = ServeBatcher(fake, max_batch_wait_ms=1.0)
        bat.close()
        ids, vals = _examples(rng, 2)
        with pytest.raises(RuntimeError):
            bat.submit(ids, vals)

    def test_batch_fill_accounting(self, rng):
        fake = _FakeScorer()
        tel = obs.Telemetry()
        bat = ServeBatcher(fake, max_batch_wait_ms=0.0, telemetry=tel)
        try:
            ids, vals = _examples(rng, 32)  # exactly the small rung
            bat.score(ids, vals, timeout=10)
            assert bat.batch_fill == pytest.approx(1.0)
            snap = tel.snapshot()
            assert snap["counters"]["serve.examples"] == 32
            assert snap["counters"]["serve.batches"] == 1
            assert snap["timers"]["serve.latency"]["count"] == 1
            assert "p99_ms" in snap["timers"]["serve.latency"]
        finally:
            bat.close()


# ----------------------------------------------------------------------
# hot swap
# ----------------------------------------------------------------------


class TestHotSwap:
    def test_swap_mid_traffic_never_torn(self, rng):
        """Concurrent traffic across a swap sees only old-table or
        new-table scores — never a mix — and no request fails."""
        cfg = _cfg_mem()
        pa, pb = _params(cfg, seed=0), _params(cfg, seed=1)
        tel = obs.Telemetry()
        sc = FixedShapeScorer(cfg, pa, telemetry=tel)
        sc.warmup()
        ids, vals = _examples(rng, 8)
        ref_a = sc.score(ids, vals)
        bat = ServeBatcher(sc, max_batch_wait_ms=0.5, telemetry=tel)
        try:
            # Compute the post-swap reference on a SEPARATE scorer so
            # the serving one only ever sees the swap itself.
            ref_b = FixedShapeScorer(cfg, pb).score(ids, vals)
            assert not np.array_equal(ref_a, ref_b)
            stop = threading.Event()
            seen: list = []
            errors: list = []

            def traffic():
                while not stop.is_set():
                    try:
                        seen.append(bat.score(ids, vals, timeout=10))
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return

            threads = [
                threading.Thread(target=traffic) for _ in range(3)
            ]
            for t in threads:
                t.start()
            time.sleep(0.15)
            sc.swap(
                fm.FmParams(*[np.asarray(x) for x in pb]), step=7
            )
            time.sleep(0.15)
            stop.set()
            for t in threads:
                t.join()
            assert not errors
            assert len(seen) > 4
            n_a = n_b = 0
            for s in seen:
                if np.array_equal(s, ref_a):
                    n_a += 1
                elif np.array_equal(s, ref_b):
                    n_b += 1
                else:
                    pytest.fail("a served microbatch mixed old and "
                                "new tables (torn swap)")
            assert n_b >= 1  # the swap actually took effect
            assert sc.steady_compiles == 0  # swap never recompiles
            assert sc.step == 7
            assert tel.snapshot()["counters"]["serve.swaps"] == 1
        finally:
            bat.close()

    def test_manifest_watcher_swaps(self, trained):
        """checkpoint.save republishing the manifest drives a watcher
        swap; the reloaded params change served scores."""
        tmp_path, cfg = trained
        fmt, step0, model = load_model(cfg)
        assert fmt == "dense"
        sc = make_scorer(cfg)
        sc.warmup()
        man = checkpoint.read_manifest(cfg.model_file)
        assert man is not None and man["step"] == step0
        watcher = CheckpointWatcher(cfg, sc, poll_secs=0.05)
        try:
            new_params = _params(cfg, seed=9)
            checkpoint.save(
                cfg.model_file, step0 + 100,
                fm.FmParams(*[np.asarray(x) for x in new_params]),
            )
            deadline = time.time() + 10
            while time.time() < deadline and sc.step != step0 + 100:
                time.sleep(0.05)
            assert sc.step == step0 + 100
            assert sc.steady_compiles == 0
        finally:
            watcher.close()
            # Restore the original checkpoint for the other tests.
            checkpoint.save(
                cfg.model_file, step0,
                fm.FmParams(*[np.asarray(x) for x in model]),
            )


# ----------------------------------------------------------------------
# end-to-end: HTTP server vs offline predict (bitwise), observability
# ----------------------------------------------------------------------


class TestEndToEnd:
    def test_served_scores_bitwise_identical_to_predict(self, trained):
        tmp_path, cfg = trained
        n = predict(cfg)
        offline = open(cfg.score_path).read().splitlines()
        assert len(offline) == n == 256
        handle = serve(cfg, port=0)
        try:
            lines = open(cfg.predict_files[0]).read()
            req = urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/score",
                data=lines.encode(), method="POST",
            )
            served = urllib.request.urlopen(
                req, timeout=60
            ).read().decode().splitlines()
            assert served == offline  # bitwise at full %.6f precision
            # Steady-state serving performed ZERO compiles: traffic
            # only ever hit precompiled ladder rungs.
            assert handle.scorer.steady_compiles == 0
            # Observability surface: tffm_serve_* series on /metrics,
            # the serve block on /status.
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{handle.port}/metrics", timeout=10
            ).read().decode()
            for series in ("tffm_counter_serve_requests_total",
                           "tffm_counter_serve_examples_total",
                           "tffm_timer_serve_latency_p99_ms",
                           "tffm_gauge_serve_batch_fill",
                           "tffm_timer_serve_compile_count",
                           # The serve record block renders too — the
                           # alertable scalars with no raw-instrument
                           # equivalent (qps, steady_compiles).
                           "tffm_serve_qps",
                           "tffm_serve_steady_compiles"):
                assert series in metrics, f"missing {series}"
            status = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{handle.port}/status", timeout=10
            ).read())
            assert status["record"] == "status"
            blk = status["serve"]
            assert blk["examples"] == 256
            assert blk["steady_compiles"] == 0
            assert blk["qps"] > 0
            assert "p99_ms" in blk
        finally:
            handle.close()

    def test_label_less_lines_accepted(self, trained):
        tmp_path, cfg = trained
        labeled = "1 5:0.5 9:0.25\n"
        bare = "5:0.5 9:0.25\n"
        ids_a, vals_a, _, na, _ = parse_request(labeled, cfg)
        ids_b, vals_b, _, nb, _ = parse_request(bare, cfg)
        assert na == nb == 1
        np.testing.assert_array_equal(ids_a, ids_b)
        np.testing.assert_array_equal(vals_a, vals_b)

    def test_truncation_counted(self, trained):
        """A request wider than max_features is a data-integrity event
        (the example scores as a DIFFERENT example) — parse_request
        reports the dropped occurrences instead of silently eating
        them."""
        tmp_path, cfg = trained  # max_features = 4
        wide = "0 " + " ".join(f"{i}:0.5" for i in range(7)) + "\n"
        ids, vals, _, n, truncated = parse_request(wide, cfg)
        assert n == 1
        assert truncated == 3
        assert (vals[0] != 0).sum() == cfg.max_features

    def test_malformed_line_rejected(self, trained):
        tmp_path, cfg = trained
        with pytest.raises(ValueError, match="line 1"):
            parse_request("not a libsvm line at:all:really:no\n", cfg)

    def test_missing_content_length_rejected(self, trained):
        """A body the handler cannot measure (chunked encoding) must be
        refused, not silently answered with zero scores."""
        import socket

        tmp_path, cfg = trained
        handle = serve(cfg, port=0)
        try:
            s = socket.create_connection(
                ("127.0.0.1", handle.port), timeout=10
            )
            s.sendall(
                b"POST /score HTTP/1.1\r\nHost: x\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
            )
            status_line = s.recv(4096).split(b"\r\n", 1)[0]
            s.close()
            assert b"411" in status_line
        finally:
            handle.close()

    def test_last_line_without_trailing_newline_is_scored(
        self, trained
    ):
        """The framing contract (SERVING.md): one example per
        non-blank LINE, and a final line without a trailing newline is
        still a line — ISSUE 12 flagged this as a potential
        silent-drop off-by-one, so it is pinned both at the parser and
        over the socket."""
        tmp_path, cfg = trained
        with_nl = "1 5:0.5 9:0.25\n0 3:1\n"
        without_nl = "1 5:0.5 9:0.25\n0 3:1"
        ids_a, vals_a, _, na, _ = parse_request(with_nl, cfg)
        ids_b, vals_b, _, nb, _ = parse_request(without_nl, cfg)
        assert na == nb == 2, (
            "a request whose last line lacks the trailing newline "
            "dropped an example"
        )
        np.testing.assert_array_equal(ids_a, ids_b)
        handle = serve(cfg, port=0)
        try:
            scores = []
            for body in (with_nl, without_nl):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{handle.port}/score",
                    data=body.encode(), method="POST",
                )
                scores.append(
                    urllib.request.urlopen(req, timeout=30).read()
                )
            assert scores[0] == scores[1]
            assert len(scores[0].splitlines()) == 2
        finally:
            handle.close()

    def test_binary_transport_bitwise_equals_text(self, trained):
        """/score_bin == /score bitwise for the same examples — both
        directly and proxied through a router mounted over the live
        replica — and the binary decode is accounted in its own
        serve.parse_bin timer."""
        tmp_path, cfg = trained
        handle = serve(cfg, port=0)
        router = None
        try:
            text = open(cfg.predict_files[0]).read()
            req = urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/score",
                data=text.encode(), method="POST",
            )
            text_scores = urllib.request.urlopen(
                req, timeout=60
            ).read().decode().splitlines()
            ids, vals, fields, n, _ = parse_request(text, cfg)
            frame = wire.encode_bin_request(ids, vals)
            req = urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/score_bin",
                data=frame, method="POST",
            )
            raw = urllib.request.urlopen(req, timeout=60).read()
            bin_scores = [
                f"{s:.6f}" for s in wire.decode_bin_response(raw)
            ]
            assert bin_scores == text_scores
            # Through a router over this live replica: still bitwise.
            router = ServeRouter(
                0, [Replica(0, "127.0.0.1", handle.port)], cfg,
            )
            raw = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{router.port}/score_bin",
                data=frame, method="POST",
            ), timeout=60).read()
            routed_scores = [
                f"{s:.6f}" for s in wire.decode_bin_response(raw)
            ]
            assert routed_scores == text_scores
            blk = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{handle.port}/status", timeout=10
            ).read())["serve"]
            assert "parse_bin_p50_ms" in blk
            assert "inflight" in blk
        finally:
            if router is not None:
                router.close()
            handle.close()

    def test_transport_knob_gates_endpoints(self, trained):
        import dataclasses

        tmp_path, cfg = trained
        handle = serve(
            dataclasses.replace(cfg, serve_transport="text"), port=0
        )
        try:
            frame = wire.encode_bin_request(
                np.zeros((1, 4), np.int32), np.ones((1, 4), np.float32)
            )
            req = urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/score_bin",
                data=frame, method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=30)
            assert exc.value.code == 404
            assert b"disabled" in exc.value.read()
        finally:
            handle.close()

    def test_malformed_bin_frame_rejected(self, trained):
        import struct

        tmp_path, cfg = trained
        handle = serve(cfg, port=0)
        try:
            for bad in (b"", b"XXXX" + b"\0" * 9,
                        wire.encode_bin_request(
                            np.zeros((2, 4), np.int32),
                            np.ones((2, 4), np.float32),
                        )[:-3],
                        # n of billions over an f=0 header: the length
                        # check must not be vacuous (a 13-byte body
                        # must never reach an [n, F] allocation).
                        struct.pack("<4sIIB", b"TFB1", 2**31, 0, 0)):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{handle.port}/score_bin",
                    data=bad, method="POST",
                )
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(req, timeout=30)
                assert exc.value.code == 400
                exc.value.read()
        finally:
            handle.close()

    def test_admin_reload_promote_rollback(self, trained, rng):
        """The canary swap surface on a REAL scorer: only
        /reload?keep_prev=1 (the router's canary reload) retains the
        replaced params for /rollback; a plain /reload leaves no
        window (a stray admin call must neither pin a second table
        nor make the model flippable), and /promote closes it."""
        tmp_path, cfg = trained
        fmt, step0, model = load_model(cfg)
        handle = serve(cfg, port=0)
        base = f"http://127.0.0.1:{handle.port}"
        ids, vals = _examples(rng, 8)
        try:
            ref_old = handle.scorer.score(ids, vals)
            new_params = _params(cfg, seed=21)
            checkpoint.save(
                cfg.model_file, step0 + 50,
                fm.FmParams(*[np.asarray(x) for x in new_params]),
            )
            doc = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/reload?keep_prev=1", data=b"",
                    method="POST",
                ), timeout=60,
            ).read())
            assert doc["step"] == step0 + 50
            ref_new = handle.scorer.score(ids, vals)
            assert not np.array_equal(ref_old, ref_new)
            # A RETRIED keep_prev reload (a canary check that died
            # between reload and verdict) must anchor, not clobber,
            # the rollback target.
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/reload?keep_prev=1", data=b"", method="POST"
            ), timeout=60).read()
            # Rollback restores the exact ORIGINAL params.
            doc = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/rollback", data=b"", method="POST"
                ), timeout=60,
            ).read())
            assert doc["step"] == step0
            np.testing.assert_array_equal(
                handle.scorer.score(ids, vals), ref_old
            )
            # A second rollback has nothing to restore -> 409.
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/rollback", data=b"", method="POST"
                ), timeout=60)
            assert exc.value.code == 409
            exc.value.read()
            # A PLAIN reload opens no window at all: rollback 409s
            # and the new params stay.
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/reload", data=b"", method="POST"
            ), timeout=60).read()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/rollback", data=b"", method="POST"
                ), timeout=60)
            assert exc.value.code == 409
            exc.value.read()
            np.testing.assert_array_equal(
                handle.scorer.score(ids, vals), ref_new
            )
            # keep_prev reload + PROMOTE: the window closes again.
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/reload?keep_prev=1", data=b"", method="POST"
            ), timeout=60).read()
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/promote", data=b"", method="POST"
            ), timeout=60).read()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/rollback", data=b"", method="POST"
                ), timeout=60)
            assert exc.value.code == 409
            exc.value.read()
            assert handle.scorer.steady_compiles == 0
        finally:
            handle.close()
            # Restore the original checkpoint for the other tests.
            checkpoint.save(
                cfg.model_file, step0,
                fm.FmParams(*[np.asarray(x) for x in model]),
            )

    def test_serve_stream_and_report_compat(self, trained, tmp_path):
        """A serve run's metrics stream carries the serve block;
        tools/report.py --compare flattens serve.* keys and a training
        stream contributes none (back-compat n/a)."""
        import os
        import sys

        tools = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        )
        if tools not in sys.path:
            sys.path.insert(0, tools)
        import report

        _, cfg = trained
        stream = tmp_path / "serve_metrics.jsonl"
        import dataclasses
        scfg = dataclasses.replace(cfg, metrics_file=str(stream))
        handle = serve(scfg, port=0)
        try:
            lines = open(cfg.predict_files[0]).read()
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/score",
                data=lines.encode(), method="POST",
            ), timeout=60).read()
        finally:
            handle.close()
        recs = [json.loads(l) for l in open(stream)]
        kinds = {r["record"] for r in recs}
        assert "run_header" in kinds and "final" in kinds
        header = next(r for r in recs if r["record"] == "run_header")
        assert header["mode"] == "serve"
        # ISSUE 16: the accept-path shape is reconstructable from any
        # metrics stream (KD discipline for the new front-end knobs).
        assert header["serve_parse_mode"] == scfg.serve_parse_mode
        assert header["serve_http_threads"] == scfg.serve_http_threads
        assert (
            header["serve_http_acceptors"] == scfg.serve_http_acceptors
        )
        assert header["serve_request_queue_size"] >= 1
        final = next(r for r in recs if r["record"] == "final")
        assert final["serve"]["requests"] >= 1
        flat = report._comparable_metrics(str(stream))
        assert flat["serve.requests"] >= 1
        assert "serve.qps" in flat
        assert report._direction("serve.p99_ms") == "low"
        assert report._direction("serve_qps") == "high"
        assert report._direction("serve_batch_fill") == "high"
        assert report._direction("serve_steady_compiles") == "low"


# ----------------------------------------------------------------------
# tiered overlay predict/serve (direction-2 residue)
# ----------------------------------------------------------------------


class TestOverlay:
    @pytest.fixture()
    def overlay_cfg(self, tmp_path, rng, monkeypatch):
        """A tiered VIRTUAL run at tiny V: its checkpoint is the
        sparse overlay format (tiered.npz), no dense dirs."""
        monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)
        _write_data(tmp_path / "train.libsvm", rng)
        cfg = _cfg(tmp_path, "m", table_tiering="on", hot_rows=192)
        Trainer(cfg).train()
        assert checkpoint.exists_tiered(cfg.model_file)
        assert not checkpoint.exists(cfg.model_file)
        return cfg

    def test_overlay_predict_writes_scores(self, overlay_cfg):
        """The tiered-overlay refusal is gone: predict scores straight
        from tiered.npz via the compact per-batch remap."""
        n = predict(overlay_cfg)
        scores = np.loadtxt(overlay_cfg.score_path)
        assert n == len(scores) == 256
        assert np.all((scores > 0) & (scores < 1))

    def test_overlay_matches_full_table_scoring(self, overlay_cfg, rng):
        """Compact-remap scoring == scoring against the fully
        materialized logical table (the dense-parity oracle)."""
        fmt, step, (w0, store) = load_model(overlay_cfg)
        assert fmt == "tiered" and step == 8
        sc = make_scorer(overlay_cfg)
        assert isinstance(sc, OverlayScorer)
        ids, vals = _examples(rng, 50)
        got = sc.score(ids, vals)
        table = store.gather(np.arange(V))
        ref = np.asarray(jax.nn.sigmoid(fm.fm_scores(
            fm.FmParams(
                w0=jax.numpy.float32(w0),
                table=jax.numpy.asarray(table),
            ),
            ids, vals, None, factor_num=4, field_num=0,
        )))
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-6)

    def test_overlay_parity_vs_dense_format(self, tmp_path, rng):
        """A tiered EXACT run saves the DENSE format; predict from it
        must equal predict from an identical dense run — the overlay/
        dense interchange contract on the scoring side."""
        _write_data(tmp_path / "train.libsvm", rng)
        cfg_d = _cfg(tmp_path, "dense")
        Trainer(cfg_d).train()
        predict(cfg_d)
        dense_scores = open(cfg_d.score_path).read()
        cfg_t = _cfg(
            tmp_path, "tiered", table_tiering="on", hot_rows=192,
            score_path=str(tmp_path / "scores_t.txt"),
        )
        Trainer(cfg_t).train()
        assert checkpoint.exists(cfg_t.model_file)  # dense format
        predict(cfg_t)
        assert open(cfg_t.score_path).read() == dense_scores

    def test_overlay_descriptor_mismatch_refused(self, overlay_cfg):
        import dataclasses

        bad = dataclasses.replace(overlay_cfg, seed=99)
        with pytest.raises(ValueError, match="different init"):
            load_model(bad)

    def test_overlay_serve_deterministic_and_zero_steady(
        self, overlay_cfg, rng
    ):
        tel = obs.Telemetry()
        sc = make_scorer(overlay_cfg, telemetry=tel)
        sc.warmup()
        ids, vals = _examples(rng, 40)
        a = sc.score(ids, vals)
        # The first >8-unique-ids chunk lazily compiles a larger
        # compact-table bucket — EXPECTED by design, so it must not
        # read as the "shape escaped the ladder" latency-cliff signal.
        assert sc.steady_compiles == 0
        before = sc.compiles
        b = sc.score(ids, vals)
        np.testing.assert_array_equal(a, b)
        # Repeat traffic at a seen (rung, bucket) shape: no compile.
        assert sc.compiles == before
        assert tel.snapshot()["counters"].get(
            "serve.recompiles_unexpected", 0
        ) == 0


# ----------------------------------------------------------------------
# offline predict through the ladder
# ----------------------------------------------------------------------


class TestOfflinePredict:
    def test_predict_emits_accounted_compiles(self, trained, tmp_path):
        tmp, cfg = trained
        import dataclasses

        stream = tmp_path / "predict_metrics.jsonl"
        pcfg = dataclasses.replace(
            cfg, metrics_file=str(stream),
            score_path=str(tmp_path / "s.txt"),
        )
        n = predict(pcfg)
        assert n == 256
        compiles = [
            json.loads(l) for l in open(stream)
            if json.loads(l).get("record") == "compile"
        ]
        assert compiles, "predict compiles must surface as records"
        assert all(c["where"] == "serve" for c in compiles)
        # Every shape predict scores is in its ladder (batch_size is an
        # extra rung): nothing unexpected.
        assert all(c["expected"] for c in compiles)
