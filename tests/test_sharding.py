"""Sharded-training tests on the 8-device virtual CPU mesh (SURVEY.md §4).

The key property: a (data x model)-sharded train step computes EXACTLY the
same math as the single-device step — GSPMD only changes where the compute
runs. This is the sync-DP upgrade over the reference's async PS training.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train.loop import Trainer


def _batch(rng, cfg, batch_size):
    return Batch(
        labels=rng.integers(0, 2, size=(batch_size,)).astype(np.float32),
        ids=rng.integers(0, cfg.vocabulary_size,
                         size=(batch_size, cfg.max_features)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0,
                         size=(batch_size, cfg.max_features)).astype(np.float32),
        fields=np.zeros((batch_size, cfg.max_features), np.int32),
        weights=np.ones((batch_size,), np.float32),
    )


def _cfg(tmp_path, **kw):
    defaults = dict(
        vocabulary_size=256, factor_num=4, max_features=8, batch_size=64,
        model_file=str(tmp_path / "model"), log_steps=0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def test_eight_device_mesh_available():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"


@pytest.mark.parametrize("d,m", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_mesh_shapes(tmp_path, d, m):
    cfg = _cfg(tmp_path, mesh_data=d, mesh_model=m)
    mesh = mesh_lib.make_mesh(cfg)
    assert mesh.shape == {"data": d, "model": m}


def test_table_row_sharded(tmp_path):
    cfg = _cfg(tmp_path, mesh_data=2, mesh_model=4)
    trainer = Trainer(cfg)
    table = trainer.state.params.table
    # 256 rows over 4 model shards -> 64 rows per shard.
    shard_shapes = {s.data.shape for s in table.addressable_shards}
    assert shard_shapes == {(64, 5)}
    # Optimizer accumulator shares the layout (never gathered).
    accs = [
        leaf for leaf in jax.tree.leaves(trainer.state.opt_state)
        if getattr(leaf, "shape", None) == table.shape
    ]
    assert accs, "expected a table-shaped accumulator"
    for acc in accs:
        assert {s.data.shape for s in acc.addressable_shards} == {(64, 5)}


@pytest.mark.parametrize("d,m", [(4, 2), (1, 8), (8, 1)])
def test_sharded_step_matches_single_device(tmp_path, d, m):
    """Bitwise-level parity between sharded and single-device training."""
    rng = np.random.default_rng(0)
    cfg1 = _cfg(tmp_path / "a", mesh_data=1, mesh_model=1)
    cfgN = _cfg(tmp_path / "b", mesh_data=d, mesh_model=m)
    batches = [_batch(rng, cfg1, cfg1.batch_size) for _ in range(3)]

    t1 = Trainer(cfg1, mesh=mesh_lib.make_mesh(cfg1, jax.devices()[:1]))
    tN = Trainer(cfgN)
    for b in batches:
        t1.state = t1._train_step(t1.state, t1._put(b))
        tN.state = tN._train_step(tN.state, tN._put(b))

    np.testing.assert_allclose(
        np.asarray(t1.state.params.table), np.asarray(tN.state.params.table),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        float(t1.state.metrics.loss_sum), float(tN.state.metrics.loss_sum),
        rtol=1e-5,
    )


def test_sharded_ffm_step(tmp_path):
    cfg = _cfg(tmp_path, mesh_data=4, mesh_model=2, field_num=4, batch_size=32)
    trainer = Trainer(cfg)
    rng = np.random.default_rng(1)
    b = _batch(rng, cfg, cfg.batch_size)
    b = b._replace(fields=rng.integers(0, 4, size=b.fields.shape).astype(np.int32))
    state = trainer._train_step(trainer.state, trainer._put(b))
    assert int(state.step) == 1
    assert np.isfinite(float(state.metrics.loss_sum))


def test_dryrun_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1024,)
