"""Tiered embedding table (ISSUE 6 tentpole): device-resident hot rows
over a host-RAM cold store, occupancy-driven migration.

The pinned guarantees:

  * parity — tiered training is ELEMENT-WISE IDENTICAL to dense training
    at small V (merged logical table, loss, auc), for Adagrad and FTRL,
    across K-step dispatch, eviction churn, and multi-epoch streams;
  * resume — checkpoints are tier-layout-independent: dense <-> tiered
    and tiered(H1) -> tiered(H2) warm starts continue bit-identically,
    including mid-epoch positions; the huge-V sparse overlay format
    round-trips exactly;
  * mechanics — LRU eviction never evicts the current super-batch's
    rows, the pending write-back ledger serves re-fetched rows, OOR ids
    keep the dense path's silently-dropped-update contract, and a
    too-small hot table fails loudly.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.data.pipeline import stack_batches
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.train import checkpoint, tiered
from fast_tffm_tpu.train.loop import Trainer

V = 256


def _write_data(path, rng, lines=256, vocab=V):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5 "
                f"{rng.integers(0, vocab)}:0.25\n"
            )


def _cfg(tmp_path, model, **kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / model),
        epoch_num=2, log_steps=0, thread_num=1, seed=3,
        steps_per_dispatch=2,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _dense_table(model_file, cfg):
    from functools import partial

    tmpl = jax.eval_shape(
        partial(fm.init_params, cfg=cfg), jax.random.PRNGKey(0)
    )
    params, step = checkpoint.restore_params(model_file, tmpl)
    return np.asarray(params[1]), np.asarray(params[0]), step


def _merged(trainer):
    return trainer.tiered.merged_dense(trainer._hot_host_tables())


# ------------------------------------------------------------- parity


@pytest.mark.parametrize("optimizer", ["adagrad", "ftrl"])
@pytest.mark.parametrize("hot_rows", [V, 160])
def test_tiered_matches_dense_elementwise(tmp_path, rng, optimizer,
                                          hot_rows):
    """Tiered == dense: merged logical table bitwise, loss/auc exact —
    with (hot_rows < V forces eviction churn) and without evictions."""
    _write_data(tmp_path / "train.libsvm", rng)
    rd = Trainer(_cfg(tmp_path, "dense", optimizer=optimizer)).train()
    t = Trainer(_cfg(
        tmp_path, "tiered", optimizer=optimizer,
        table_tiering="on", hot_rows=hot_rows,
    ))
    rt = t.train()
    assert rt["train"]["loss"] == rd["train"]["loss"]
    assert rt["train"]["auc"] == rd["train"]["auc"]
    d_table, d_w0, _ = _dense_table(str(tmp_path / "dense"),
                                    _cfg(tmp_path, "x"))
    merged = _merged(t)
    np.testing.assert_array_equal(merged[0], d_table)
    np.testing.assert_array_equal(
        np.asarray(t.state.params.w0), d_w0
    )
    snap = rt["train"]["tiered"]
    if hot_rows < V:
        assert snap["rows_evicted"] > 0  # churn actually exercised
    assert snap["hit_occurrences"] + snap["miss_occurrences"] > 0
    assert 0.0 < snap["hot_hit_frac"] < 1.0


def test_tiered_opt_state_matches_dense(tmp_path, rng):
    """The optimizer slot tables migrate with the params: merged adagrad
    accumulators equal the dense run's bitwise.  save_steps exercises
    the MID-RUN checkpoint path (merge while plans are in flight)."""
    _write_data(tmp_path / "train.libsvm", rng)
    Trainer(_cfg(tmp_path, "dense", save_steps=4)).train()
    t = Trainer(_cfg(
        tmp_path, "tiered", table_tiering="on", hot_rows=160,
        save_steps=4,
    ))
    t.train()
    cfg = _cfg(tmp_path, "x")
    from functools import partial

    tmpl = jax.eval_shape(
        partial(fm.init_params, cfg=cfg), jax.random.PRNGKey(0)
    )
    from fast_tffm_tpu.train import sparse as sparse_lib

    opt_tmpl = jax.eval_shape(
        partial(sparse_lib.init_sparse_opt_state, cfg), tmpl
    )
    opt_np = checkpoint.restore_opt(str(tmp_path / "dense"), opt_tmpl)
    merged = _merged(t)
    np.testing.assert_array_equal(merged[1], np.asarray(opt_np.acc.table))
    np.testing.assert_array_equal(
        np.asarray(t.state.opt_state.acc.w0), np.asarray(opt_np.acc.w0)
    )


def test_tiered_metrics_and_validation_match_dense(tmp_path, rng):
    """Validation runs against the MERGED logical table — cold rows
    included — and matches the dense run exactly."""
    _write_data(tmp_path / "train.libsvm", rng)
    _write_data(tmp_path / "valid.libsvm", np.random.default_rng(9),
                lines=64)
    kw = dict(validation_files=[str(tmp_path / "valid.libsvm")])
    rd = Trainer(_cfg(tmp_path, "dense", **kw)).train()
    rt = Trainer(_cfg(
        tmp_path, "tiered", table_tiering="on", hot_rows=160, **kw
    )).train()
    assert rt["validation"]["loss"] == rd["validation"]["loss"]
    assert rt["validation"]["auc"] == rd["validation"]["auc"]


# ------------------------------------------------------------- resume


@pytest.mark.parametrize("optimizer", ["adagrad", "ftrl"])
def test_resume_across_tier_layout_change(tmp_path, rng, optimizer):
    """Checkpoints are tier-layout-independent: dense -> tiered(H1) ->
    tiered(H2) -> dense warm-start chains all land on the same params
    as an all-dense chain (each train() on a completed checkpoint
    trains epoch_num fresh epochs)."""
    _write_data(tmp_path / "train.libsvm", rng)

    def chain(model, layouts):
        for layout in layouts:
            kw = dict(optimizer=optimizer, epoch_num=1, model_file=str(
                tmp_path / model
            ))
            if layout is not None:
                kw.update(table_tiering="on", hot_rows=layout)
            t = Trainer(_cfg(tmp_path, model, **kw))
            t.train()
        return t

    chain("all_dense", [None, None, None])
    t = chain("mixed", [None, 192, 160])  # dense -> H=192 -> H=160
    d_table, d_w0, d_step = _dense_table(
        str(tmp_path / "all_dense"), _cfg(tmp_path, "x")
    )
    m_table, m_w0, m_step = _dense_table(
        str(tmp_path / "mixed"), _cfg(tmp_path, "x")
    )
    assert m_step == d_step == 24  # 3 chained 1-epoch runs, 8 steps each
    np.testing.assert_array_equal(m_table, d_table)
    np.testing.assert_array_equal(m_w0, d_w0)
    # ... and the final tiered trainer's own merged view agrees.
    np.testing.assert_array_equal(_merged(t)[0], d_table)


def test_tiered_mid_epoch_resume_matches_dense(tmp_path, rng):
    """A mid-epoch interruption resumed under a DIFFERENT tier layout
    retrains the same remaining batches as the dense resume."""
    from tests.conftest import set_data_state

    _write_data(tmp_path / "train.libsvm", rng)
    for model, kw1, kw2 in (
        ("dense", {}, {}),
        ("tiered", dict(table_tiering="on", hot_rows=192),
         dict(table_tiering="on", hot_rows=160)),
    ):
        cfg1 = _cfg(tmp_path, model, epoch_num=1, **kw1)
        Trainer(cfg1).train()
        set_data_state(cfg1.model_file, epoch=0, batches_done=4)
        t2 = Trainer(_cfg(tmp_path, model, epoch_num=1, **kw2))
        assert t2._restored_step == 8
        r2 = t2.train()
        assert r2["train"]["steps"] == 4  # only the remaining batches
    d_table, _, d_step = _dense_table(str(tmp_path / "dense"),
                                      _cfg(tmp_path, "x"))
    t_table, _, t_step = _dense_table(str(tmp_path / "tiered"),
                                      _cfg(tmp_path, "x"))
    assert t_step == d_step == 12
    np.testing.assert_array_equal(t_table, d_table)


def test_overlay_checkpoint_roundtrip(tmp_path, rng, monkeypatch):
    """The sparse overlay format (huge-V tiered checkpoints): forcing
    the virtual cold store at tiny V, a save -> restore across a
    hot_rows change continues training deterministically, and the
    overlay supersedes any stale dense checkpoint dirs."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)  # force virtual
    _write_data(tmp_path / "train.libsvm", rng)
    cfg1 = _cfg(tmp_path, "m", epoch_num=1, table_tiering="on",
                hot_rows=192)
    t1 = Trainer(cfg1)
    r1 = t1.train()
    assert checkpoint.exists_tiered(cfg1.model_file)
    assert not checkpoint.exists(cfg1.model_file)  # dense dirs removed
    step, scalars, stores = checkpoint.restore_tiered(cfg1.model_file)
    assert step == 8 and "w0" in scalars and "table" in stores
    assert len(stores["table"]["ids"]) > 0
    # Resume with a different hot size: continues from the overlay.
    t2 = Trainer(_cfg(tmp_path, "m", epoch_num=1, table_tiering="on",
                      hot_rows=160))
    assert t2._restored_step == 8
    r2 = t2.train()
    assert r2["train"]["steps"] == 8
    # Reference: the same two-run chain through the EXACT (dense-backed)
    # store must produce different bits (virtual init differs by design)
    # but the virtual chain must agree with ITSELF when replayed.
    t3 = Trainer(_cfg(tmp_path, "m2", epoch_num=1, table_tiering="on",
                      hot_rows=192))
    t3.train()
    t4 = Trainer(_cfg(tmp_path, "m2", epoch_num=1, table_tiering="on",
                      hot_rows=160))
    t4.train()
    a = checkpoint.restore_tiered(str(tmp_path / "m"))
    b = checkpoint.restore_tiered(str(tmp_path / "m2"))
    np.testing.assert_array_equal(a[2]["table"]["ids"],
                                  b[2]["table"]["ids"])
    np.testing.assert_array_equal(a[2]["table"]["rows"],
                                  b[2]["table"]["rows"])


def test_virtual_store_validation_matches_manual_scoring(
    tmp_path, rng, monkeypatch
):
    """Huge-V (virtual cold store) evaluation: no dense merge exists,
    so eval scores each batch against a compact per-batch table — and
    the result must equal scoring with the full reconstructed table."""
    from fast_tffm_tpu.data.pipeline import BatchPipeline
    from fast_tffm_tpu.parallel import mesh as mesh_lib
    from fast_tffm_tpu.train.loop import (
        MetricState, _finalize_metrics, make_eval_step,
    )

    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)  # force virtual
    _write_data(tmp_path / "train.libsvm", rng)
    _write_data(tmp_path / "valid.libsvm", np.random.default_rng(9),
                lines=64)
    cfg = _cfg(tmp_path, "m", table_tiering="on", hot_rows=192,
               validation_files=[str(tmp_path / "valid.libsvm")])
    t = Trainer(cfg)
    r = t.train()
    # Reference: reconstruct the full logical table row-by-row from the
    # same cold store (V is tiny here) and score the stream directly.
    t.tiered.sync_from_device(t._hot_host_tables())
    table = t.tiered.gather_logical(np.arange(V, dtype=np.int64))
    step = jax.jit(make_eval_step(cfg))
    ms = MetricState.zeros()
    params = fm.FmParams(
        w0=np.asarray(t.state.params.w0), table=table
    )
    for batch in BatchPipeline(cfg.validation_files, cfg, epochs=1,
                               shuffle=False, ordered=True):
        ms = step(params, ms, mesh_lib.shard_batch(batch, t.mesh))
    expect = _finalize_metrics(ms, cfg.loss_type)
    assert r["validation"]["loss"] == expect["loss"]
    assert r["validation"]["auc"] == expect["auc"]


def test_dense_trainer_refuses_overlay_checkpoint(tmp_path, rng,
                                                  monkeypatch):
    """A dense trainer pointed at a tiered-overlay-only checkpoint must
    refuse loudly, not silently cold-start over it; and a dense save
    clears a stale overlay so precedence can't flip back."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)  # force overlay
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, "m", epoch_num=1, table_tiering="on",
               hot_rows=192)
    Trainer(cfg).train()
    assert checkpoint.exists_tiered(cfg.model_file)
    with pytest.raises(ValueError, match="tiered overlay"):
        Trainer(_cfg(tmp_path, "m", epoch_num=1))
    # BOTH formats present (crash-window debris) is ambiguous — the two
    # carry no shared freshness marker — so the dense path refuses too.
    import shutil

    cfg2 = _cfg(tmp_path, "m2", epoch_num=1)
    Trainer(cfg2).train()
    shutil.copy(f"{cfg.model_file}/tiered.npz",
                f"{cfg2.model_file}/tiered.npz")
    with pytest.raises(ValueError, match="tiered overlay"):
        Trainer(cfg2)
    # Clearing the debris restores the dense flow, and a dense save
    # leaves no overlay behind.
    checkpoint.clear_tiered(cfg2.model_file)
    Trainer(cfg2).train()
    assert checkpoint.exists(cfg2.model_file)
    assert not checkpoint.exists_tiered(cfg2.model_file)


def test_cold_store_tail_compaction_ordering():
    """Repeated scatters to overlapping ids: the newest write wins
    through the write tail, across compactions, and in export."""
    cfg = FmConfig(vocabulary_size=1 << 20, factor_num=2,
                   table_tiering="on", hot_rows=64, seed=7)
    store = tiered._virtual_store(cfg, "table")
    dim = cfg.embedding_dim
    ids = np.arange(10, dtype=np.int64)
    for round_ in range(5):
        store.scatter(ids, np.full((10, dim), float(round_), np.float32))
        np.testing.assert_array_equal(
            store.gather(ids), np.full((10, dim), float(round_))
        )
    store._compact()
    np.testing.assert_array_equal(
        store.gather(ids), np.full((10, dim), 4.0)
    )
    assert len(store._ids) == 10  # deduped, newest kept
    exp = store.export()
    np.testing.assert_array_equal(exp["ids"], ids)
    np.testing.assert_array_equal(exp["rows"], np.full((10, dim), 4.0))


def test_overlay_descriptor_mismatch_raises(tmp_path, rng, monkeypatch):
    """An overlay saved under a different seed must refuse to load: the
    non-materialized rows would silently regenerate differently."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)
    _write_data(tmp_path / "train.libsvm", rng)
    cfg1 = _cfg(tmp_path, "m", epoch_num=1, table_tiering="on",
                hot_rows=192)
    Trainer(cfg1).train()
    with pytest.raises(ValueError, match="different init"):
        Trainer(_cfg(tmp_path, "m", epoch_num=1, table_tiering="on",
                     hot_rows=192, seed=99))


# ------------------------------------------------------------- mechanics


def test_hot_rows_too_small_raises(tmp_path, rng):
    """A super-batch whose unique ids outgrow the hot table fails with
    an actionable error (surfaced through the prefetcher)."""
    _write_data(tmp_path / "train.libsvm", rng)
    t = Trainer(_cfg(tmp_path, "m", table_tiering="on", hot_rows=16))
    with pytest.raises(RuntimeError, match="hot_rows"):
        t.train()


def test_tiering_requires_sparse_path(tmp_path):
    with pytest.raises(ValueError, match="sparse update path"):
        Trainer(_cfg(tmp_path, "m", table_tiering="on", optimizer="adam"))


def test_plan_remap_and_oor_contract(rng):
    """TieredTable.plan unit semantics: remap is a bijection on present
    ids, padding id 0 stays mapped, and out-of-range ids map to the
    hot-table size (device scatter drops them — the dense contract)."""
    cfg = FmConfig(vocabulary_size=64, factor_num=2, max_features=4,
                   table_tiering="on", hot_rows=32)
    man = tiered.TieredTable(cfg)
    ids = np.array([[0, 5, 9, 5], [70, 9, 0, 63]], np.int32)  # 70 OOR
    new_ids, plan = man.plan(ids)
    assert new_ids.shape == ids.shape
    assert new_ids[1, 0] == 32  # OOR -> hot_rows (dropped on device)
    # bijection: equal logical ids -> equal slots, distinct -> distinct
    m = {}
    for lg, sl in zip(ids.reshape(-1), new_ids.reshape(-1)):
        if lg >= 64:
            continue
        assert m.setdefault(int(lg), int(sl)) == int(sl)
    assert len(set(m.values())) == len(m)
    assert plan.n_load == len(m)
    snap = man.snapshot()
    assert snap["oor_occurrences"] == 1
    assert snap["resident_rows"] == len(m)


def test_plan_lru_never_evicts_current_superbatch(rng):
    """Eviction picks least-recently-used slots and never a slot the
    current super-batch (or this plan's fresh loads) occupies."""
    cfg = FmConfig(vocabulary_size=64, factor_num=2, max_features=2,
                   table_tiering="on", hot_rows=8)
    man = tiered.TieredTable(cfg)
    _, p1 = man.plan(np.arange(0, 6, dtype=np.int32).reshape(1, -1))
    assert p1.n_evict == 0
    # 4 new ids: 2 fresh slots remain, 2 evictions — must come from
    # ids 0..5 (LRU), never from the new ids' own fresh slots.
    _, p2 = man.plan(np.arange(6, 10, dtype=np.int32).reshape(1, -1))
    assert p2.n_load == 4 and p2.n_evict == 2
    resident = {int(i) for i in man.id_of_slot if i >= 0}
    assert {6, 7, 8, 9} <= resident
    assert len(resident) == 8
    # Write-back entry exists for the evicted ids and a re-fetch is
    # served from it once the dispatch loop hands the rows over.
    evicted = {0, 1, 2, 3, 4, 5} - resident
    assert len(evicted) == 2
    rows = tuple(
        np.full((tiered._bucket(p2.n_evict), cfg.embedding_dim),
                7.5, np.float32)
        for _ in man.names
    )
    man.push_writeback(p2.plan_id, rows)
    eid = sorted(evicted)[0]
    _, p3 = man.plan(np.array([[eid, 6]], np.int32))
    assert p3.n_load == 1
    np.testing.assert_array_equal(
        p3.load_rows[0][0], np.full(cfg.embedding_dim, 7.5, np.float32)
    )


def test_cancel_waits_releases_blocked_writeback_wait():
    """A transfer thread blocked waiting for a write-back fill that will
    never come (the dispatch loop died) must be released by
    cancel_waits() — otherwise prefetcher.close()'s join deadlocks the
    whole shutdown path under nan_policy=halt / KeyboardInterrupt."""
    import threading
    import time as _time

    cfg = FmConfig(vocabulary_size=64, factor_num=2, max_features=2,
                   table_tiering="on", hot_rows=8)
    man = tiered.TieredTable(cfg)
    man.plan(np.arange(0, 6, dtype=np.int32).reshape(1, -1))
    _, p2 = man.plan(np.arange(6, 10, dtype=np.int32).reshape(1, -1))
    assert p2.n_evict == 2  # pending entry created, never filled
    evicted = sorted({0, 1, 2, 3, 4, 5}
                     - {int(i) for i in man.id_of_slot if i >= 0})
    outcome: list = []

    def refetch():
        try:
            man.plan(np.array([[evicted[0], 6]], np.int32))
            outcome.append("returned")
        except RuntimeError as e:
            outcome.append(str(e))

    worker = threading.Thread(target=refetch, daemon=True)
    worker.start()
    _time.sleep(0.2)
    assert worker.is_alive()  # blocked on the never-coming fill
    man.cancel_waits()
    worker.join(timeout=5)
    assert not worker.is_alive()
    assert outcome and "cancelled" in outcome[0]
    # reopen() re-arms the manager for the next run.
    man.reopen()
    assert man._cancelled is False


def test_cold_store_gather_scatter_roundtrip():
    """Virtual cold store: deterministic row init, sparse overlay
    read-your-writes, export/import roundtrip."""
    cfg = FmConfig(vocabulary_size=1 << 20, factor_num=4,
                   table_tiering="on", hot_rows=64, seed=11)
    import fast_tffm_tpu.train.tiered as tl

    store = tl._virtual_store(cfg, "table")
    ids = np.array([3, 999_999, 12345], np.int64)
    a = store.gather(ids)
    b = store.gather(ids)
    np.testing.assert_array_equal(a, b)  # deterministic init
    assert np.abs(a).max() <= cfg.init_value_range
    wrote = np.full((2, cfg.embedding_dim), 0.25, np.float32)
    store.scatter(ids[:2], wrote)
    got = store.gather(ids)
    np.testing.assert_array_equal(got[:2], wrote)
    np.testing.assert_array_equal(got[2], a[2])  # untouched row = init
    fresh = tl._virtual_store(cfg, "table")
    fresh.import_overlay(store.export())
    np.testing.assert_array_equal(fresh.gather(ids), got)
    assert store.nbytes < 1 << 12  # sparse: bytes track written rows


def test_run_header_and_results_carry_tiering(tmp_path, rng):
    """Observability: run_header names the tiering mode, heartbeat/final
    records and train results carry the hot/cold counters."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, "m", table_tiering="on", hot_rows=192,
               metrics_file=str(tmp_path / "metrics.jsonl"))
    r = Trainer(cfg).train()
    snap = r["train"]["tiered"]
    occ = snap["hit_occurrences"] + snap["miss_occurrences"]
    assert occ == 2 * 256 * 4  # 2 epochs x 256 lines x max_features
    assert snap["hot_hit_frac"] == pytest.approx(
        snap["hit_occurrences"] / occ, abs=1e-6
    )
    assert snap["rows_loaded"] >= snap["resident_rows"]
    recs = [json.loads(line) for line in
            open(tmp_path / "metrics.jsonl")]
    header = [x for x in recs if x["record"] == "run_header"][0]
    assert header["table_tiering"] == "on"
    assert header["hot_rows"] == 192
    final = [x for x in recs if x["record"] == "final"][0]
    assert final["tiered"]["hot_hit_frac"] == snap["hot_hit_frac"]
    # Logical (not hot-slot) occupancy in the health record.
    assert final["health"]["emb_rows_touched"] == snap["rows_seen"]


def test_staging_pool_disables_reuse_when_put_aliases():
    """The pre-existing hazard the tiered work exposed: on a backend
    where device_put ALIASES host memory (single-device CPU zero-copy),
    recycling staging buffers would rewrite in-flight super-batches.
    The pool must detect aliasing on first retire and stop recycling."""
    from fast_tffm_tpu.data.pipeline import _StagingPool

    pool = _StagingPool(1)
    rng = np.random.default_rng(0)

    def batch():
        return Batch(
            labels=rng.random(4, np.float32),
            ids=rng.integers(0, 8, (4, 2)).astype(np.int32),
            vals=rng.random((4, 2), np.float32),
            fields=np.zeros((4, 2), np.int32),
            weights=np.ones(4, np.float32),
        )

    group = [batch(), batch()]
    bufs = pool.acquire(group)
    stacked = stack_batches(group, out=bufs)
    # A single-device put on CPU aliases the host buffer.
    dev = jax.tree.map(
        lambda x: jax.device_put(x, jax.devices()[0]), stacked
    )
    aliased = any(
        np.shares_memory(np.asarray(d), h)
        for d, h in zip(jax.tree.leaves(dev), jax.tree.leaves(stacked))
    )
    pool.retire(dev, group, bufs)
    if aliased:
        assert pool._alias_mode is True
        # acquire must hand out FRESH buffers now, never bufs again.
        bufs2 = pool.acquire(group)
        assert bufs2.ids is not bufs.ids
    else:  # pragma: no cover - backend copied; contract already safe
        assert pool._alias_mode is False
