"""End-to-end batch tracing + training-health monitors (ISSUE 5).

Pins the tentpole guarantees:

  * the ``trace_file`` output is valid Chrome-trace (Perfetto-loadable)
    JSON, with spans from EVERY execution context of a
    ``parse_processes`` run — reader, SHM ring slot acquire, spawned
    parse workers (their spans ship back over the result messages),
    delivery, prefetcher stack/H2D, and the train loop's wait/dispatch;
  * super-batch ids correlate across the process boundary: every
    dispatched super-batch reconstructs a CONNECTED chain
    read -> ring slot -> parse -> deliver -> stack -> H2D -> dispatch
    (tools/report.py --trace is the reference chain-walker, and its
    merge output stays loadable);
  * ``trace_file`` unset = shared no-op tracer = bit-identical training;
  * the scan-carry health monitors detect an injected NaN under both
    ``nan_policy`` modes — ``halt`` raises within one dispatch of the
    poisoned one, ``warn`` finishes and reports the damage in the final
    record;
  * a crashed run's metrics stream still ends with a ``final`` record
    (exception type + partial counters) — the try/finally contract
    tools/report.py relies on;
  * tools/check_tier1.py (the marker audit bench.py preflights) and
    tools/report.py --compare behave.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train.loop import NonFiniteGradError, Trainer

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import check_tier1  # noqa: E402
import report  # noqa: E402


# ---------------------------------------------------------------------------
# Tracer unit behavior
# ---------------------------------------------------------------------------


class TestTracer:
    def test_span_records_complete_event_with_args(self):
        tr = obs.Tracer(enabled=True)
        with tr.span("work", args={"seq": 7}):
            pass
        evs = [e for e in tr.take() if e.get("ph") == "X"]
        assert len(evs) == 1
        ev = evs[0]
        assert ev["name"] == "work" and ev["args"] == {"seq": 7}
        for key in ("ts", "dur", "pid", "tid"):
            assert key in ev
        assert ev["dur"] >= 1  # zero-length spans stay visible

    def test_flow_events_bind_to_span(self):
        tr = obs.Tracer(enabled=True)
        with tr.span("stack", flow=("s", "sb3")):
            pass
        with tr.span("dispatch", flow=("f", "sb3")):
            pass
        evs = tr.take()
        flows = [e for e in evs if e.get("cat") == "tffm_flow"]
        assert [f["ph"] for f in flows] == ["s", "f"]
        assert all(f["id"] == "sb3" for f in flows)
        assert flows[1]["bp"] == "e"  # flow end binds to enclosing slice

    def test_disabled_tracer_is_noop(self):
        tr = obs.Tracer(enabled=False)
        with tr.span("x", args={"a": 1}):
            pass
        tr.point("y")
        tr.emit("z", 0.0, 1.0)
        tr.add_raw([{"ph": "X"}])
        assert tr.take() == []
        assert obs.NULL_TRACER.take() == []

    def test_add_raw_merges_shipped_events(self):
        worker = obs.Tracer(enabled=True, process_name="w")
        with worker.span("parse.batch", args={"seq": 1}):
            pass
        shipped = worker.take()
        parent = obs.Tracer(enabled=True)
        parent.add_raw(shipped)
        names = {e.get("name") for e in parent.take()}
        assert "parse.batch" in names and "process_name" in names

    def test_event_cap_drops_and_counts(self, tmp_path):
        tr = obs.Tracer(enabled=True, max_events=3)
        for i in range(10):
            tr.point(f"e{i}")
        path = str(tmp_path / "t.json")
        assert tr.dump(path) == 3
        doc = json.load(open(path))
        assert doc["otherData"]["dropped_events"] == 7

    def test_cap_overflow_warns_at_dump_and_exposes_count(
        self, tmp_path, caplog
    ):
        """Silent truncation is a lie by omission: past the cap, dump()
        must WARN and the dropped count must be queryable."""
        tr = obs.Tracer(enabled=True, max_events=3)
        for i in range(10):
            tr.point(f"e{i}")
        assert tr.dropped_events == 7
        with caplog.at_level("WARNING", logger="fast_tffm_tpu.obs.trace"):
            tr.dump(str(tmp_path / "t.json"))
        assert any("TRUNCATED" in r.message for r in caplog.records)
        # A clean dump stays quiet.
        caplog.clear()
        tr2 = obs.Tracer(enabled=True)
        tr2.point("a")
        with caplog.at_level("WARNING", logger="fast_tffm_tpu.obs.trace"):
            tr2.dump(str(tmp_path / "t2.json"))
        assert not caplog.records
        assert tr2.dropped_events == 0

    def test_reset_preserves_process_name(self):
        tr = obs.Tracer(enabled=True, process_name="trainer")
        tr.point("a")
        tr.reset()
        evs = tr.take()
        assert [e["name"] for e in evs] == ["process_name"]


# ---------------------------------------------------------------------------
# Traced training runs
# ---------------------------------------------------------------------------


def _write_libsvm(path, n_lines, vocab=50, n_feat=3, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.choice(vocab, size=n_feat, replace=False)
            toks = " ".join(f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats)
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


def _cfg(data, tmp_path, tag, **kw):
    defaults = dict(
        vocabulary_size=50,
        factor_num=4,
        model_file=str(tmp_path / f"model_{tag}"),
        train_files=[data],
        epoch_num=1,
        batch_size=32,
        max_features=4,
        log_steps=0,
        thread_num=2,
        steps_per_dispatch=4,
        seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.fixture(scope="module")
def train_file(tmp_path_factory):
    out = tmp_path_factory.mktemp("trace_data")
    return _write_libsvm(out / "train.libsvm", 640)


@pytest.fixture(scope="module")
def traced_procs_run(train_file, tmp_path_factory):
    """ONE traced run shared by the trace-content tests: the acceptance
    configuration — parse_processes=2, steps_per_dispatch=4."""
    tmp = tmp_path_factory.mktemp("traced_run")
    trace = str(tmp / "trace.json")
    metrics = str(tmp / "metrics.jsonl")
    cfg = _cfg(
        train_file, tmp, "procs", parse_processes=2,
        trace_file=trace, metrics_file=metrics,
    )
    result = Trainer(cfg).train()
    return {"trace": trace, "metrics": metrics, "result": result,
            "tmp": tmp}


def _events(path):
    doc = json.load(open(path))
    assert isinstance(doc, dict) and "traceEvents" in doc
    return doc["traceEvents"]


class TestTraceContent:
    def test_trace_is_valid_chrome_trace_json(self, traced_procs_run):
        doc = json.load(open(traced_procs_run["trace"]))
        # Perfetto object format: traceEvents + clock anchors for the
        # multi-rank merge.
        assert isinstance(doc["traceEvents"], list)
        assert doc["traceEvents"], "empty trace"
        for key in ("wall_anchor", "perf_anchor"):
            assert key in doc["otherData"], key
        for ev in doc["traceEvents"]:
            assert ev["ph"] in ("X", "M", "s", "t", "f"), ev
            assert "pid" in ev and "tid" in ev
            if ev["ph"] == "X":
                assert "ts" in ev and "dur" in ev and "name" in ev

    def test_spans_cover_every_stage(self, traced_procs_run):
        names = {e.get("name") for e in _events(traced_procs_run["trace"])}
        for stage in (
            "read.item",          # reader window production
            "ring.slot_acquire",  # SHM ring slot wait (reader side)
            "parse.window",       # worker-side window span (slot release)
            "parse.batch",        # worker-side per-batch parse
            "ingest.deliver",     # delivery bridge (seq -> batch idx)
            "prefetch.stack",     # transfer-stage stacking
            "prefetch.h2d",       # device put
            "train.wait_input",   # starvation side of the loop
            "train.dispatch",     # fused-scan dispatch
        ):
            assert stage in names, f"missing stage span {stage}"

    def test_worker_spans_carry_worker_pids(self, traced_procs_run):
        evs = _events(traced_procs_run["trace"])
        parent_pids = {
            e["pid"] for e in evs if e.get("name") == "train.dispatch"
        }
        parse_pids = {
            e["pid"] for e in evs if e.get("name") == "parse.batch"
        }
        assert parse_pids, "no parse spans"
        # parse spans were recorded in spawned workers and shipped back:
        # they carry the WORKER pids, not the trainer's.
        assert parse_pids.isdisjoint(parent_pids)

    def test_every_dispatch_has_connected_chain(self, traced_procs_run):
        """The acceptance criterion: every dispatched super-batch's life
        reconstructs as one connected chain across the process
        boundary (sb -> batch range -> seq -> worker parse spans)."""
        chains = report.trace_chains(_events(traced_procs_run["trace"]))
        assert chains, "no dispatched super-batches in trace"
        # 640 lines / 32 = 20 batches at K=4 -> 5 dispatches.
        assert len(chains) == 5
        for c in chains:
            assert c["complete"], f"disconnected chain for sb {c['sb']}"
            # Chain links really cross the process boundary: the parse
            # span of every batch came from a worker pid.
            disp_pid = c["dispatch"]["pid"]
            for b in c["batches"]:
                assert b["parse"]["pid"] != disp_pid

    def test_report_trace_merges_to_loadable_file(self, traced_procs_run,
                                                  capsys):
        merged = str(traced_procs_run["tmp"] / "merged.json")
        rc = report.main(
            ["--trace", traced_procs_run["trace"], "-o", merged]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "critical path" in out
        assert "5 with a complete" in out
        doc = json.load(open(merged))
        # Normalized timeline starts at zero and chains still connect.
        tss = [e["ts"] for e in doc["traceEvents"] if "ts" in e]
        assert min(tss) == 0
        chains = report.trace_chains(doc["traceEvents"])
        assert all(c["complete"] for c in chains)

    def test_prestacked_replay_chains_complete(self, train_file,
                                               tmp_path):
        """cache_prestacked replay epochs deliver whole SuperBatches —
        ONE ingest.deliver point covering n batches.  Chain completeness
        must treat that range as delivered (a healthy prestacked trace
        used to report every replay chain incomplete)."""
        trace = str(tmp_path / "prestack_trace.json")
        cfg = _cfg(
            train_file, tmp_path, "prestack", epoch_num=2,
            cache_epochs=True, cache_prestacked=True, trace_file=trace,
        )
        Trainer(cfg).train()
        chains = report.trace_chains(_events(trace))
        # 20 batches/epoch at K=4 -> 5 dispatches x 2 epochs.
        assert len(chains) == 10
        assert all(c["complete"] for c in chains), [
            c["sb"] for c in chains if not c["complete"]
        ]
        # Every dispatch took the prestacked path (epoch 0 stacks ONCE
        # in the pipeline; replays reuse): h2d spans carry the batch
        # range + prestacked flag, no transfer-stage stack span.
        assert all(c["stack"] is None for c in chains)
        assert all(
            (c["h2d"]["args"] or {}).get("prestacked") for c in chains
        )

    def test_multi_rank_merge_builds_per_rank_chains(
        self, traced_procs_run, tmp_path, capsys
    ):
        """Fleet merge: sb/seq ids restart per rank, so chains must be
        reconstructed per input file — two rank files with identical id
        spaces merge without cross-wiring (or crashing on duplicate
        ring seqs) and yield 2x the chains."""
        import shutil

        r0 = str(tmp_path / "t.rank0.json")
        r1 = str(tmp_path / "t.rank1.json")
        shutil.copy(traced_procs_run["trace"], r0)
        shutil.copy(traced_procs_run["trace"], r1)
        merged = str(tmp_path / "fleet.json")
        rc = report.main(["--trace", r0, r1, "-o", merged])
        assert rc == 0
        out = capsys.readouterr().out
        assert "10 dispatched, 10 with a complete" in out

    def test_health_in_final_record_and_results(self, traced_procs_run):
        recs = [json.loads(l) for l in open(traced_procs_run["metrics"])]
        final = [r for r in recs if r.get("record") == "final"][-1]
        health = final["health"]
        for key in ("grad_norm", "grad_norm_rms", "nonfinite_steps",
                    "first_nonfinite_step", "emb_rows_touched",
                    "emb_row_occupancy", "emb_touch_events"):
            assert key in health, key
        assert health["nonfinite_steps"] == 0
        assert health["first_nonfinite_step"] == -1
        assert 0 < health["emb_rows_touched"] <= 50
        # 640 lines x 3 real features each.
        assert health["emb_touch_events"] == 1920.0
        rh = traced_procs_run["result"]["train"]["health"]
        assert rh["nonfinite_steps"] == 0
        assert rh["emb_rows_touched"] == health["emb_rows_touched"]

    def test_final_record_surfaces_trace_dropped_events(
        self, traced_procs_run, train_file, tmp_path
    ):
        """The final metrics record carries ``trace_dropped_events`` on
        traced runs — 0 for a healthy run, the true drop count for a
        run that overflowed the event cap."""
        recs = [json.loads(l) for l in open(traced_procs_run["metrics"])]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert final["trace_dropped_events"] == 0
        # Overflowed run: shrink the live tracer's cap before training.
        metrics = str(tmp_path / "m.jsonl")
        cfg = _cfg(train_file, tmp_path, "capped",
                   trace_file=str(tmp_path / "t.json"),
                   metrics_file=metrics)
        trainer = Trainer(cfg)
        trainer.tracer._max = 5
        trainer.train()
        recs = [json.loads(l) for l in open(metrics)]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert final["trace_dropped_events"] > 0
        # An untraced run's final record carries no trace field at all.
        cfg2 = _cfg(train_file, tmp_path, "untraced",
                    metrics_file=str(tmp_path / "m2.jsonl"))
        Trainer(cfg2).train()
        recs = [json.loads(l) for l in open(str(tmp_path / "m2.jsonl"))]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert "trace_dropped_events" not in final


class TestTraceOff:
    def test_trace_off_is_bit_identical_training(self, train_file,
                                                 tmp_path):
        """trace_file unset must not perturb a single bit of training:
        the tracer is the shared no-op and no span code runs."""
        import jax

        states = {}
        for tag in ("on", "off"):
            cfg = _cfg(
                train_file, tmp_path, f"bit_{tag}",
                trace_file=(
                    str(tmp_path / "t.json") if tag == "on" else ""
                ),
            )
            t = Trainer(cfg)
            t.train()
            states[tag] = t.state
        eq = jax.tree.map(
            lambda a, b: bool(np.array_equal(np.asarray(a),
                                             np.asarray(b))),
            states["on"], states["off"],
        )
        assert all(jax.tree.leaves(eq))


# ---------------------------------------------------------------------------
# Windowed trace rotation (ISSUE 7 tentpole, layer 3)
# ---------------------------------------------------------------------------


class TestTraceRotation:
    def test_rotates_at_watermark_with_zero_drops(self, tmp_path):
        base = str(tmp_path / "t.json")
        tr = obs.Tracer(enabled=True, rotate_events=5, rotate_path=base)
        for i in range(17):
            tr.point(f"e{i}")
        # 17 events at watermark 5 -> 3 full windows already on disk.
        assert tr.windows_written == 3
        last = tr.dump(base)  # final window: the 2-event remainder
        assert last == 2
        assert tr.windows_written == 4
        assert tr.dropped_events == 0
        total = 0
        for i in range(4):
            doc = json.load(open(str(tmp_path / f"t.{i}.json")))
            other = doc["otherData"]
            assert other["window"] == i
            assert other["dropped_events"] == 0
            for key in ("wall_anchor", "perf_anchor", "pid"):
                assert key in other
            total += len(doc["traceEvents"])
        assert total == 17  # every event landed in exactly one window

    def test_watermark_above_event_cap_still_rotates(self, tmp_path):
        """The in-memory drop cap must not apply under rotation: a
        watermark past the cap used to hit the cap's drop path first
        and silently never rotate — the exact truncation rotation
        exists to prevent."""
        base = str(tmp_path / "t.json")
        tr = obs.Tracer(
            enabled=True, max_events=10, rotate_events=20,
            rotate_path=base,
        )
        for i in range(50):
            tr.point(f"e{i}")
        tr.dump(base)
        assert tr.dropped_events == 0
        total = sum(
            len(json.load(open(str(p)))["traceEvents"])
            for p in tmp_path.glob("t.*.json")
        )
        assert total == 50

    def test_worker_shipment_crossing_watermark_never_truncates(
        self, tmp_path
    ):
        """add_raw ships worker span BATCHES; a batch landing near the
        watermark must rotate, not truncate (the cap's room check used
        to drop the batch's tail before the rotation check ran)."""
        base = str(tmp_path / "t.json")
        tr = obs.Tracer(
            enabled=True, max_events=20, rotate_events=20,
            rotate_path=base,
        )
        for i in range(15):
            tr.point(f"e{i}")
        worker = obs.Tracer(enabled=True)
        for i in range(30):
            worker.point(f"w{i}")
        tr.add_raw(worker.take())  # 15 + 30 crosses the watermark
        tr.dump(base)
        assert tr.dropped_events == 0
        total = sum(
            len(json.load(open(str(p)))["traceEvents"])
            for p in tmp_path.glob("t.*.json")
        )
        assert total == 45

    def test_window_naming(self, tmp_path):
        tr = obs.Tracer(
            enabled=True, rotate_events=5,
            rotate_path=str(tmp_path / "trace.json"),
        )
        assert tr.window_path(0).endswith("trace.0.json")
        tr2 = obs.Tracer(
            enabled=True, rotate_events=5,
            rotate_path=str(tmp_path / "trace.json.rank1"),
        )
        assert tr2.window_path(2).endswith("trace.json.rank1.2.json")

    def test_reset_restarts_window_numbering(self, tmp_path):
        base = str(tmp_path / "t.json")
        tr = obs.Tracer(enabled=True, rotate_events=3, rotate_path=base)
        for i in range(7):
            tr.point(f"e{i}")
        assert tr.windows_written == 2
        tr.reset()
        assert tr.windows_written == 0

    def test_traced_run_rotates_and_chains_remerge(self, train_file,
                                                   tmp_path, capsys):
        """The acceptance criterion: a run traced past the watermark
        yields rotated files that --trace merges back into COMPLETE
        chains with zero dropped events — including chains that span a
        rotation boundary."""
        trace = str(tmp_path / "rot.json")
        metrics = str(tmp_path / "rot_metrics.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "rotate", trace_file=trace,
            trace_rotate_events=40, metrics_file=metrics,
        )
        Trainer(cfg).train()
        windows = sorted(
            str(p) for p in tmp_path.glob("rot.*.json")
        )
        assert len(windows) >= 3, windows  # genuinely rotated
        assert not (tmp_path / "rot.json").exists()  # windows only
        # Zero drops, surfaced in the final record (rotation is WHY).
        recs = [json.loads(l) for l in open(metrics)]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert final["trace_dropped_events"] == 0
        assert final["trace_windows"] == len(windows) - 1  # pre-final
        # Windows re-join into one stream; every chain reconnects.
        merged = str(tmp_path / "rot_merged.json")
        rc = report.main(["--trace"] + windows + ["-o", merged])
        assert rc == 0
        out = capsys.readouterr().out
        assert (
            f"re-joined {len(windows)} file(s) into 1 stream(s)" in out
        )
        # 640 lines / 32 = 20 batches at K=4 -> 5 dispatches.
        assert "5 dispatched, 5 with a complete" in out
        # And the merged artifact stays Perfetto-loadable.
        doc = json.load(open(merged))
        assert doc["traceEvents"]

    def test_rotation_bitwise_identical_to_unrotated(self, train_file,
                                                     tmp_path):
        """Rotation is a storage policy of the trace output: the
        recorded EVENTS (ignoring timestamps/ids) and the trained model
        must match an unrotated run exactly."""
        import jax

        states = {}
        for tag, rot in (("rot", 40), ("flat", 0)):
            cfg = _cfg(
                train_file, tmp_path, f"parity_{tag}",
                trace_file=str(tmp_path / f"parity_{tag}.json"),
                trace_rotate_events=rot,
            )
            t = Trainer(cfg)
            t.train()
            states[tag] = t.state
        eq = jax.tree.map(
            lambda a, b: bool(np.array_equal(np.asarray(a),
                                             np.asarray(b))),
            states["rot"], states["flat"],
        )
        assert all(jax.tree.leaves(eq))
        flat_events = _events(str(tmp_path / "parity_flat.json"))
        rot_events = []
        for p in sorted(tmp_path.glob("parity_rot.*.json"),
                        key=lambda p: int(p.name.split(".")[1])):
            rot_events.extend(json.load(open(str(p)))["traceEvents"])

        def stage_counts(events):
            # Only the work-deterministic spans: thread-scheduling
            # artifacts (thread_name metadata, conditional
            # staging_wait spans) legitimately vary run to run.
            out: dict = {}
            for e in events:
                if e.get("ph") == "X" and e["name"] in (
                    "read.item", "parse.batch", "ingest.deliver",
                    "prefetch.stack", "prefetch.h2d", "train.dispatch",
                ):
                    out[e["name"]] = out.get(e["name"], 0) + 1
            return out

        assert stage_counts(rot_events) == stage_counts(flat_events)
        assert stage_counts(rot_events)["train.dispatch"] == 5

    def test_straggler_section_names_slowest_rank(self, train_file,
                                                  tmp_path, capsys):
        """Two rank streams -> the merge grows a straggler section
        attributing each chain segment to its slowest rank."""
        trace = str(tmp_path / "strag.json")
        cfg = _cfg(
            train_file, tmp_path, "strag", trace_file=trace,
            trace_rotate_events=40,
        )
        Trainer(cfg).train()
        windows = sorted(str(p) for p in tmp_path.glob("strag.*.json"))
        # Synthesize rank 1: same windows under a different pid +
        # anchors (a different process would differ in exactly these).
        rank1 = []
        for i, path in enumerate(windows):
            doc = json.load(open(path))
            doc["otherData"]["pid"] = 99999
            doc["otherData"]["wall_anchor"] += 1000.0
            out = str(tmp_path / f"strag_rank1.{i}.json")
            json.dump(doc, open(out, "w"))
            rank1.append(out)
        rc = report.main(
            ["--trace"] + windows + rank1
            + ["-o", str(tmp_path / "strag_merged.json")]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "10 dispatched, 10 with a complete" in out  # 5 + 5
        assert "straggler attribution" in out
        assert "slowest dispatch" in out
        assert "slowest latency" in out

    def test_unrotated_files_stay_separate_streams(self, traced_procs_run,
                                                   tmp_path):
        """Legacy traces (no window metadata) must keep the one-file =
        one-rank contract even when byte-identical copies are merged
        (sb ids restart per rank; anchor-grouping them would
        cross-wire the chains)."""
        import shutil

        r0 = str(tmp_path / "a.json")
        r1 = str(tmp_path / "b.json")
        shutil.copy(traced_procs_run["trace"], r0)
        shutil.copy(traced_procs_run["trace"], r1)
        _, _, per_file = report.merge_traces([r0, r1])
        streams = report.group_streams(per_file)
        assert len(streams) == 2


# ---------------------------------------------------------------------------
# Health monitors: NaN injection under both nan_policy modes
# ---------------------------------------------------------------------------


def _poison(trainer):
    """Inject a NaN that corrupts every subsequent gradient: w0 = NaN
    makes scores (hence dL/dscore) non-finite from the first step."""
    trainer.state = trainer.state._replace(
        params=trainer.state.params._replace(
            w0=jnp.full((), jnp.nan, jnp.float32)
        )
    )


class TestNanPolicy:
    def test_halt_raises_within_one_dispatch(self, train_file, tmp_path):
        k = 4
        mf = str(tmp_path / "halt.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "halt", steps_per_dispatch=k,
            nan_policy="halt", metrics_file=mf,
        )
        t = Trainer(cfg)
        _poison(t)
        with pytest.raises(NonFiniteGradError):
            t.train()
        # The poisoned dispatch is #0; the delayed check consumes its
        # scalars right after dispatch #1 — within one dispatch, i.e.
        # at most 2K steps ever ran.
        assert int(t.state.step) <= 2 * k
        # Crash-truthful stream: the final record names the exception
        # and carries the health counters.
        recs = [json.loads(l) for l in open(mf)]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert final["exception"] == "NonFiniteGradError"
        assert final["health"]["nonfinite_steps"] > 0
        assert final["health"]["first_nonfinite_step"] == 0

    def test_warn_completes_and_reports(self, train_file, tmp_path):
        mf = str(tmp_path / "warn.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "warn", nan_policy="warn",
            metrics_file=mf,
        )
        t = Trainer(cfg)
        _poison(t)
        result = t.train()  # must NOT raise
        health = result["train"]["health"]
        assert health["nonfinite_steps"] == 20  # every step was bad
        assert health["first_nonfinite_step"] == 0
        # The damage appears in the final record too (no exception —
        # the run completed under warn).
        recs = [json.loads(l) for l in open(mf)]
        final = [r for r in recs if r.get("record") == "final"][-1]
        assert "exception" not in final
        assert final["health"]["nonfinite_steps"] == 20
        assert final["health"]["first_nonfinite_step"] == 0

    def test_health_reporting_is_per_run(self, train_file, tmp_path):
        """state.step is instance-cumulative; health reporting must
        rebase to the run (a clean first run then a poisoned second on
        the same Trainer reports first_nonfinite_step 0, not 20, and an
        RMS over run-2 steps only)."""
        cfg = _cfg(train_file, tmp_path, "rerun", nan_policy="warn")
        t = Trainer(cfg)
        r1 = t.train()
        assert r1["train"]["health"]["nonfinite_steps"] == 0
        _poison(t)
        r2 = t.train()
        health = r2["train"]["health"]
        assert health["nonfinite_steps"] == 20
        assert health["first_nonfinite_step"] == 0  # per-run step base

    def test_nan_policy_validated(self):
        with pytest.raises(ValueError, match="nan_policy"):
            FmConfig(nan_policy="explode")

    def test_halt_blocks_periodic_save_of_poisoned_params(
        self, train_file, tmp_path
    ):
        """A save boundary in the same iteration as the poisoned
        dispatch must NOT write the checkpoint first: the save path
        force-consumes the pending health readback, so halt fires
        before any poisoned params persist."""
        from fast_tffm_tpu.train import checkpoint

        cfg = _cfg(
            train_file, tmp_path, "halt_save", steps_per_dispatch=4,
            nan_policy="halt", save_steps=4,  # save every dispatch
        )
        t = Trainer(cfg)
        _poison(t)
        with pytest.raises(NonFiniteGradError):
            t.train()
        # The first save boundary coincided with the first (poisoned)
        # dispatch; the forced check ran first, so no checkpoint exists.
        assert not checkpoint.exists(cfg.model_file)


# ---------------------------------------------------------------------------
# Crash-truthful final record (any crash, not just nan halt)
# ---------------------------------------------------------------------------


class TestCrashTruthfulFinal:
    def test_interrupted_run_still_writes_final_record(self, train_file,
                                                       tmp_path, capsys):
        mf = str(tmp_path / "crash.jsonl")
        cfg = _cfg(
            train_file, tmp_path, "crash", metrics_file=mf,
            steps_per_dispatch=2,
        )
        t = Trainer(cfg)
        real = t._scan_train_step
        count = {"n": 0}

        def dying(state, batch):
            if count["n"] >= 2:
                raise KeyboardInterrupt("simulated preemption")
            count["n"] += 1
            return real(state, batch)

        t._scan_train_step = dying
        with pytest.raises(KeyboardInterrupt):
            t.train()
        recs = [json.loads(l) for l in open(mf)]
        final = [r for r in recs if r.get("record") == "final"]
        assert len(final) == 1
        final = final[-1]
        assert final["exception"] == "KeyboardInterrupt"
        assert final["step"] == 4  # partial counters survived
        assert "stages" in final and "health" in final
        # And report.py summarizes the crashed stream end to end.
        assert report.main([mf]) == 0
        out = capsys.readouterr().out
        assert "KeyboardInterrupt" in out


# ---------------------------------------------------------------------------
# tools/check_tier1.py — the marker audit bench.py preflights
# ---------------------------------------------------------------------------


_GOOD = """
import pytest

def test_fast():
    pass

@pytest.mark.slow
def test_slow():
    pass

class TestGroup:
    def test_also_fast(self):
        pass
"""

_ALL_SLOW = """
import pytest
pytestmark = pytest.mark.slow

def test_one():
    pass

def test_two():
    pass
"""

_TYPO_MARK = """
import pytest

@pytest.mark.sloww
def test_typo():
    pass
"""


class TestCheckTier1:
    def _repo(self, tmp_path, files):
        tests = tmp_path / "tests"
        tests.mkdir()
        (tmp_path / "pytest.ini").write_text(
            "[pytest]\nmarkers =\n    slow: slow tests\n    tpu: tpu\n"
        )
        for name, body in files.items():
            (tests / name).write_text(body)
        return str(tests), str(tmp_path)

    def test_counts_and_module_pytestmark(self, tmp_path):
        tests, root = self._repo(tmp_path, {
            "test_good.py": _GOOD, "test_allslow.py": _ALL_SLOW,
        })
        result = check_tier1.audit(tests, root)
        assert result["per_file"]["test_good.py"] == {
            "tests": 3, "tier1": 2, "slow": 1,
            "marks_used": {"slow"},
        }
        assert result["per_file"]["test_allslow.py"]["tier1"] == 0
        assert not result["ok"]
        assert any("test_allslow.py" in p for p in result["problems"])

    def test_undeclared_marker_flagged(self, tmp_path):
        tests, root = self._repo(tmp_path, {"test_typo.py": _TYPO_MARK})
        result = check_tier1.audit(tests, root)
        assert any("sloww" in p for p in result["problems"])

    def test_real_repo_passes(self):
        repo = os.path.dirname(_TOOLS)
        result = check_tier1.audit(os.path.join(repo, "tests"), repo)
        assert result["ok"], result["problems"]
        # This very file must contribute tier-1 tests.
        assert result["per_file"]["test_tracing.py"]["tier1"] > 0


# ---------------------------------------------------------------------------
# tools/report.py --compare — regression flagging
# ---------------------------------------------------------------------------


class TestCompare:
    def test_bench_json_regression_flagged(self, tmp_path, capsys):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        base = {"metric": "x", "value": 100.0,
                "e2e_examples_per_sec": 100.0, "ingest_wait_frac": 0.10,
                "platform": "cpu"}
        a.write_text(json.dumps(base))
        worse = dict(base, e2e_examples_per_sec=80.0, value=80.0,
                     ingest_wait_frac=0.30)
        b.write_text(json.dumps(worse))
        rc = report.main(["--compare", str(a), str(b)])
        out = capsys.readouterr().out
        assert rc == 2
        assert out.count("REGRESSION") >= 3  # rate fell, wait rose

    def test_no_flag_within_threshold(self, tmp_path, capsys):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"metric": "x", "value": 100.0}))
        b.write_text(json.dumps({"metric": "x", "value": 98.0}))
        assert report.main(["--compare", str(a), str(b)]) == 0
        assert "REGRESSION" not in capsys.readouterr().out

    def test_metrics_jsonl_compare(self, traced_procs_run, capsys):
        mf = traced_procs_run["metrics"]
        rc = report.main(["--compare", mf, mf])
        assert rc == 0  # identical run: no regression against itself
        out = capsys.readouterr().out
        assert "examples_in" in out


# ---------------------------------------------------------------------------
# ISSUE 14: per-request distributed tracing across the serve fleet
# ---------------------------------------------------------------------------


class TestServeTrace:
    """A sampled request through an (in-process) 2-replica router
    renders as ONE connected cross-process chain — router admit ->
    proxy -> replica queue wait -> coalesce -> rung dispatch ->
    respond — and ``tools/report.py --serve-trace`` walks it.  The
    unsampled path stays bitwise-identical (same score bytes, no
    X-Request-Id, zero spans)."""

    _CFG_KW = dict(
        vocabulary_size=64, factor_num=4, max_features=4,
        serve_batch_sizes="8", max_batch_wait_ms=1.0,
    )

    @pytest.fixture(scope="class")
    def fleet(self, tmp_path_factory):
        import urllib.request

        import jax

        from fast_tffm_tpu.models import fm
        from fast_tffm_tpu.serve import wire
        from fast_tffm_tpu.serve.batcher import ServeBatcher
        from fast_tffm_tpu.serve.router import Replica, ServeRouter
        from fast_tffm_tpu.serve.scorer import FixedShapeScorer
        from fast_tffm_tpu.serve.server import ServeServer

        tmp = tmp_path_factory.mktemp("serve_trace")
        cfg = FmConfig(model_file=str(tmp / "model"), **self._CFG_KW)
        params = jax.jit(
            lambda k: fm.init_params(k, cfg=cfg)
        )(jax.random.PRNGKey(0))
        stacks = []
        replicas = []
        for i in range(2):
            tracer = obs.Tracer(enabled=True,
                                process_name=f"replica{i}")
            scorer = FixedShapeScorer(cfg, params)
            scorer.warmup()
            batcher = ServeBatcher(
                scorer, max_batch_wait_ms=cfg.max_batch_wait_ms,
                tracer=tracer,
            )
            server = ServeServer(
                0, batcher, cfg, lambda: {"record": "status"},
                tracer=tracer,
            )
            stacks.append((tracer, batcher, server))
            replicas.append(Replica(i, "127.0.0.1", server.port))
        router_tracer = obs.Tracer(enabled=True,
                                   process_name="router")
        rcfg = FmConfig(model_file=str(tmp / "model"),
                        serve_replicas=2, **self._CFG_KW)
        router = ServeRouter(
            0, replicas, rcfg, health_secs=10.0,
            tracer=router_tracer,
            sampler=wire.RequestSampler(1.0, enabled=True, tag="rt"),
        )

        def post(path, body, headers=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}{path}", data=body,
                method="POST", headers=headers or {},
            )
            resp = urllib.request.urlopen(req, timeout=30)
            return resp.status, resp.read(), dict(resp.headers)

        yield {
            "router": router, "router_tracer": router_tracer,
            "stacks": stacks, "post": post, "tmp": tmp,
        }
        router.close()
        for _, batcher, server in stacks:
            server.close()
            batcher.close()

    def _dump_all(self, fleet):
        tmp = fleet["tmp"]
        paths = []
        router_path = str(tmp / "trace.json")
        fleet["router_tracer"].dump(router_path)
        paths.append(router_path)
        for i, (tracer, _, _) in enumerate(fleet["stacks"]):
            p = str(tmp / f"trace.json.replica{i}")
            tracer.dump(p)
            paths.append(p)
        return paths

    def test_sampled_request_chain_is_complete(self, fleet):
        status, body, hdrs = fleet["post"](
            "/score", b"1 3:1\n0 2:0.5\n"
        )
        assert status == 200
        rid = hdrs.get("X-Request-Id")
        assert rid, "sampled request lost its id echo"
        assert len(body.decode().split()) == 2
        paths = self._dump_all(fleet)
        events, _, _ = report.merge_traces(paths)
        chains = report.serve_request_chains(events)
        mine = [c for c in chains if c["rid"] == rid]
        assert len(mine) == 1
        chain = mine[0]
        assert chain["complete"], (
            f"chain missing segments: {sorted(chain['spans'])}"
        )
        for seg in ("admit", "proxy", "queue_wait", "coalesce",
                    "dispatch", "respond"):
            assert seg in chain["spans"], seg
        assert chain["replica"] in (0, 1)
        # The replica half carries the SAME rid the router minted:
        # the spans came from different Tracer instances, joined only
        # by the propagated id.
        assert chain["spans"]["dispatch"]["args"]["rid"] == rid
        # Flow arrows: start at the proxy, step at the dispatch, end
        # at the respond — the Perfetto-visible connection.
        flows = [
            ev for ev in events
            if ev.get("cat") == "tffm_flow" and ev.get("id") == rid
        ]
        assert {f["ph"] for f in flows} == {"s", "t", "f"}

    def test_sampled_score_bin_chain_is_complete(self, fleet):
        """The acceptance shape: a sampled /score_bin request — the id
        rides the frame's flags-bit-1 trailer across the proxy hop —
        still reconstructs the full cross-process chain."""
        from fast_tffm_tpu.serve import wire

        ids = np.zeros((2, 4), np.int32)
        vals = np.ones((2, 4), np.float32)
        status, body, hdrs = fleet["post"](
            "/score_bin", wire.encode_bin_request(ids, vals),
            headers={"Content-Type": "application/octet-stream"},
        )
        assert status == 200
        rid = hdrs.get("X-Request-Id")
        assert rid
        assert len(wire.decode_bin_response(body)) == 2
        paths = self._dump_all(fleet)
        events, _, _ = report.merge_traces(paths)
        chains = [
            c for c in report.serve_request_chains(events)
            if c["rid"] == rid
        ]
        assert len(chains) == 1 and chains[0]["complete"], (
            f"bin chain: {sorted(chains[0]['spans']) if chains else []}"
        )

    def test_report_serve_trace_mode(self, fleet, capsys):
        for _ in range(3):
            status, _, _ = fleet["post"]("/score", b"1 3:1\n")
            assert status == 200
        paths = self._dump_all(fleet)
        rc = report.main(["--serve-trace"] + paths)
        out = capsys.readouterr().out
        assert rc == 0
        assert "sampled requests:" in out
        assert "critical path" in out
        assert "dispatch" in out

    def test_unsampled_serving_is_bitwise_identical(
        self, tmp_path_factory
    ):
        import urllib.request

        import jax

        from fast_tffm_tpu.models import fm
        from fast_tffm_tpu.serve import wire
        from fast_tffm_tpu.serve.batcher import ServeBatcher
        from fast_tffm_tpu.serve.scorer import FixedShapeScorer
        from fast_tffm_tpu.serve.server import ServeServer

        tmp = tmp_path_factory.mktemp("serve_trace_off")
        cfg = FmConfig(model_file=str(tmp / "model"), **self._CFG_KW)
        params = jax.jit(
            lambda k: fm.init_params(k, cfg=cfg)
        )(jax.random.PRNGKey(0))
        scorer = FixedShapeScorer(cfg, params)
        scorer.warmup()

        def serve_once(tracer, sampler):
            batcher = ServeBatcher(scorer, tracer=tracer)
            server = ServeServer(
                0, batcher, cfg, lambda: {"record": "status"},
                tracer=tracer, sampler=sampler,
            )
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{server.port}/score",
                    data=b"1 3:1\n0 2:0.5\n", method="POST",
                )
                resp = urllib.request.urlopen(req, timeout=30)
                return resp.read(), dict(resp.headers)
            finally:
                server.close()
                batcher.close()

        off_tracer = obs.Tracer(enabled=True)  # enabled, NOT sampled
        body_off, hdrs_off = serve_once(
            off_tracer, wire.RequestSampler(0.0, enabled=True)
        )
        on_tracer = obs.Tracer(enabled=True)
        body_on, hdrs_on = serve_once(
            on_tracer, wire.RequestSampler(1.0, enabled=True)
        )
        # Scores are bitwise-identical with tracing on or off...
        assert body_off == body_on
        # ...the unsampled response carries no id header...
        assert "X-Request-Id" not in hdrs_off
        assert "X-Request-Id" in hdrs_on
        # ...and the unsampled path emitted ZERO spans (no-op spans,
        # no id allocation — the satellite contract).
        assert off_tracer.take() == []
        assert [e for e in on_tracer.take()
                if e.get("ph") == "X"] != []
