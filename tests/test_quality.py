"""Model-quality & data-drift plane (obs/sketch.py + obs/quality.py):
sketch unit properties (merge, rank error, fixed memory), windowed
online eval parity, PSI fires-on-shift / quiet-on-identity, the
quality=off inert-knob + parity discipline, manifest sketch
publication, and training→serving skew end-to-end over real sockets.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.alerts import AlertEngine, parse_rules, resolved_signal
from fast_tffm_tpu.obs.quality import (
    QualityMonitor, ServeSkewMonitor, StreamSketch, window_auc,
    window_logloss,
)
from fast_tffm_tpu.obs.sketch import (
    FreqSketch, QuantileSketch, SketchSet, psi_freq, psi_quantile,
)

# ----------------------------------------------------------------------
# sketch unit properties
# ----------------------------------------------------------------------


class TestQuantileSketch:
    def test_rank_error_bound(self, rng):
        """The pinned accuracy claim: every estimated quantile's true
        rank is within 2% of the requested one at the default k, over
        a stream ~400x the sketch's capacity."""
        data = rng.normal(size=50_000)
        sk = QuantileSketch()
        for chunk in np.array_split(data, 137):  # ragged update sizes
            sk.update(chunk)
        assert sk.n == len(data)
        for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99):
            est = sk.quantile(q)
            true_rank = float(np.mean(data <= est))
            assert abs(true_rank - q) <= 0.02, (q, true_rank)

    def test_merge_order_independence_within_bound(self, rng):
        """Merge associativity, stated honestly: compaction makes
        different merge ORDERS produce different internal states, but
        every order's quantile estimates must stay within the rank
        bound of the full stream — so partial sketches combine like
        one stream regardless of worker scheduling."""
        data = rng.standard_gamma(2.0, size=30_000)
        parts = np.array_split(data, 3)

        def sketch(arr):
            s = QuantileSketch()
            s.update(arr)
            return s

        # (a + b) + c  vs  a + (b + c)
        left = sketch(parts[0]).merge(sketch(parts[1]))
        left.merge(sketch(parts[2]))
        right_tail = sketch(parts[1]).merge(sketch(parts[2]))
        right = sketch(parts[0]).merge(right_tail)
        assert left.n == right.n == len(data)
        for sk in (left, right):
            for q in (0.1, 0.5, 0.9):
                true_rank = float(np.mean(data <= sk.quantile(q)))
                assert abs(true_rank - q) <= 0.03, (q, true_rank)

    def test_fixed_memory(self, rng):
        """Retained items are O(k log n), not O(n): a 400k-element
        stream keeps under ~30 levels x k items."""
        sk = QuantileSketch()
        for _ in range(100):
            sk.update(rng.normal(size=4096))
        assert sk.n == 409_600
        assert sk.retained <= sk.k * 30
        before = sk.retained
        for _ in range(100):  # doubling n must not double retention
            sk.update(rng.normal(size=4096))
        assert sk.retained <= before + 2 * sk.k

    def test_empty_and_nonfinite(self):
        sk = QuantileSketch()
        assert sk.quantile(0.5) is None
        sk.update([np.inf, np.nan])
        assert sk.n == 0  # non-finite inputs never poison the sketch
        sk.update([1.0])
        assert sk.quantile(0.5) == 1.0


class TestFreqSketch:
    def test_merge_is_exact(self, rng):
        a, b = FreqSketch(), FreqSketch()
        ids_a = rng.integers(0, 10_000, 5000)
        ids_b = rng.integers(0, 10_000, 7000)
        a.update(ids_a)
        b.update(ids_b)
        both = FreqSketch()
        both.update(np.concatenate([ids_a, ids_b]))
        merged = FreqSketch()
        merged.merge(a).merge(b)
        np.testing.assert_array_equal(merged.counts, both.counts)
        assert merged.n == both.n == 12_000

    def test_bucket_mismatch_refused(self):
        with pytest.raises(ValueError, match="buckets"):
            FreqSketch(64).merge(FreqSketch(128))


class TestSerialization:
    def test_sketchset_json_roundtrip(self, rng):
        ss = SketchSet()
        for _ in range(20):
            ids = rng.integers(0, 5000, size=(64, 8))
            vals = np.where(rng.random((64, 8)) < 0.7,
                            rng.normal(size=(64, 8)), 0.0)
            ss.update_batch(ids, vals)
        ss.update_scores(rng.random(500))
        doc = json.loads(json.dumps(ss.to_dict()))  # through real JSON
        back = SketchSet.from_dict(doc)
        assert back.examples == ss.examples
        np.testing.assert_array_equal(back.ids.counts, ss.ids.counts)
        # A roundtripped sketch judged against its source is identity.
        psi = back.psi_vs(ss)
        assert psi["psi_max"] <= 0.02, psi


class TestPsi:
    def test_identity_quiet_shift_fires(self, rng):
        base = rng.normal(size=20_000)
        same = rng.normal(size=20_000)
        shifted = rng.normal(1.5, size=20_000)
        s_base, s_same, s_shift = (
            QuantileSketch(), QuantileSketch(), QuantileSketch()
        )
        s_base.update(base)
        s_same.update(same)
        s_shift.update(shifted)
        assert psi_quantile(s_base, s_same) < 0.05
        assert psi_quantile(s_base, s_shift) > 0.25

        f_base, f_same = FreqSketch(), FreqSketch()
        f_base.update(rng.integers(0, 1000, 20_000))
        f_same.update(rng.integers(0, 1000, 20_000))
        assert psi_freq(f_base, f_same) < 0.05
        # Concentration shift (traffic collapsing onto 10x fewer
        # rows): the canonical occupancy drift, read as SHIFTED.
        f_narrow = FreqSketch()
        f_narrow.update(rng.integers(5000, 5100, 20_000))
        assert psi_freq(f_base, f_narrow) > 0.25
        # Matched-density disjoint swap: the documented weak case —
        # still reads as drifting, not stable.
        f_disjoint = FreqSketch()
        f_disjoint.update(rng.integers(5000, 6000, 20_000))
        assert psi_freq(f_base, f_disjoint) > 0.1

    def test_small_window_identity_debiased(self, rng):
        """The debias property thresholds rely on: two SMALL samples
        of the same distribution read ~0, not sampling noise."""
        f1, f2 = FreqSketch(), FreqSketch()
        f1.update(rng.integers(0, 50, 500))
        f2.update(rng.integers(0, 50, 500))
        assert psi_freq(f1, f2) < 0.05

    def test_empty_is_none_not_zero(self):
        assert psi_quantile(QuantileSketch(), QuantileSketch()) is None
        assert psi_freq(FreqSketch(), FreqSketch()) is None
        assert SketchSet().psi_vs(SketchSet()) == {}

    def test_constant_reference(self, rng):
        """A constant reference stream (degenerate cut points) must
        still compare, and still see a moved live stream."""
        ref, same, moved = (
            QuantileSketch(), QuantileSketch(), QuantileSketch()
        )
        ref.update(np.ones(1000))
        same.update(np.ones(1000))
        moved.update(np.full(1000, 5.0))
        assert psi_quantile(ref, same) < 0.05
        assert psi_quantile(ref, moved) > 0.25


# ----------------------------------------------------------------------
# windowed online eval
# ----------------------------------------------------------------------


class TestOnlineEval:
    def test_window_auc_exact_vs_pairwise(self, rng):
        """The windowed AUC is EXACT (weighted Mann-Whitney with
        midranks) — pinned against the O(n^2) definition, ties and
        weights included."""
        s = np.round(rng.random(600), 2)  # plenty of ties
        y = (rng.random(600) < 0.4).astype(float)
        w = rng.uniform(0.5, 2.0, 600)
        got = window_auc(s, y, w)
        P, WP = s[y > 0], w[y > 0]
        N, WN = s[y <= 0], w[y <= 0]
        cmp = ((P[:, None] > N[None, :]).astype(float)
               + 0.5 * (P[:, None] == N[None, :]))
        want = float((WP[:, None] * WN[None, :] * cmp).sum()
                     / (WP.sum() * WN.sum()))
        assert abs(got - want) < 1e-12

    def test_single_class_window_is_none(self):
        assert window_auc(np.array([0.5, 0.6]), np.array([1.0, 1.0]),
                          np.ones(2)) is None

    def test_windowed_stream_vs_exact_batch_parity(self, rng):
        """Online (chunked, ring-buffered) eval == exact batch eval
        over the same most-recent window examples, on a synthetic
        stream longer than the window."""
        window = 1000
        mon = QualityMonitor(loss_type="logistic", window=window)
        raw_all, y_all = [], []
        for _ in range(7):  # 7 x 400 = 2800 > window
            raw = rng.normal(size=400)
            p = 1 / (1 + np.exp(-raw))
            y = (rng.random(400) < p).astype(float)
            mon.observe(raw, y, np.ones(400))
            raw_all.append(raw)
            y_all.append(y)
        raw_all = np.concatenate(raw_all)
        y_all = np.concatenate(y_all)
        p_last = 1 / (1 + np.exp(-raw_all[-window:]))
        y_last = y_all[-window:]
        w = np.ones(window)
        block = mon.block()
        assert block["window_examples"] == window
        assert abs(block["logloss"]
                   - window_logloss(p_last, y_last, w)) < 1e-6
        assert abs(block["auc"] - window_auc(p_last, y_last, w)) < 1e-6

    def test_calib_ratio(self):
        mon = QualityMonitor(loss_type="mse", window=100)
        scores = np.full(100, 0.6)
        labels = (np.arange(100) < 30).astype(float)  # rate 0.3
        mon.observe(scores, labels, np.ones(100))
        block = mon.block()
        assert abs(block["calib_ratio"] - 2.0) < 1e-6
        assert abs(block["score_mean"] - 0.6) < 1e-6
        assert abs(block["label_rate"] - 0.3) < 1e-6

    def test_logloss_drift_rises_on_degradation(self, rng):
        """Stationary stream -> drift ~1; a model that starts scoring
        anti-correlated windows -> drift well above 1."""
        window = 200
        mon = QualityMonitor(loss_type="logistic", window=window)
        t = [0.0]

        def block():
            t[0] += 1.0  # sidestep the memo; one baseline sample per
            return mon.block(now=t[0])  # full window of new examples

        for _ in range(6):  # healthy windows build the baseline
            raw = rng.normal(size=window)
            y = (rng.random(window) < 1 / (1 + np.exp(-raw))).astype(float)
            mon.observe(raw, y, np.ones(window))
            healthy = block()
        assert 0.8 <= healthy.get("logloss_drift", 1.0) <= 1.2
        for _ in range(2):  # poisoned windows: labels flipped
            raw = rng.normal(size=window)
            y = (rng.random(window) >= 1 / (1 + np.exp(-raw))).astype(float)
            mon.observe(raw, y, np.ones(window))
            bad = block()
        assert bad["logloss_drift"] > 1.2, bad


# ----------------------------------------------------------------------
# StreamSketch rotation + drift signals + alert integration
# ----------------------------------------------------------------------


def _feed(sketch, rng, n_batches, id_lo, id_hi, val_scale=1.0):
    for _ in range(n_batches):
        ids = rng.integers(id_lo, id_hi, size=(64, 8))
        vals = np.where(rng.random((64, 8)) < 0.75,
                        rng.random((64, 8)) * val_scale, 0.0)
        sketch.update_batch(ids, vals)


class TestStreamSketch:
    def test_rotation_and_adjacent_window_psi(self, rng):
        ss = StreamSketch(window_examples=512)
        _feed(ss, rng, 16, 0, 1000)  # 1024 identity examples
        assert ss.rotations >= 1
        quiet = ss.psi()
        assert quiet and quiet["psi_max"] < 0.1, quiet
        # Mid-transition (shifted window filling against an identity
        # prev) the drift is loud...
        _feed(ss, rng, 6, 50_000, 50_200, val_scale=40.0)
        loud = ss.psi()
        assert loud["psi_values"] > 0.25, loud
        assert loud["psi_ids"] > 0.25, loud
        # ...and once the NEW regime fills adjacent windows of its
        # own, the rolling baseline self-heals back to quiet.
        _feed(ss, rng, 26, 50_000, 50_200, val_scale=40.0)
        healed = ss.psi()
        assert healed["psi_max"] < 0.1, healed
        # total keeps accumulating across rotations
        assert ss.examples == 48 * 64

    def test_absorb_matches_direct(self, rng):
        """A worker-shipped delta stream reconstructs the same totals
        as direct updates (the procpool contract)."""
        direct = StreamSketch(window_examples=10_000)
        via_deltas = StreamSketch(window_examples=10_000)
        for _ in range(8):
            ids = rng.integers(0, 5000, size=(32, 8))
            vals = rng.random((32, 8))
            direct.update_batch(ids, vals)
            delta = SketchSet()
            delta.update_batch(ids, vals)
            via_deltas.absorb(delta.to_dict())
        assert via_deltas.examples == direct.examples
        np.testing.assert_array_equal(
            via_deltas.total.ids.counts, direct.total.ids.counts
        )

    def test_alert_rule_fires_on_injected_drift(self, rng):
        """The acceptance demo: `quality.psi_values > 0.2 for 3 : warn`
        fires on an injected distribution shift and stays quiet on
        identity — through the REAL AlertEngine over REAL quality
        blocks."""
        rules = parse_rules("quality.psi_values > 0.2 for 3 : warn")
        engine = AlertEngine(rules)
        ss = StreamSketch(window_examples=512)
        mon = QualityMonitor(window=256, sketch=ss)
        t = [0.0]

        def beat():
            t[0] += 1.0
            return engine.observe(
                {"record": "heartbeat", "step": int(t[0]),
                 "quality": mon.block(now=t[0])}
            )

        for _ in range(12):  # identity traffic: no alert
            _feed(ss, rng, 2, 0, 1000)
            assert beat() == []
        assert engine.fired_total == 0
        fired = []
        # Injected shift, beating at a realistic many-beats-per-window
        # cadence (1 batch per beat, 8 beats per window): the breach
        # sustains across the transition and `for 3` fires.
        for _ in range(12):
            _feed(ss, rng, 1, 80_000, 80_200, val_scale=30.0)
            fired += beat()
        assert engine.fired_total >= 1
        assert fired and fired[0]["signal"] == "quality.psi_values"
        assert fired[0]["action"] == "warn"

    def test_quality_aliases_resolve(self):
        assert resolved_signal("logloss_drift") == "quality.logloss_drift"
        assert resolved_signal("calib_ratio") == "quality.calib_ratio"
        assert resolved_signal("psi_max") == "quality.psi_max"


# ----------------------------------------------------------------------
# config: inert-knob discipline
# ----------------------------------------------------------------------


class TestConfig:
    def _kw(self, tmp_path):
        return dict(
            model_file=str(tmp_path / "m"),
            heartbeat_secs=1.0,
        )

    def test_refuses_quality_rules_when_off(self, tmp_path):
        with pytest.raises(ValueError, match="quality"):
            FmConfig(
                quality=False,
                alert_rules="quality.psi_values > 0.2 : warn",
                **self._kw(tmp_path),
            )
        with pytest.raises(ValueError, match="quality"):
            FmConfig(
                quality=False,
                alert_rules="logloss_drift > 2 : halt",
                **self._kw(tmp_path),
            )

    def test_quality_rules_accepted_when_on(self, tmp_path):
        cfg = FmConfig(
            alert_rules="quality.psi_values > 0.2 for 3 : warn",
            **self._kw(tmp_path),
        )
        assert cfg.quality

    def test_refuses_skew_rules_when_off(self, tmp_path):
        """serve.skew_* keys only exist when the skew monitor does —
        same inertness hazard as the quality.* rules."""
        with pytest.raises(ValueError, match="quality"):
            FmConfig(
                quality=False,
                alert_rules="serve.skew_psi_max > 0.25 for 3 : warn",
                **self._kw(tmp_path),
            )

    def test_quality_window_validated(self, tmp_path):
        with pytest.raises(ValueError, match="quality_window"):
            FmConfig(quality_window=0, model_file=str(tmp_path / "m"))
        # A window below the judgeable mass would silently disable the
        # PSI signals — refused, and the config's literal must agree
        # with the quality plane's constant.
        from fast_tffm_tpu.obs.quality import _MIN_PSI_EXAMPLES

        assert _MIN_PSI_EXAMPLES == 32
        with pytest.raises(ValueError, match="judgeable"):
            FmConfig(quality_window=16, model_file=str(tmp_path / "m"))
        FmConfig(quality_window=32, model_file=str(tmp_path / "m"))

    def test_cli_no_quality_flag(self):
        from fast_tffm_tpu.cli import build_argparser

        args = build_argparser().parse_args(
            ["train", "x.cfg", "--no_quality"]
        )
        assert args.no_quality
        args2 = build_argparser().parse_args(
            ["train", "x.cfg", "--quality_window", "1234"]
        )
        assert args2.quality_window == 1234


# ----------------------------------------------------------------------
# trainer integration: parity, quality block, manifest publication
# ----------------------------------------------------------------------


def _write_libsvm(path, n_lines, vocab=50, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.choice(vocab, size=3, replace=False)
            toks = " ".join(f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats)
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def train_file(tmp_path_factory):
    out = tmp_path_factory.mktemp("quality_data")
    return _write_libsvm(out / "train.libsvm", 320)


def _train_cfg(data, tmp_path, tag, **kw):
    defaults = dict(
        vocabulary_size=50, factor_num=4,
        model_file=str(tmp_path / f"model_{tag}"),
        train_files=[data], epoch_num=1, batch_size=32,
        max_features=4, log_steps=0, thread_num=2,
        steps_per_dispatch=2, seed=3, quality_window=64,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


class TestTrainerQuality:
    def test_quality_off_is_bitwise_identical(self, train_file, tmp_path):
        """The inert-knob parity pin: quality on vs off trains to
        BITWISE-identical parameters (the scan emits scores but the
        carry math is untouched)."""
        from fast_tffm_tpu.train.loop import Trainer

        params = {}
        for tag, on in (("qon", True), ("qoff", False)):
            cfg = _train_cfg(train_file, tmp_path, tag, quality=on)
            trainer = Trainer(cfg)
            results = trainer.train()
            params[tag] = (trainer.state.params, results)
        on_p, on_res = params["qon"]
        off_p, off_res = params["qoff"]
        np.testing.assert_array_equal(
            np.asarray(on_p.table), np.asarray(off_p.table)
        )
        np.testing.assert_array_equal(
            np.asarray(on_p.w0), np.asarray(off_p.w0)
        )
        assert on_res["train"]["loss"] == off_res["train"]["loss"]
        # The block rides results only when the plane is on.
        assert "quality" in on_res["train"]
        assert "quality" not in off_res["train"]

    def test_quality_block_and_manifest(self, train_file, tmp_path):
        from fast_tffm_tpu.train.loop import Trainer
        from fast_tffm_tpu.train.manifest import read_manifest

        mf = str(tmp_path / "metrics_q.jsonl")
        cfg = _train_cfg(
            train_file, tmp_path, "blk", metrics_file=mf,
            heartbeat_secs=0.05,
        )
        res = Trainer(cfg).train()
        q = res["train"]["quality"]
        for key in ("examples", "logloss", "window_examples",
                    "sketch_examples"):
            assert key in q, q
        assert q["examples"] == 320
        # Every parsed example was sketched (thread-worker path).
        assert q["sketch_examples"] == 320
        records = [json.loads(line) for line in open(mf)]
        header = records[0]
        assert header["quality"] is True
        assert header["quality_window"] == 64
        final = [r for r in records if r["record"] == "final"][-1]
        assert "quality" in final
        # The manifest carries the skew reference next to the step.
        man = read_manifest(cfg.model_file)
        assert man["quality"]["examples"] == 320
        ref = SketchSet.from_dict(man["quality"]["sketches"])
        assert ref.examples == 320
        assert ref.scores.n > 0  # training scores sketched too
        # Self-skew of the reference is ~0.
        assert ref.psi_vs(ref)["psi_max"] <= 0.01

    def test_process_workers_ship_sketches(self, train_file, tmp_path):
        """The procpool channel: sketches computed IN spawned workers
        arrive complete (periodic deltas + the done-flush)."""
        from fast_tffm_tpu.train.loop import Trainer
        from fast_tffm_tpu.train.manifest import read_manifest

        cfg = _train_cfg(
            train_file, tmp_path, "procs", parse_processes=2,
        )
        res = Trainer(cfg).train()
        assert res["train"]["quality"]["sketch_examples"] == 320
        man = read_manifest(cfg.model_file)
        assert man["quality"]["examples"] == 320

    def test_sketch_failure_never_kills_training(self, train_file,
                                                 tmp_path,
                                                 monkeypatch):
        """The observer contract: a sketching exception on the parse
        path degrades the quality plane, it must never surface through
        the worker's fatal error path and abort the run."""
        from fast_tffm_tpu.train.loop import Trainer

        def boom(self, *a, **kw):
            raise MemoryError("injected sketch failure")

        monkeypatch.setattr(StreamSketch, "update_batch", boom)
        cfg = _train_cfg(train_file, tmp_path, "sketchfail")
        res = Trainer(cfg).train()  # must complete despite the raise
        assert res["train"]["examples"] == 320
        # The plane degraded: no ingest sketch mass, eval still ran.
        q = res["train"]["quality"]
        assert q["sketch_examples"] == 0
        assert q["examples"] == 320

    def test_quality_off_manifest_has_no_payload(self, train_file,
                                                 tmp_path):
        from fast_tffm_tpu.train.loop import Trainer
        from fast_tffm_tpu.train.manifest import read_manifest

        cfg = _train_cfg(train_file, tmp_path, "noq", quality=False)
        Trainer(cfg).train()
        man = read_manifest(cfg.model_file)
        assert "quality" not in man


# ----------------------------------------------------------------------
# serving: skew detection end-to-end over real sockets
# ----------------------------------------------------------------------


def _post(url, body, timeout=30):
    req = urllib.request.Request(url, data=body)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def served(tmp_path_factory, train_file):
    """One trained checkpoint with manifest sketches, shared by the
    serving skew tests."""
    tmp_path = tmp_path_factory.mktemp("quality_serve")
    from fast_tffm_tpu.train.loop import Trainer

    cfg = _train_cfg(train_file, tmp_path, "serve",
                     serve_poll_secs=0, quality_window=128)
    Trainer(cfg).train()
    return tmp_path, cfg, train_file


class TestServeSkew:
    def test_skew_identity_then_breach_over_sockets(self, served):
        """The acceptance path: train -> manifest sketches -> serve ->
        identity traffic reads ~0 -> shifted traffic breaches
        tffm_serve_skew_* on /metrics."""
        from fast_tffm_tpu.serve.server import serve

        _, cfg, data = served
        handle = serve(cfg, port=0)
        try:
            url = f"http://127.0.0.1:{handle.port}"
            body = open(data, "rb").read()
            _post(url + "/score", body)
            block = _get_json(url + "/status")["serve"]
            assert block["skew_ref_step"] > 0
            assert block["skew_examples"] >= 128
            assert block["skew_psi_max"] <= 0.1, block
            # Shifted traffic: foreign id range, 50x values, 4 feats.
            rng = np.random.default_rng(9)
            shifted = "\n".join(
                "0 " + " ".join(
                    f"{int(j)}:{v * 50:.3f}" for j, v in
                    zip(rng.integers(45, 50, 4), rng.random(4) + 4)
                )
                for _ in range(320)
            ).encode()
            _post(url + "/score", shifted)
            block = _get_json(url + "/status")["serve"]
            assert block["skew_psi_max"] > 0.25, block
            assert block["skew_psi_values"] > 0.25, block
            metrics = urllib.request.urlopen(
                url + "/metrics", timeout=10
            ).read().decode()
            assert "tffm_serve_skew_psi_max" in metrics
            assert "tffm_serve_skew_psi_values" in metrics
            # Timing percentile series carry their sample-count
            # companion (the tffm_*_count satellite).
            assert "tffm_timer_serve_latency_window_count" in metrics
            assert "latency_count" in block
            assert "latency_window_n" in block
        finally:
            handle.close()

    def test_quality_off_serving_byte_identical(self, served, tmp_path):
        """Responses must be byte-identical with the skew monitor on
        or off — observation only, pinned."""
        import dataclasses

        from fast_tffm_tpu.serve.server import serve

        _, cfg, data = served
        body = open(data, "rb").read()
        out = {}
        for tag, on in (("on", True), ("off", False)):
            c = dataclasses.replace(cfg, quality=on)
            handle = serve(c, port=0)
            try:
                url = f"http://127.0.0.1:{handle.port}"
                out[tag] = _post(url + "/score", body)
                block = _get_json(url + "/status")["serve"]
                if on:
                    assert "skew_ref_step" in block
                else:
                    assert not any(
                        k.startswith("skew_") for k in block
                    ), block
            finally:
                handle.close()
        assert out["on"] == out["off"]

    def test_no_reference_reports_absence(self, served, tmp_path):
        """A pre-quality manifest (no sketches) yields skew_ref_step
        -1 and NO psi keys — absence, never a lying zero."""
        monitor = ServeSkewMonitor(
            window_examples=64, read_reference=lambda: None
        )
        monitor.observe_batch(
            np.ones((80, 4), np.int32), np.ones((80, 4), np.float32)
        )
        block = monitor.block()
        assert block["skew_ref_step"] == -1
        assert not any(k.startswith("skew_psi") for k in block), block

    def test_reference_follows_reload(self, rng):
        """reload_reference() re-reads the manifest payload — the
        hot-swap hook's contract."""
        ref_a = SketchSet()
        ref_a.update_batch(
            rng.integers(0, 100, (64, 4)), rng.random((64, 4))
        )
        payload = [{"step": 7, "sketches": ref_a.to_dict()}]
        monitor = ServeSkewMonitor(
            window_examples=1024, read_reference=lambda: payload[0]
        )
        assert monitor.reload_reference()
        assert monitor.block()["skew_ref_step"] == 7
        payload[0] = {"step": 11, "sketches": ref_a.to_dict()}
        assert monitor.reload_reference()
        assert monitor.block()["skew_ref_step"] == 11

    def test_reference_clears_when_payload_vanishes(self, rng):
        """A readable manifest WITHOUT a quality payload (--no_quality
        retrain, in-place conversion) must CLEAR the reference — a
        stale one would judge the NEW model's traffic against the old
        checkpoint's sketches (phantom skew)."""
        ref = SketchSet()
        ref.update_batch(
            rng.integers(0, 100, (64, 4)), rng.random((64, 4))
        )
        payload = [{"step": 7, "sketches": ref.to_dict()}]
        monitor = ServeSkewMonitor(
            window_examples=1024, read_reference=lambda: payload[0]
        )
        assert monitor.reload_reference()
        monitor.observe_batch(
            np.ones((64, 4), np.int32), np.ones((64, 4), np.float32)
        )
        assert "skew_psi_max" in monitor.block()
        payload[0] = None  # the next manifest carries no sketches
        assert not monitor.reload_reference()
        block = monitor.block()
        assert block["skew_ref_step"] == -1
        assert not any(k.startswith("skew_psi") for k in block), block

    def test_rollback_restores_previous_reference(self, rng):
        """The canary /rollback path: served params revert to the
        pre-canary checkpoint, so the skew reference reverts from the
        stash (its manifest is gone from disk)."""
        ref = SketchSet()
        ref.update_batch(
            rng.integers(0, 100, (64, 4)), rng.random((64, 4))
        )
        payload = [{"step": 7, "sketches": ref.to_dict()}]
        monitor = ServeSkewMonitor(
            window_examples=1024, read_reference=lambda: payload[0]
        )
        assert monitor.reload_reference()  # baseline checkpoint
        payload[0] = {"step": 11, "sketches": ref.to_dict()}
        assert monitor.reload_reference()  # the canary reload
        assert monitor.block()["skew_ref_step"] == 11
        monitor.restore_previous_reference()  # rejected -> rollback
        assert monitor.block()["skew_ref_step"] == 7


# ----------------------------------------------------------------------
# router fleet aggregation + rendering + report
# ----------------------------------------------------------------------


class TestFleetAndTooling:
    def test_router_fleet_scrape_max_merges_skew(self):
        """One router scrape answers 'is ANY replica skewed': skew_psi
        keys MAX-merge under the same names, skew_examples sums."""
        from fast_tffm_tpu.serve.router import ServeRouter

        per = [{"index": 0}, {"index": 1}]
        now = 1000.0
        scrapes = {
            0: (now - 1, {"requests": 10, "skew_psi_max": 0.02,
                          "skew_psi_values": 0.01,
                          "skew_examples": 100}),
            1: (now - 2, {"requests": 20, "skew_psi_max": 0.9,
                          "skew_psi_values": 0.8,
                          "skew_examples": 50}),
        }
        out = ServeRouter._fleet_aggregates(None, per, scrapes, now)
        assert out["skew_psi_max"] == 0.9
        assert out["skew_psi_values"] == 0.8
        assert out["skew_examples"] == 150

    def test_render_prometheus_quality_block_and_window_count(self):
        from fast_tffm_tpu.obs.status import render_prometheus

        tel = obs.Telemetry()
        t = tel.timer("serve.latency")
        for _ in range(5):
            t.observe(0.01)
        rec = {
            "record": "status",
            "quality": {"logloss": 0.31, "psi_max": 0.02},
            "stages": tel.snapshot(),
        }
        text = render_prometheus(rec)
        assert "tffm_quality_logloss 0.31" in text
        assert "tffm_quality_psi_max 0.02" in text
        assert "tffm_timer_serve_latency_window_count 5" in text

    def test_report_directions(self):
        from tools.report import _direction

        assert _direction("quality.logloss") == "low"
        assert _direction("quality.auc") == "high"
        assert _direction("quality.calib_ratio") == "both"
        assert _direction("quality.psi_values") == "low"
        assert _direction("serve.skew_psi_max") == "low"
        assert _direction("quality_overhead") == "low"
        assert _direction("quality_psi_identity") == "low"

    def test_report_quality_section_never_keyerrors(self, capsys):
        """Pre-quality streams (no quality block) summarize with the
        n/a line, never a KeyError."""
        from tools.report import _print_breakdown

        rec = {"record": "final", "step": 10, "elapsed": 1.0,
               "stages": {}}
        _print_breakdown(rec)
        out = capsys.readouterr().out
        assert "quality & drift: n/a" in out

    def test_report_flattens_quality_keys(self, tmp_path):
        from tools.report import _comparable_metrics

        mf = tmp_path / "m.jsonl"
        recs = [
            {"record": "run_header", "time": 0},
            {"record": "final", "step": 4, "elapsed": 1.0,
             "quality": {"logloss": 0.5, "auc": 0.7, "psi_max": 0.1},
             "serve": {"skew_psi_max": 0.2}},
        ]
        mf.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        out = _comparable_metrics(str(mf))
        assert out["quality.logloss"] == 0.5
        assert out["quality.auc"] == 0.7
        assert out["serve.skew_psi_max"] == 0.2
