"""Kernel autotuner (ISSUE 17 tentpole): measured promotion of the
interaction hot path + fused stack+H2D shipping + persistent caches.

The pinned guarantees:

  * zero-overhead CPU contract — ``interaction_impl=auto`` off-TPU
    resolves to reference through the single-candidate fast path
    WITHOUT running one measurement;
  * parity gate — a candidate whose outputs drift from reference
    beyond PARITY_TOL is excluded from selection no matter how fast
    it measured (a wrong kernel can never win);
  * cache discipline — a persistent-cache hit skips measurement
    entirely; ANY drift in the key (batch, table dtype, jax version,
    ...) re-measures; pins and the legacy surface never consult it;
  * training equivalence — a run resolved via ``auto`` produces
    BIT-IDENTICAL tables to one pinned to the impl auto chose;
  * fused H2D — FusedShipper's single-buffer ship + on-device carve
    is bitwise-equal to the classic stack_batches + shard_super_batch
    path (core leaves AND sort_meta), and its gate never opens on a
    multi-device mesh;
  * serve warmup — the concurrent ladder warmup compiles every rung
    (zero steady-state compiles after), and with a persistent compile
    cache a fresh scorer spawn re-lowers nothing.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax

from fast_tffm_tpu import obs, platform
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch, SortMeta
from fast_tffm_tpu.data.pipeline import stack_batches
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import autotune
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.serve.scorer import FixedShapeScorer
from fast_tffm_tpu.train.loop import Trainer

V = 64
F = 4


@pytest.fixture(autouse=True)
def _isolated_autotune(monkeypatch):
    """Every test gets an empty in-process cache and a memory-only
    default cache path (no autotune_cache.json left on disk unless the
    test passes cache_path explicitly)."""
    monkeypatch.setattr(autotune, "_MEM_CACHE", {})
    monkeypatch.setenv("FAST_TFFM_AUTOTUNE_CACHE", "")


def _cfg(**kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=F, batch_size=32,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _train_cfg(tmp_path, model, **kw):
    return _cfg(
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / model),
        epoch_num=1, log_steps=0, thread_num=1, seed=3, **kw,
    )


def _write_data(path, rng, lines=160, vocab=V):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5\n"
            )


# ----------------------------------------------------------------------
# resolve: pins, CPU fast path, parity gate
# ----------------------------------------------------------------------


class TestResolve:
    def test_cpu_auto_is_reference_with_zero_measurement(self):
        """The near-zero-overhead contract bench.py's
        autotune_overhead budget prices: off-TPU `auto` must win by
        construction, not by benchmark."""
        n0 = autotune.measurement_count()
        d = autotune.resolve(_cfg(interaction_impl="auto"))
        assert d.impl == "reference"
        assert d.interaction == "jnp"
        assert d.source == "single_candidate"
        assert autotune.measurement_count() == n0

    def test_pin_bypasses_measurement_and_cache(self, tmp_path):
        cache = str(tmp_path / "autotune_cache.json")
        n0 = autotune.measurement_count()
        d = autotune.resolve(
            _cfg(interaction_impl="packed"), cache_path=cache
        )
        assert (d.impl, d.interaction, d.source) == (
            "packed", "flat", "pinned"
        )
        assert autotune.measurement_count() == n0
        assert not os.path.exists(cache)

    def test_legacy_surface_maps_without_measurement(self):
        n0 = autotune.measurement_count()
        d = autotune.resolve(_cfg(interaction="flat"))
        assert (d.impl, d.interaction, d.source) == (
            "packed", "flat", "legacy"
        )
        assert autotune.measurement_count() == n0

    def test_ffm_collapses_to_reference(self):
        """field_num > 0: impl routing doesn't apply to the FFM op, so
        auto must not measure anything."""
        n0 = autotune.measurement_count()
        d = autotune.resolve(
            _cfg(interaction_impl="auto", field_num=3)
        )
        assert d.impl == "reference"
        assert d.source == "single_candidate"
        assert autotune.measurement_count() == n0

    def test_parity_gate_rejects_wrong_candidate(self):
        """A deliberately-wrong 'packed' (scores scaled 2x) must lose
        to reference even though it is the 'fastest' — wrong answers
        never get timed, let alone win."""
        cfg = _cfg(interaction_impl="auto")
        rng = np.random.default_rng(0)
        rows = rng.uniform(-0.1, 0.1, (32, F, 4)).astype(np.float32)
        vals = rng.uniform(0.1, 1.0, (32, F)).astype(np.float32)

        def make(user_impl):
            from fast_tffm_tpu.ops import interaction

            scale = 2.0 if user_impl == "packed" else 1.0

            def f(r, v):
                return interaction.fm_interaction(r, v, "jnp") * scale

            return jax.jit(f)

        d = autotune.resolve(
            cfg, candidates=("reference", "packed"),
            candidate_fns=(make, (rows, vals)),
        )
        assert d.source == "measured"
        assert d.impl == "reference"
        assert d.parity_err["packed"] > autotune.PARITY_TOL
        assert "packed" not in d.times_ms  # gated out before timing

    def test_real_packed_candidate_passes_parity(self):
        """The actual flat-layout impl IS element-wise equivalent: a
        forced CPU measurement must keep it as a survivor (times
        recorded) with tiny parity error, whoever wins."""
        d = autotune.resolve(
            _cfg(interaction_impl="auto"),
            candidates=("reference", "packed"),
        )
        assert d.source == "measured"
        assert "packed" in d.times_ms
        assert d.parity_err["packed"] <= autotune.PARITY_TOL

    def test_serve_context_int8_dequant_candidates(self):
        """Serve-context measurement routes the int8 fused-gather
        forward; packed must be parity-equivalent there too."""
        d = autotune.resolve(
            _cfg(interaction_impl="auto", serve_table_dtype="int8"),
            context="serve", batch=32,
            candidates=("reference", "packed"), table_dtype="int8",
        )
        assert d.source == "measured"
        assert d.impl in ("reference", "packed")
        assert d.parity_err["packed"] <= autotune.PARITY_TOL


# ----------------------------------------------------------------------
# persistent cache: hits skip measurement, drift re-measures
# ----------------------------------------------------------------------


class TestCache:
    CANDS = ("reference", "packed")

    def test_hit_skips_measurement(self, tmp_path):
        cfg = _cfg(interaction_impl="auto")
        cache = str(tmp_path / "autotune_cache.json")
        d1 = autotune.resolve(
            cfg, candidates=self.CANDS, cache_path=cache
        )
        assert d1.source == "measured"
        n1 = autotune.measurement_count()
        d2 = autotune.resolve(
            cfg, candidates=self.CANDS, cache_path=cache
        )
        assert d2.source == "cache"
        assert d2.impl == d1.impl
        assert autotune.measurement_count() == n1

    def test_hit_from_disk_across_processes(self, tmp_path, monkeypatch):
        """A fresh process (fresh _MEM_CACHE) reads the file — the
        replica-fleet / restart contract."""
        cfg = _cfg(interaction_impl="auto")
        cache = str(tmp_path / "autotune_cache.json")
        autotune.resolve(cfg, candidates=self.CANDS, cache_path=cache)
        assert os.path.exists(cache)
        monkeypatch.setattr(autotune, "_MEM_CACHE", {})  # "new process"
        n1 = autotune.measurement_count()
        d = autotune.resolve(cfg, candidates=self.CANDS, cache_path=cache)
        assert d.source == "cache"
        assert autotune.measurement_count() == n1

    @pytest.mark.parametrize("drift", ["batch", "table_dtype",
                                       "jax_version", "candidates"])
    def test_key_drift_re_measures(self, tmp_path, drift):
        """ANY axis of the key changing invalidates the entry — a
        stale winner never leaks across shapes/dtypes/upgrades."""
        cfg = _cfg(interaction_impl="auto")
        cache = str(tmp_path / "autotune_cache.json")
        kw = dict(candidates=self.CANDS, cache_path=cache, batch=32)
        autotune.resolve(cfg, **kw)
        n1 = autotune.measurement_count()
        if drift == "batch":
            kw["batch"] = 64
        elif drift == "table_dtype":
            kw["table_dtype"] = "bf16"
        elif drift == "jax_version":
            kw["jax_version"] = "999.0.0"
        else:
            kw["candidates"] = ("reference", "pallas", "packed")
        d = autotune.resolve(cfg, **kw)
        assert d.source == "measured"
        assert autotune.measurement_count() > n1

    def test_corrupt_cache_file_re_measures(self, tmp_path):
        cfg = _cfg(interaction_impl="auto")
        cache = str(tmp_path / "autotune_cache.json")
        with open(cache, "w") as f:
            f.write("{not json")
        d = autotune.resolve(cfg, candidates=self.CANDS, cache_path=cache)
        assert d.source == "measured"
        # and the re-measure repaired the file in place
        entries = autotune.load_cache(cache)
        assert entries and all(
            e["impl"] in autotune.INTERNAL for e in entries.values()
        )

    def test_record_schema(self, tmp_path):
        """The `record: autotune` observability contract
        OBSERVABILITY.md pins: impl/source/time always present."""
        path = tmp_path / "m.jsonl"
        writer = obs.JsonlWriter(str(path))
        autotune.resolve(
            _cfg(interaction_impl="auto"), writer=writer,
        )
        writer.close()
        recs = [json.loads(l) for l in open(path)]
        assert len(recs) == 1
        r = recs[0]
        assert r["record"] == "autotune"
        for key in ("impl", "source", "time", "context", "key",
                    "candidates", "times_ms", "parity_err"):
            assert key in r
        assert r["impl"] == "reference"


# ----------------------------------------------------------------------
# training through the resolved impl
# ----------------------------------------------------------------------


def test_train_auto_bitwise_identical_to_pinned_reference(tmp_path, rng):
    """The acceptance property: a training run resolved via `auto`
    produces BIT-IDENTICAL params/metrics to one pinned to the impl
    auto chose (on CPU: reference) — selection may change speed,
    never math."""
    _write_data(tmp_path / "train.libsvm", rng)
    t_auto = Trainer(
        _train_cfg(tmp_path, "m_auto", interaction_impl="auto")
    )
    assert t_auto.kernel_impl == "reference"  # CPU contract
    assert t_auto._autotune is not None
    assert t_auto._autotune.source == "single_candidate"
    r_auto = t_auto.train()
    t_ref = Trainer(
        _train_cfg(tmp_path, "m_ref", interaction_impl="reference")
    )
    r_ref = t_ref.train()
    assert r_auto["train"]["steps"] == r_ref["train"]["steps"] > 0
    for a, b in zip(jax.tree.leaves(t_auto.state.params),
                    jax.tree.leaves(t_ref.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("knobs", [
    dict(table_tiering="on", hot_rows=64),
    dict(table_tiering="on", hot_rows=64, cold_dtype="bf16"),
    dict(compute_dtype="bfloat16"),
], ids=["tiered", "tiered-bf16-cold", "bf16-compute"])
def test_train_auto_identical_at_parity_matrix_knobs(tmp_path, rng,
                                                     knobs):
    """The existing tiered/quant parity matrices hold through the
    autotuner: at each knob point, `auto` training == pinned-reference
    training element-wise (the resolution happens before step build,
    so every downstream path sees the same impl)."""
    _write_data(tmp_path / "train.libsvm", rng)
    t_auto = Trainer(_train_cfg(
        tmp_path, "m_auto", interaction_impl="auto", **knobs
    ))
    r_auto = t_auto.train()
    t_ref = Trainer(_train_cfg(
        tmp_path, "m_ref", interaction_impl="reference", **knobs
    ))
    r_ref = t_ref.train()
    assert r_auto["train"]["steps"] == r_ref["train"]["steps"] > 0
    for a, b in zip(jax.tree.leaves(t_auto.state.params),
                    jax.tree.leaves(t_ref.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_run_header_carries_kernel_impl(tmp_path, rng):
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _train_cfg(
        tmp_path, "m_hdr", interaction_impl="auto",
        metrics_file=str(tmp_path / "m.jsonl"),
    )
    Trainer(cfg).train()
    recs = [json.loads(l) for l in open(tmp_path / "m.jsonl")]
    header = [r for r in recs if r.get("record") == "run_header"][-1]
    assert header["kernel_impl"] == "reference"
    assert header["interaction_impl"] == "auto"
    assert [r for r in recs if r.get("record") == "autotune"]


# ----------------------------------------------------------------------
# fused stack+H2D shipping
# ----------------------------------------------------------------------


def _batch(rng, b=32, f=F, vocab=V, with_meta=False):
    meta = None
    if with_meta:
        n_pad = b * f
        meta = SortMeta(
            perm=rng.integers(0, n_pad, n_pad).astype(np.int32),
            upos=rng.integers(0, n_pad, n_pad).astype(np.int32),
            lrow_last=rng.uniform(0, 8, n_pad).astype(np.float32),
            starts=rng.integers(0, n_pad, n_pad // 8).astype(np.int32),
            firsts=rng.integers(0, 2, n_pad // 8 + 1).astype(np.int32),
            ends=rng.integers(0, n_pad, n_pad // 8).astype(np.int32),
            tile_start=rng.integers(0, n_pad, 9).astype(np.int32),
        )
    return Batch(
        labels=rng.integers(0, 2, b).astype(np.float32),
        ids=rng.integers(0, vocab, (b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (b, f)).astype(np.float32),
        fields=np.zeros((b, f), np.int32),
        weights=np.ones((b,), np.float32),
        sort_meta=meta,
    )


class TestFusedShipper:
    @pytest.mark.parametrize("k", [1, 3])
    @pytest.mark.parametrize("with_meta", [False, True])
    def test_bitwise_matches_classic_path(self, rng, k, with_meta):
        """One fused buffer ship + on-device carve == stack_batches +
        shard_super_batch, bitwise, every leaf (the unpack is a pure
        bitcast — no arithmetic may touch the payload)."""
        cfg = _cfg()
        mesh = mesh_lib.make_mesh(cfg, jax.devices()[:1])
        ship = mesh_lib.FusedShipper(mesh, depth=2)
        group = [_batch(rng, with_meta=with_meta) for _ in range(k)]
        fused = ship(group)
        classic = mesh_lib.shard_super_batch(stack_batches(group), mesh)
        assert ship.ships == 1
        for name in ("labels", "ids", "vals", "fields", "weights"):
            a, b = getattr(fused, name), getattr(classic, name)
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        if with_meta:
            assert fused.sort_meta is not None
            for a, b in zip(fused.sort_meta, classic.sort_meta):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)
                )
        else:
            assert fused.sort_meta is None

    def test_meta_all_or_nothing(self, rng):
        """Mixed group (one member meta-less) drops meta, mirroring
        stack_batches."""
        cfg = _cfg()
        mesh = mesh_lib.make_mesh(cfg, jax.devices()[:1])
        ship = mesh_lib.FusedShipper(mesh)
        group = [_batch(rng, with_meta=True), _batch(rng)]
        assert ship(group).sort_meta is None

    def test_empty_group_declines(self):
        cfg = _cfg()
        mesh = mesh_lib.make_mesh(cfg, jax.devices()[:1])
        assert mesh_lib.FusedShipper(mesh)([]) is None

    def test_unpack_cache_reused_across_ships(self, rng):
        cfg = _cfg()
        mesh = mesh_lib.make_mesh(cfg, jax.devices()[:1])
        ship = mesh_lib.FusedShipper(mesh)
        for _ in range(3):
            ship([_batch(rng), _batch(rng)])
        assert ship.ships == 3
        assert len(ship._unpack_cache) == 1  # one spec -> one jit

    def test_gate_closed_on_multi_device_mesh(self, monkeypatch):
        """The structural gate is unconditional: a multi-device mesh
        never fuses, even force-enabled (the flat replicated buffer
        can't reproduce per-leaf data sharding)."""
        cfg = _cfg()
        multi = mesh_lib.make_mesh(cfg)  # conftest: 8 virtual devices
        assert multi.size > 1
        monkeypatch.setenv("FAST_TFFM_FUSED_H2D", "1")
        assert mesh_lib.fused_h2d_enabled(multi) is False
        single = mesh_lib.make_mesh(cfg, jax.devices()[:1])
        assert mesh_lib.fused_h2d_enabled(single) is True
        monkeypatch.setenv("FAST_TFFM_FUSED_H2D", "0")
        assert mesh_lib.fused_h2d_enabled(single) is False
        monkeypatch.delenv("FAST_TFFM_FUSED_H2D")
        # default off-TPU: classic path (device_put is zero-copy there)
        assert mesh_lib.fused_h2d_enabled(single) is False

    def test_train_with_fused_shipping_matches_classic(self, tmp_path,
                                                       rng, monkeypatch):
        """End-to-end: a K=4 training run through the fused transfer
        stage reproduces the classic-path run bit-for-bit."""
        _write_data(tmp_path / "train.libsvm", rng)
        monkeypatch.setenv("FAST_TFFM_FUSED_H2D", "1")
        cfg_f = _train_cfg(tmp_path, "m_fused", steps_per_dispatch=4)
        t_fused = Trainer(
            cfg_f, mesh=mesh_lib.make_mesh(cfg_f, jax.devices()[:1])
        )
        r_fused = t_fused.train()
        monkeypatch.setenv("FAST_TFFM_FUSED_H2D", "0")
        cfg_c = _train_cfg(tmp_path, "m_classic", steps_per_dispatch=4)
        t_classic = Trainer(
            cfg_c, mesh=mesh_lib.make_mesh(cfg_c, jax.devices()[:1])
        )
        r_classic = t_classic.train()
        assert r_fused["train"]["steps"] == r_classic["train"]["steps"]
        for a, b in zip(jax.tree.leaves(t_fused.state.params),
                        jax.tree.leaves(t_classic.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------------------
# serve: concurrent warmup + persistent compile cache
# ----------------------------------------------------------------------


def _params(cfg, seed=0):
    return jax.jit(lambda k: fm.init_params(k, cfg=cfg))(
        jax.random.PRNGKey(seed)
    )


def _cfg_mem(**kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=F, batch_size=32,
        serve_batch_sizes="8,16,32", max_batch_wait_ms=1.0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


class TestServeWarmup:
    def test_concurrent_warmup_compiles_every_rung(self, rng):
        """The serial-ladder fix: warmup still compiles the WHOLE
        ladder (scores after it are steady-state, zero compiles) and
        accounts both the wall time and the summed compile seconds."""
        tel = obs.Telemetry()
        cfg = _cfg_mem()
        sc = FixedShapeScorer(cfg, _params(cfg), telemetry=tel)
        n = sc.warmup()
        assert n == len(sc.ladder) == 3
        assert sc.warmup_wall_s > 0.0
        assert sc.warmup_compile_s > 0.0
        for size in (1, 7, 16, 33, 100):
            ids = rng.integers(0, V, (size, F)).astype(np.int32)
            vals = rng.uniform(0.1, 1.0, (size, F)).astype(np.float32)
            sc.score(ids, vals)
        assert sc.steady_compiles == 0
        snap = tel.snapshot()
        assert snap["timers"]["serve.compile"]["count"] == n

    def test_warmup_scores_match_lazy_compiled_scorer(self, rng):
        """Concurrent compilation may reorder nothing: scores from a
        warmed ladder equal a never-warmed scorer's lazily-compiled
        ones bitwise."""
        cfg = _cfg_mem()
        params = _params(cfg)
        warm = FixedShapeScorer(cfg, params)
        warm.warmup()
        lazy = FixedShapeScorer(cfg, params)
        ids = rng.integers(0, V, (20, F)).astype(np.int32)
        vals = rng.uniform(0.1, 1.0, (20, F)).astype(np.float32)
        np.testing.assert_array_equal(
            warm.score(ids, vals), lazy.score(ids, vals)
        )

    def test_warm_spawn_zero_fresh_lowers(self, rng, tmp_path):
        """With compile_cache_dir set, a second scorer spawn (same
        shapes/params structure) must warm up purely from the
        persistent cache: hits > 0, NO new misses."""
        if not platform.enable_compile_cache(str(tmp_path / "cc")):
            pytest.skip("persistent compile cache unavailable")
        try:
            cfg = _cfg_mem(serve_batch_sizes="8,16")
            params = _params(cfg)
            a = FixedShapeScorer(cfg, params)
            a.warmup()
            st0 = platform.compile_cache_stats()
            assert st0["misses"] > 0  # cold spawn populated the cache
            b = FixedShapeScorer(cfg, params)
            b.warmup()
            st1 = platform.compile_cache_stats()
            assert st1["misses"] == st0["misses"]  # zero fresh lowers
            assert st1["hits"] > st0["hits"]
            ids = rng.integers(0, V, (10, F)).astype(np.int32)
            vals = rng.uniform(0.1, 1.0, (10, F)).astype(np.float32)
            np.testing.assert_array_equal(
                a.score(ids, vals), b.score(ids, vals)
            )
        finally:
            platform.disable_compile_cache()
