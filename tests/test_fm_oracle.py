"""FM oracle numeric tests: sum-square trick vs brute-force pairwise sum."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models import fm


def brute_force_fm(w0, table, ids, vals, k):
    """O(F^2) pairwise definition of the 2nd-order FM score."""
    b, f = ids.shape
    out = np.zeros(b)
    for e in range(b):
        s = float(w0)
        for i in range(f):
            s += table[ids[e, i], 0] * vals[e, i]
        for i in range(f):
            for j in range(i + 1, f):
                vi = table[ids[e, i], 1 : 1 + k]
                vj = table[ids[e, j], 1 : 1 + k]
                s += float(np.dot(vi, vj)) * vals[e, i] * vals[e, j]
        out[e] = s
    return out


@pytest.fixture
def small_problem(rng):
    vocab, k, b, f = 50, 4, 8, 5
    table = rng.normal(size=(vocab, 1 + k)).astype(np.float32) * 0.1
    ids = rng.integers(0, vocab, size=(b, f)).astype(np.int32)
    vals = rng.normal(size=(b, f)).astype(np.float32)
    return table, ids, vals, k


def test_sum_square_trick_matches_brute_force(small_problem):
    table, ids, vals, k = small_problem
    params = fm.FmParams(w0=jnp.float32(0.3), table=jnp.asarray(table))
    got = fm.fm_scores(params, jnp.asarray(ids), jnp.asarray(vals), factor_num=k)
    want = brute_force_fm(0.3, table, ids, vals, k)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_padding_is_inert(small_problem):
    """val==0 slots must not change scores (SURVEY.md §7 static-shape rule)."""
    table, ids, vals, k = small_problem
    params = fm.FmParams(w0=jnp.float32(0.0), table=jnp.asarray(table))
    base = fm.fm_scores(params, jnp.asarray(ids), jnp.asarray(vals), factor_num=k)
    # Append padded columns: arbitrary ids, zero vals.
    ids_pad = np.concatenate([ids, np.full((ids.shape[0], 3), 7, np.int32)], axis=1)
    vals_pad = np.concatenate([vals, np.zeros((vals.shape[0], 3), np.float32)], axis=1)
    padded = fm.fm_scores(
        params, jnp.asarray(ids_pad), jnp.asarray(vals_pad), factor_num=k
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(padded), rtol=1e-6)


def brute_force_ffm(w0, table, ids, vals, fields, k, field_num):
    b, f = ids.shape
    out = np.zeros(b)
    for e in range(b):
        s = float(w0)
        for i in range(f):
            s += table[ids[e, i], 0] * vals[e, i]
        V = table[:, 1:].reshape(table.shape[0], field_num, k)
        for i in range(f):
            for j in range(i + 1, f):
                vi = V[ids[e, i], fields[e, j]]
                vj = V[ids[e, j], fields[e, i]]
                s += float(np.dot(vi, vj)) * vals[e, i] * vals[e, j]
        out[e] = s
    return out


def test_ffm_matches_brute_force(rng):
    vocab, k, field_num, b, f = 30, 3, 4, 6, 5
    table = rng.normal(size=(vocab, 1 + field_num * k)).astype(np.float32) * 0.1
    ids = rng.integers(0, vocab, size=(b, f)).astype(np.int32)
    vals = rng.normal(size=(b, f)).astype(np.float32)
    fields = rng.integers(0, field_num, size=(b, f)).astype(np.int32)
    params = fm.FmParams(w0=jnp.float32(0.1), table=jnp.asarray(table))
    got = fm.fm_scores(
        params,
        jnp.asarray(ids),
        jnp.asarray(vals),
        jnp.asarray(fields),
        factor_num=k,
        field_num=field_num,
    )
    want = brute_force_ffm(0.1, table, ids, vals, fields, k, field_num)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_loss_logistic_gradient_finite_diff(rng):
    cfg = FmConfig(vocabulary_size=20, factor_num=3, loss_type="logistic")
    params = fm.init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(rng.integers(0, 20, size=(4, 3)), jnp.int32)
    vals = jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)
    labels = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    weights = jnp.ones((4,))

    def f(p):
        loss, _ = fm.loss_and_metrics(p, labels, ids, vals, None, weights, cfg)
        return loss

    g = jax.grad(f)(params)
    # Finite-difference check on w0.
    eps = 1e-3
    up = f(params._replace(w0=params.w0 + eps))
    dn = f(params._replace(w0=params.w0 - eps))
    np.testing.assert_allclose(g.w0, (up - dn) / (2 * eps), rtol=1e-3, atol=1e-4)


def test_loss_weights_mask_padded_examples(rng):
    cfg = FmConfig(vocabulary_size=20, factor_num=3)
    params = fm.init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(rng.integers(0, 20, size=(4, 3)), jnp.int32)
    vals = jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)
    labels = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    # Padded tail example (weight 0) must not affect the loss.
    w_full = jnp.asarray([1.0, 1.0, 1.0, 0.0])
    loss_a, _ = fm.loss_and_metrics(
        params, labels, ids, vals, None, w_full, cfg
    )
    loss_b, _ = fm.loss_and_metrics(
        params,
        labels.at[3].set(123.0),
        ids,
        vals,
        None,
        w_full,
        cfg,
    )
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)


def test_l2_modes(rng):
    ids = jnp.asarray(rng.integers(0, 20, size=(4, 3)), jnp.int32)
    vals = jnp.ones((4, 3), jnp.float32)
    labels = jnp.zeros((4,))
    weights = jnp.ones((4,))
    for mode in ("batch", "full"):
        cfg = FmConfig(
            vocabulary_size=20,
            factor_num=3,
            factor_lambda=0.1,
            bias_lambda=0.05,
            l2_mode=mode,
        )
        params = fm.init_params(jax.random.PRNGKey(0), cfg)
        loss, aux = fm.loss_and_metrics(params, labels, ids, vals, None, weights, cfg)
        assert float(aux["reg"]) > 0
        assert float(loss) > float(aux["data_loss"])
