"""ops.sparse_apply (tile-scan Pallas apply) vs the XLA scatter path.

The tile path must reproduce the scatter path's semantics exactly (up to
the ~1e-6 relative error of its bf16 hi/lo matmul splits): per-occurrence
Adagrad accumulator updates with a shared post-update denominator for
duplicates, FTRL's single -sigma*w correction per row, and correct
handling of hot ids whose occurrence runs span many K1 chunks.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.ops import sparse_apply
from fast_tffm_tpu.train import sparse as sparse_lib


V, D = 2048, 9  # vocab divisible by TILE


def _ids_grads(seed, n, hot=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, V, size=n).astype(np.int32)
    if hot:
        ids[:hot] = 77  # one id with `hot` duplicate occurrences
    g = rng.uniform(-1, 1, size=(n, D)).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(g)


def _table(seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.uniform(-0.1, 0.1, (V, D)).astype(np.float32))


@pytest.mark.parametrize("hot", [0, 700, 1300])
def test_adagrad_matches_scatter(hot):
    ids, g = _ids_grads(0, 1200, hot)
    table = _table(1)
    acc = jnp.full((V, D), 0.1, jnp.float32)
    lr, eps = 0.05, sparse_lib.ADAGRAD_EPS

    t_tile, a_tile = sparse_apply.adagrad_apply(
        table, acc, ids, g, lr=lr, eps=eps
    )
    a_ref = acc.at[ids].add(g * g)
    t_ref = table.at[ids].add(-lr * g * jax.lax.rsqrt(a_ref[ids] + eps))

    np.testing.assert_allclose(t_tile, t_ref, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(a_tile, a_ref, rtol=1e-4, atol=1e-4)


def test_sgd_matches_scatter():
    ids, g = _ids_grads(2, 1024, hot=200)
    table = _table(3)
    t_tile = sparse_apply.sgd_apply(table, ids, g, lr=0.1)
    t_ref = table.at[ids].add(-0.1 * g)
    np.testing.assert_allclose(t_tile, t_ref, rtol=1e-4, atol=1e-6)


def test_ftrl_matches_scatter_path():
    """Full-step comparison: tile vs scatter through sparse_step."""
    cfg_base = dict(
        vocabulary_size=V, factor_num=D - 1, max_features=8, batch_size=64,
        optimizer="ftrl", learning_rate=0.05, ftrl_l1=0.01, ftrl_l2=0.1,
        ftrl_beta=1.0, adagrad_initial_accumulator=0.1,
    )
    rng = np.random.default_rng(4)
    batch = Batch(
        labels=rng.integers(0, 2, 64).astype(np.float32),
        ids=rng.integers(0, V, (64, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (64, 8)).astype(np.float32),
        fields=np.zeros((64, 8), np.int32),
        weights=np.ones((64,), np.float32),
    )
    batch = jax.tree.map(jnp.asarray, batch)

    results = {}
    for mode in ("tile", "scatter"):
        cfg = FmConfig(sparse_apply=mode, **cfg_base)
        from fast_tffm_tpu.models import fm
        params = fm.init_params(jax.random.PRNGKey(0), cfg)
        opt = sparse_lib.init_sparse_opt_state(cfg, params)
        for _ in range(3):
            params, opt, _ = jax.jit(
                lambda p, o, b, cfg=cfg: sparse_lib.sparse_step(cfg, p, o, b)
            )(params, opt, batch)
        results[mode] = (params, opt)

    p_t, o_t = results["tile"]
    p_s, o_s = results["scatter"]
    np.testing.assert_allclose(p_t.table, p_s.table, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(p_t.w0, p_s.w0, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(o_t.z.table, o_s.z.table, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o_t.n.table, o_s.n.table, rtol=1e-4, atol=1e-5)


def test_adagrad_multi_step_training_converges():
    """Loss decreases over tile-apply steps on a learnable pattern."""
    cfg = FmConfig(
        vocabulary_size=V, factor_num=D - 1, max_features=4, batch_size=128,
        optimizer="adagrad", learning_rate=0.1, sparse_apply="tile",
    )
    from fast_tffm_tpu.models import fm
    rng = np.random.default_rng(5)
    params = fm.init_params(jax.random.PRNGKey(1), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)
    step = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )
    ids = rng.integers(0, V, (128, 4)).astype(np.int32)
    labels = (ids[:, 0] % 2).astype(np.float32)  # learnable from feature id
    batch = Batch(
        labels=jnp.asarray(labels),
        ids=jnp.asarray(ids),
        vals=jnp.ones((128, 4), jnp.float32),
        fields=jnp.zeros((128, 4), jnp.int32),
        weights=jnp.ones((128,), jnp.float32),
    )
    def loss_of(params):
        scores = fm.fm_scores(
            params, batch.ids, batch.vals, factor_num=cfg.factor_num
        )
        return float(jnp.mean(
            fm.example_losses(scores, batch.labels, "logistic")
        ))
    before = loss_of(params)
    for _ in range(60):
        params, opt, _ = step(params, opt, batch)
    after = loss_of(params)
    assert after < before - 0.1, (before, after)


@pytest.mark.parametrize(
    "chunk,tile,group",
    [
        (256, 512, 1),   # ungrouped K2: one window per grid step
        (1024, 256, 2),  # minimal double-buffer rotation
        (256, 128, 16),  # large unrolled loop (16 of V/128 = 16 tiles)
    ],
)
def test_adagrad_matches_scatter_alternate_blocks(chunk, tile, group):
    """The tunable CHUNK/TILE/GROUP candidates must stay numerically
    exact, not just compile: the hardware sweep would otherwise crown a
    fast-but-wrong block size.  Hot ids span multiple chunks at both
    chunk sizes."""
    orig = sparse_apply.CHUNK, sparse_apply.TILE, sparse_apply.GROUP
    sparse_apply.CHUNK = chunk
    sparse_apply.TILE = tile
    sparse_apply.GROUP = group
    try:
        # n leaves plenty of non-hot ids at both chunk sizes: the hot
        # run spans 2+ chunks AND chunks still mix distinct ids (an
        # all-one-id batch would degenerate the placement coverage).
        ids, g = _ids_grads(3, 4096, hot=chunk * 2 + 100)
        table = _table(0)
        acc = jnp.full((V, D), 0.1, jnp.float32)
        t_tile, a_tile = sparse_apply.adagrad_apply(
            table, acc, ids, g, lr=0.1, eps=1e-7
        )
        a_ref = acc.at[ids].add(g * g)
        t_ref = table.at[ids].add(
            -0.1 * g * jax.lax.rsqrt(a_ref[ids] + 1e-7)
        )
        # atol 5e-6: the bf16 hi/lo-split one-hot matmuls accumulate in
        # different orders per chunk size (~1e-6 jitter); real block-size
        # logic errors (mis-placed carries/windows) show at 1e-2+.
        np.testing.assert_allclose(t_tile, t_ref, rtol=2e-5, atol=5e-6)
        np.testing.assert_allclose(a_tile, a_ref, rtol=2e-5, atol=5e-6)
    finally:
        sparse_apply.CHUNK, sparse_apply.TILE, sparse_apply.GROUP = orig


def test_adagrad_exact_at_odd_group():
    """Odd group sizes end the unrolled loop on the opposite buffer slot;
    the slot/semaphore rotation must still line up.  Needs a non-power-
    of-two tile count (1536/256 = 6, group 3)."""
    v = 1536
    orig = sparse_apply.GROUP
    sparse_apply.GROUP = 3
    try:
        rng = np.random.default_rng(11)
        ids = jnp.asarray(rng.integers(0, v, (1200,)), jnp.int32)
        g = jnp.asarray(rng.uniform(-1, 1, (1200, D)), jnp.float32)
        table = jnp.asarray(rng.uniform(-1, 1, (v, D)), jnp.float32)
        acc = jnp.full((v, D), 0.1, jnp.float32)
        t_tile, a_tile = sparse_apply.adagrad_apply(
            table, acc, ids, g, lr=0.1, eps=1e-7
        )
        a_ref = acc.at[ids].add(g * g)
        t_ref = table.at[ids].add(
            -0.1 * g * jax.lax.rsqrt(a_ref[ids] + 1e-7)
        )
        np.testing.assert_allclose(t_tile, t_ref, rtol=2e-5, atol=5e-6)
        np.testing.assert_allclose(a_tile, a_ref, rtol=2e-5, atol=5e-6)
    finally:
        sparse_apply.GROUP = orig


def test_group_for_clamps_to_divisor():
    """GROUP is a preference; the kernel needs a divisor of the tile
    count (and at least 1)."""
    orig = sparse_apply.GROUP
    try:
        sparse_apply.GROUP = 16
        assert sparse_apply._group_for(8) == 8    # clamp to n_tiles
        assert sparse_apply._group_for(12) == 12  # 16>12 -> clamp, divides
        assert sparse_apply._group_for(48) == 16  # fits and divides
        sparse_apply.GROUP = 5
        assert sparse_apply._group_for(8) == 4    # 5 does not divide 8
        sparse_apply.GROUP = 7
        assert sparse_apply._group_for(13) == 1   # prime tile count
    finally:
        sparse_apply.GROUP = orig


def test_supports_tile_gating():
    assert sparse_apply.supports_tile(2048, "adagrad")
    assert not sparse_apply.supports_tile(100, "adagrad")  # not TILE-aligned
    assert not sparse_apply.supports_tile(2048, "adam")
    assert sparse_apply.supports_tile_sharded(4096, "adagrad", 2)
    assert not sparse_apply.supports_tile_sharded(2048, "ftrl", 16)


@pytest.mark.parametrize("exchange", ["dense", "entries"])
@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_adagrad_sharded_matches_scatter(shape, exchange):
    """Sharded tile apply on a (data, model) virtual mesh == scatter,
    for both the dense-delta psum and the batch-proportional entries
    exchange."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    V_s = 4096  # divisible by model_shards * TILE for model <= 16
    devs = np.array(jax.devices()[:8]).reshape(shape)
    mesh = Mesh(devs, ("data", "model"))
    ids, g = _ids_grads(7, 2048, hot=500)
    rng = np.random.default_rng(8)
    table = jnp.asarray(rng.uniform(-0.1, 0.1, (V_s, D)).astype(np.float32))
    acc = jnp.full((V_s, D), 0.1, jnp.float32)
    ids = ids % V_s
    lr, eps = 0.05, sparse_lib.ADAGRAD_EPS

    table_sh = jax.device_put(table, NamedSharding(mesh, P("model", None)))
    acc_sh = jax.device_put(acc, NamedSharding(mesh, P("model", None)))
    ids_sh = jax.device_put(ids, NamedSharding(mesh, P("data")))
    g_sh = jax.device_put(g, NamedSharding(mesh, P("data", None)))

    t_tile, a_tile = jax.jit(
        lambda t, a, i, g: sparse_apply.adagrad_apply_sharded(
            t, a, i, g, lr=lr, eps=eps, mesh=mesh,
            data_axis="data", model_axis="model", exchange=exchange,
        )
    )(table_sh, acc_sh, ids_sh, g_sh)

    a_ref = acc.at[ids].add(g * g)
    t_ref = table.at[ids].add(-lr * g * jax.lax.rsqrt(a_ref[ids] + eps))
    np.testing.assert_allclose(t_tile, t_ref, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(a_tile, a_ref, rtol=1e-4, atol=1e-4)


def test_full_sparse_step_sharded_tile():
    """sparse_step with tile apply on a 4x2 mesh == single-device scatter."""
    from jax.sharding import Mesh

    V_s = 2048
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("data", "model"))
    cfg = FmConfig(
        vocabulary_size=V_s, factor_num=D - 1, max_features=8,
        batch_size=64, optimizer="adagrad", learning_rate=0.05,
        sparse_apply="tile", mesh_data=4, mesh_model=2,
    )
    rng = np.random.default_rng(9)
    batch = Batch(
        labels=rng.integers(0, 2, 64).astype(np.float32),
        ids=rng.integers(0, V_s, (64, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (64, 8)).astype(np.float32),
        fields=np.zeros((64, 8), np.int32),
        weights=np.ones((64,), np.float32),
    )
    from fast_tffm_tpu.models import fm
    from fast_tffm_tpu.parallel import mesh as mesh_lib

    params0 = fm.init_params(jax.random.PRNGKey(0), cfg)
    results = {}
    for mode, m in (("tile", mesh), ("scatter", None)):
        cfg_m = FmConfig(**{**cfg.__dict__, "sparse_apply": mode,
                            "train_files": [], "weight_files": [],
                            "validation_files": [], "predict_files": []})
        params = params0
        opt = sparse_lib.init_sparse_opt_state(cfg_m, params)
        if m is not None:
            params = mesh_lib.shard_params(params, m)
            b = mesh_lib.shard_batch(jax.tree.map(jnp.asarray, batch), m)
        else:
            b = jax.tree.map(jnp.asarray, batch)
        step = jax.jit(
            lambda p, o, bb, c=cfg_m, mm=m: sparse_lib.sparse_step(
                c, p, o, bb, mesh=mm
            )
        )
        for _ in range(2):
            params, opt, _ = step(params, opt, b)
        results[mode] = params
    np.testing.assert_allclose(
        results["tile"].table, results["scatter"].table,
        rtol=1e-4, atol=1e-6,
    )


# ------------------------- compact K2 (touched-tile streaming) and entries


def test_compact_k2_bit_identical_all_optimizers():
    """Compact K2 (touched-group grid + alias-through) must be
    bit-identical to the full-streaming K2: the same kernel body runs on
    the same windows, and unvisited blocks pass through untouched.

    FTRL's table must satisfy the training invariant w == ftrl_solve(z,
    n) (train.sparse's z-init maintains it from step 0): the full sweep
    RECOMPUTES w for every row while compact skips untouched rows, so
    the two agree exactly when — and only when — the invariant holds.
    This is also why ftrl_apply documents the invariant as a contract.
    """
    V_big = 1 << 14  # 64 tiles -> 8 groups: most untouched below
    rng = np.random.default_rng(0)
    ids = jnp.asarray(
        np.array([5, 5, 7, 300, 301, 4000] * 40, np.int32)
    )  # tiles {0, 1, 15} only
    g = jnp.asarray(rng.uniform(-1, 1, (240, D)).astype(np.float32))
    table = jnp.asarray(rng.uniform(-0.1, 0.1, (V_big, D)).astype(np.float32))
    acc = jnp.full((V_big, D), 0.1, jnp.float32)
    z = jnp.asarray(rng.uniform(-1, 1, (V_big, D)).astype(np.float32))
    n = jnp.full((V_big, D), 0.5, jnp.float32)
    lr, l1, l2, beta = 0.1, 0.01, 0.1, 1.0
    table_f = sparse_apply.ftrl_solve(z, n, lr, l1, l2, beta)  # invariant

    for make in (
        lambda c: sparse_apply.adagrad_apply(
            table, acc, ids, g, lr=0.1, eps=1e-7, compact=c
        ),
        lambda c: (sparse_apply.sgd_apply(table, ids, g, lr=0.1, compact=c),),
        lambda c: sparse_apply.ftrl_apply(
            table_f, z, n, ids, g, lr=lr, l1=l1, l2=l2, beta=beta, compact=c
        ),
    ):
        full = make(False)
        comp = make(True)
        for a, b in zip(full, comp):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compact_groups_mapping():
    """_compact_groups: touched groups in order, fillers point at an
    untouched group (re-applying an untouched group is the identity;
    re-applying a touched one would double-apply)."""
    group, n_tiles = 2, 8  # 4 groups of 2 tiles
    # entries: 3 in tile 0, 1 in tile 5 -> groups 0 and 2 touched
    tile_start = jnp.asarray([0, 3, 3, 3, 3, 3, 4, 4, 4], jnp.int32)
    comp = np.asarray(sparse_apply._compact_groups(
        tile_start, n_tiles // group, group, t_max=4
    ))
    assert list(comp[:2]) == [0, 2]  # touched, ascending
    assert all(c == comp[2] for c in comp[2:])  # one filler, repeated
    assert comp[2] in (1, 3)  # filler untouched


def test_compact_heuristic_static():
    """_compact_auto engages only when entries bound touched groups to
    <= half the table's groups, and _k2_call's grid obeys the decision
    (probed from the traced pallas_call grids, like the cost model)."""
    assert not sparse_apply._compact_auto(n_entries=512, n_groups=8)
    assert not sparse_apply._compact_auto(n_entries=512, n_groups=1000)
    assert sparse_apply._compact_auto(n_entries=512, n_groups=1024)
    assert sparse_apply._compact_auto(n_entries=4, n_groups=8)

    def k2_grids(vocab, n_ids, compact):
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, vocab, n_ids), np.int32
        )
        g = jnp.ones((n_ids, D), jnp.float32)
        table = jnp.zeros((vocab, D), jnp.float32)
        closed = jax.make_jaxpr(
            lambda t, i, gg: sparse_apply.sgd_apply(
                t, i, gg, lr=0.1, compact=compact
            )
        )(table, ids, g)
        grids = set()
        for j in _walk(closed.jaxpr):
            for eqn in j.eqns:
                if eqn.primitive.name == "pallas_call":
                    gm = eqn.params.get("grid_mapping")
                    if gm is not None and len(gm.grid) == 1:
                        grids.add(gm.grid[0])
        return grids

    def _walk(jaxpr):
        yield jaxpr
        for eqn in jaxpr.eqns:
            for val in eqn.params.values():
                for v in (val if isinstance(val, (list, tuple)) else (val,)):
                    inner = getattr(v, "jaxpr", None)
                    if inner is not None:
                        yield from _walk(inner)
                    elif hasattr(v, "eqns"):
                        yield from _walk(inner if inner else v)

    # auto at V=2^21, 200 ids: n_pad 512 < 1024 groups -> compact grid
    # (512) present, full-vocab grid (1024) absent; auto at V=2^14 ->
    # the vocab-bound grid (8) present.
    group = sparse_apply._group_for((1 << 21) // sparse_apply.TILE)
    n_groups = (1 << 21) // (sparse_apply.TILE * group)
    assert 512 in k2_grids(1 << 21, 200, None)
    assert n_groups not in k2_grids(1 << 21, 200, None)
    small_groups = (1 << 14) // (sparse_apply.TILE * sparse_apply._group_for(
        (1 << 14) // sparse_apply.TILE))
    assert small_groups in k2_grids(1 << 14, 200, None)


def test_unique_entries_and_merge_match_dense_delta():
    """unique_entries -> (gather) -> merge_entries must produce the same
    per-row (sum g, sum g²) totals as the dense K-place delta."""
    rng = np.random.default_rng(4)
    vocab = 2048
    shards = []
    cap = sparse_apply.entries_cap(600, vocab)
    dense_sum = jnp.zeros((vocab, 2 * D), jnp.float32)
    rows_all, pay_all = [], []
    for s in range(4):  # simulate 4 data shards
        ids = rng.integers(0, vocab, 600).astype(np.int32)
        ids[:100] = 77  # hot id shared across shards
        g = rng.uniform(-1, 1, (600, D)).astype(np.float32)
        rows, pay, count = sparse_apply.unique_entries(
            jnp.asarray(ids), jnp.asarray(g), vocab=vocab, cap=cap
        )
        assert int(count) <= cap
        rows_all.append(rows)
        pay_all.append(pay)
        dense_sum = dense_sum + sparse_apply.dense_delta(
            jnp.asarray(ids), jnp.asarray(g),
            vocab=vocab, vocab_local=vocab, row_lo=0,
        )
    u, ts = sparse_apply.merge_entries(
        jnp.concatenate(rows_all), jnp.concatenate(pay_all, axis=0),
        vocab=vocab,
    )
    # Apply both deltas with SGD (linear in g1: exposes placement errors).
    table = jnp.zeros((vocab, D), jnp.float32)
    (t_entries,) = sparse_apply.k2_apply(
        functools.partial(sparse_apply.sgd_update, lr=1.0),
        ts, u, (table,),
    )
    t_dense = -dense_sum[:, :D]
    np.testing.assert_allclose(
        np.asarray(t_entries), np.asarray(t_dense), rtol=1e-5, atol=1e-5
    )


def test_unique_entries_sentinel_padding():
    """Entries beyond the touched count must be sentinels (row == vocab,
    zero payload) so the merge sorts them out of coverage."""
    vocab = 2048
    ids = jnp.asarray(np.array([3, 3, 3, 9], np.int32))
    g = jnp.ones((4, D), jnp.float32)
    cap = sparse_apply.entries_cap(4, vocab)
    rows, pay, count = sparse_apply.unique_entries(
        ids, g, vocab=vocab, cap=cap
    )
    assert int(count) == 2
    rows = np.asarray(rows)
    pay = np.asarray(pay)
    assert list(rows[:2]) == [3, 9]
    assert (rows[2:] == vocab).all()
    assert (pay[2:] == 0).all()
    np.testing.assert_allclose(pay[0, :D], 3.0)   # sum g over 3 dups
    np.testing.assert_allclose(pay[0, D:], 3.0)   # sum g² over 3 dups
    np.testing.assert_allclose(pay[1, :D], 1.0)


@pytest.mark.parametrize("optimizer", ["sgd", "ftrl"])
def test_sgd_ftrl_sharded_entries_match_single_device(optimizer):
    """sgd (the n_tables==1 tuple-wrapping path) and ftrl (3 tables)
    through the GSPMD sharded apply with exchange=entries must match the
    single-device tile apply (itself scatter-parity-tested above).
    FTRL's table honors the w == ftrl_solve(z, n) invariant contract."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    V_s = 4096
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "model"))
    rng = np.random.default_rng(12)
    ids = jnp.asarray(rng.integers(0, V_s, (2048,)), jnp.int32)
    g = jnp.asarray(rng.uniform(-1, 1, (2048, D)), jnp.float32)
    lr, l1, l2, beta = 0.05, 0.01, 0.1, 1.0
    sh_m = NamedSharding(mesh, P("model", None))
    sh_d = NamedSharding(mesh, P("data"))
    sh_dn = NamedSharding(mesh, P("data", None))

    if optimizer == "sgd":
        table = jnp.asarray(rng.uniform(-0.1, 0.1, (V_s, D)), jnp.float32)
        t_ref = sparse_apply.sgd_apply(table, ids, g, lr=lr)
        t_sh = jax.jit(
            lambda t, i, gg: sparse_apply.sgd_apply_sharded(
                t, i, gg, lr=lr, mesh=mesh, data_axis="data",
                model_axis="model", exchange="entries",
            )
        )(jax.device_put(table, sh_m), jax.device_put(ids, sh_d),
          jax.device_put(g, sh_dn))
        # rtol 1e-4 like the other sharded parity tests: the merged
        # streams sum cross-shard partials in a different order than the
        # single-device K1.
        np.testing.assert_allclose(
            np.asarray(t_sh), np.asarray(t_ref), rtol=1e-4, atol=1e-5
        )
    else:
        z = jnp.asarray(rng.uniform(-1, 1, (V_s, D)), jnp.float32)
        n = jnp.full((V_s, D), 0.5, jnp.float32)
        table = sparse_apply.ftrl_solve(z, n, lr, l1, l2, beta)
        refs = sparse_apply.ftrl_apply(
            table, z, n, ids, g, lr=lr, l1=l1, l2=l2, beta=beta
        )
        shs = jax.jit(
            lambda t, zz, nn, i, gg: sparse_apply.ftrl_apply_sharded(
                t, zz, nn, i, gg, lr=lr, l1=l1, l2=l2, beta=beta,
                mesh=mesh, data_axis="data", model_axis="model",
                exchange="entries",
            )
        )(jax.device_put(table, sh_m), jax.device_put(z, sh_m),
          jax.device_put(n, sh_m), jax.device_put(ids, sh_d),
          jax.device_put(g, sh_dn))
        for a, b in zip(shs, refs):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )
