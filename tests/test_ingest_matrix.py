"""Fast ingest-equivalence matrix (tier-1, not slow): raw/line path ×
thread/process workers × cache off/on/prestacked on a tiny synthetic
libsvm file.

Every mode must deliver element-wise IDENTICAL batches in identical
(ordered) delivery order with identical epoch markers — a regression in
any ingest mode (parse content, sequencing, marker placement, cache
replay coverage) fails tier-1 here instead of surfacing as a training
drift on hardware.  The module also pins the two resource guarantees of
the SHM paths: descriptor-only work messages when the inbound ring is
on (raw window bytes never cross the worker queue), and zero leaked
/dev/shm segments once every pipeline in the module has torn down.
"""

import os

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import BatchPipeline, EpochEnd, SuperBatch


def _shm_listing():
    return {
        n for n in os.listdir("/dev/shm")
        if n.startswith(("psm_", "tffm"))
    }


@pytest.fixture(scope="module", autouse=True)
def no_leaked_shm_segments():
    """Every test in this module spins up SHM-using pipelines (worker
    result segments + the inbound ring); after they ALL finish, /dev/shm
    must hold nothing new — the tier-1 leak check for procpool's
    unlink-on-every-exit-path contract."""
    before = _shm_listing()
    yield
    leaked = _shm_listing() - before
    assert not leaked, f"leaked /dev/shm segments: {sorted(leaked)}"


def _write_data(path, lines=60):
    rng = np.random.default_rng(7)
    with open(path, "w") as f:
        for _ in range(lines):
            toks = " ".join(
                f"{rng.integers(0, 99)}:{rng.uniform(0, 2):.4f}"
                for _ in range(rng.integers(1, 5))
            )
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def data_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("matrix")
    return _write_data(d / "d.libsvm")


@pytest.fixture(scope="module")
def big_data_file(tmp_path_factory):
    """Enough lines that window bytes dwarf descriptor bytes — the
    payload-accounting test needs a real margin."""
    d = tmp_path_factory.mktemp("matrix_big")
    return _write_data(d / "big.libsvm", lines=2000)


def _cfg(**kw):
    defaults = dict(
        vocabulary_size=100, batch_size=8, max_features=4, thread_num=2,
        queue_size=4, shuffle_buffer=16,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _stream(path, cfg, cache, prestack_k=0, telemetry=None):
    """Flattened delivery: SuperBatch items unpack to their per-batch
    tuples, so streams compare element-wise across storage formats."""
    out = []
    pipe = BatchPipeline(
        [path], cfg, epochs=2, shuffle=True, seed=11, ordered=True,
        cache_epochs=cache, prestack_k=prestack_k, epoch_marks=True,
        telemetry=telemetry,
    )
    for b in pipe:
        if isinstance(b, EpochEnd):
            out.append(("mark", b.epoch))
            continue
        if isinstance(b, SuperBatch):
            sb = b.batch
            for i in range(b.n):
                out.append((
                    sb.labels[i].tobytes(), sb.ids[i].tobytes(),
                    sb.vals[i].tobytes(), sb.fields[i].tobytes(),
                    sb.weights[i].tobytes(),
                ))
            continue
        out.append((
            b.labels.tobytes(), b.ids.tobytes(), b.vals.tobytes(),
            b.fields.tobytes(), b.weights.tobytes(),
        ))
    return out


# mode -> (cache_epochs, prestack_k)
_MODES = {"stream": (False, 0), "cache": (True, 0), "prestack": (True, 3)}


@pytest.mark.parametrize("mode", list(_MODES), ids=list(_MODES))
@pytest.mark.parametrize("fast_ingest", [True, False], ids=["raw", "line"])
def test_process_workers_match_threads(data_file, fast_ingest, mode):
    """parse_processes output is element-wise identical to the
    in-process parser — same batches, same ordered delivery, same epoch
    markers — for every (ingest path × cache storage) combination.
    The procs run exercises the SHM ring on the raw path (ring_slots
    default > 0)."""
    cache, k = _MODES[mode]
    threads = _stream(
        data_file, _cfg(fast_ingest=fast_ingest), cache, prestack_k=k
    )
    procs = _stream(
        data_file, _cfg(fast_ingest=fast_ingest, parse_processes=2),
        cache, prestack_k=k,
    )
    assert threads == procs
    assert threads[-1] == ("mark", 1)  # both epochs end in their marker
    assert ("mark", 0) in threads


def test_cache_replays_epoch0_batches(data_file):
    """Cache on: epoch 1 is a permutation of epoch 0's parsed batches;
    cache off: epoch 1 reshuffles at LINE granularity (different
    batches).  Epoch 0 is byte-identical either way."""
    on = _stream(data_file, _cfg(), True)
    off = _stream(data_file, _cfg(), False)
    m = on.index(("mark", 0))
    assert on[:m + 1] == off[:m + 1]
    e1_on = [x for x in on[m + 1:] if x[0] != "mark"]
    e1_off = [x for x in off[m + 1:] if x[0] != "mark"]
    assert sorted(e1_on) == sorted(on[:m])  # replay: same batch multiset
    assert e1_on != e1_off  # ...but streaming re-mixes lines


def test_prestacked_matches_batch_cache_epoch0_and_multiset(data_file):
    """Prestacked storage changes only the replay PERMUTATION
    granularity: epoch 0 is byte-identical to the batch cache (groups
    are stacked from the same delivered batches), and epoch 1 replays
    the same batch multiset — grouped, so consecutive runs of a group's
    batches stay in epoch-0 order."""
    plain = _stream(data_file, _cfg(), True)
    pre = _stream(data_file, _cfg(), True, prestack_k=3)
    m = plain.index(("mark", 0))
    assert pre[:m + 1] == plain[:m + 1]
    e1_pre = [x for x in pre[m + 1:] if x[0] != "mark"]
    e1_plain = [x for x in plain[m + 1:] if x[0] != "mark"]
    assert sorted(e1_pre) == sorted(e1_plain)
    assert e1_pre != e1_plain  # super-batch vs batch permutation


def test_ring_work_messages_are_descriptor_only(big_data_file):
    """THE zero-copy acceptance check: with the SHM ring on, raw window
    bytes never cross the worker queue — every window lands in a ring
    slot (no fallbacks here: windows fit the slot size) and the pickled
    work messages total a tiny fraction of the window bytes.  With
    ring_slots=0 the same run ships the windows through the queue."""
    tel = obs.Telemetry()
    ringed = _stream(
        big_data_file, _cfg(parse_processes=2, ring_slots=3), False,
        telemetry=tel,
    )
    c = tel.snapshot()["counters"]
    assert c["ingest.ring_windows"] >= 1
    assert c["ingest.ring_fallback_windows"] == 0
    window_bytes = c["ingest.ring_window_bytes"]
    msg_bytes = c["ingest.work_msg_bytes"]
    assert window_bytes > 0
    # Descriptors are slot ids + group sizes (+ the line-path epoch
    # marks); give them 5% headroom over the ~60 KB of window text.
    assert msg_bytes < 0.05 * window_bytes, (msg_bytes, window_bytes)

    tel_off = obs.Telemetry()
    plain = _stream(
        big_data_file, _cfg(parse_processes=2, ring_slots=0), False,
        telemetry=tel_off,
    )
    assert plain == ringed  # ring is a transport, not a semantic
    c_off = tel_off.snapshot()["counters"]
    assert c_off["ingest.ring_windows"] == 0
    # The fallback path pickles every window's bytes through the queue.
    assert c_off["ingest.work_msg_bytes"] > window_bytes


def test_oversized_window_falls_back_to_queue(data_file):
    """A ring whose slots are too small for the window must deliver the
    identical stream through the pickled fallback (counted, never
    wrong).  Forced here by monkeypatching the slot-size estimate down
    to a few bytes."""
    cfg = _cfg(parse_processes=2, ring_slots=2)
    tel = obs.Telemetry()
    pipe = BatchPipeline(
        [data_file], cfg, epochs=2, shuffle=True, seed=11, ordered=True,
        epoch_marks=True, telemetry=tel,
    )
    pipe._ring_slot_bytes = lambda: 32  # every window overflows
    out = []
    for b in pipe:
        if isinstance(b, EpochEnd):
            out.append(("mark", b.epoch))
        else:
            out.append((
                b.labels.tobytes(), b.ids.tobytes(), b.vals.tobytes(),
                b.fields.tobytes(), b.weights.tobytes(),
            ))
    assert out == _stream(data_file, _cfg(), False)
    c = tel.snapshot()["counters"]
    assert c["ingest.ring_windows"] == 0
    assert c["ingest.ring_fallback_windows"] >= 1


def test_worker_crash_raises_and_leaves_no_shm(data_file):
    """Killing a parse worker mid-run surfaces as a RuntimeError (not a
    hang) and the teardown sweep reclaims every tagged segment — the
    'worker crash' leg of the SHM hygiene contract."""
    import multiprocessing as mp

    before = _shm_listing()
    cfg = _cfg(parse_processes=2, queue_size=2, ring_slots=2)
    existing = set(mp.active_children())
    it = iter(BatchPipeline(
        [data_file], cfg, epochs=50, shuffle=True, ordered=True,
    ))
    next(it)
    workers = [p for p in mp.active_children() if p not in existing]
    assert workers, "no spawned parse workers found"
    for w in workers:
        w.kill()
    with pytest.raises(RuntimeError, match="parse worker died"):
        for _ in it:
            pass
    assert _shm_listing() - before == set()
