"""Fast ingest-equivalence matrix (tier-1, not slow): raw/line path ×
thread/process workers × cache on/off on a tiny synthetic libsvm file.

Every mode must deliver element-wise IDENTICAL batches in identical
(ordered) delivery order with identical epoch markers — a regression in
any ingest mode (parse content, sequencing, marker placement, cache
replay coverage) fails tier-1 here instead of surfacing as a training
drift on hardware.
"""

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import BatchPipeline, EpochEnd


@pytest.fixture(scope="module")
def data_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("matrix")
    path = d / "d.libsvm"
    rng = np.random.default_rng(7)
    with open(path, "w") as f:
        for _ in range(60):
            toks = " ".join(
                f"{rng.integers(0, 99)}:{rng.uniform(0, 2):.4f}"
                for _ in range(rng.integers(1, 5))
            )
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


def _cfg(**kw):
    defaults = dict(
        vocabulary_size=100, batch_size=8, max_features=4, thread_num=2,
        queue_size=4, shuffle_buffer=16,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _stream(path, cfg, cache):
    out = []
    pipe = BatchPipeline(
        [path], cfg, epochs=2, shuffle=True, seed=11, ordered=True,
        cache_epochs=cache, epoch_marks=True,
    )
    for b in pipe:
        if isinstance(b, EpochEnd):
            out.append(("mark", b.epoch))
        else:
            out.append((
                b.labels.tobytes(), b.ids.tobytes(), b.vals.tobytes(),
                b.fields.tobytes(), b.weights.tobytes(),
            ))
    return out


@pytest.mark.parametrize("cache", [False, True], ids=["stream", "cache"])
@pytest.mark.parametrize("fast_ingest", [True, False], ids=["raw", "line"])
def test_process_workers_match_threads(data_file, fast_ingest, cache):
    """parse_processes output is element-wise identical to the
    in-process parser — same batches, same ordered delivery, same epoch
    markers — for every (ingest path × cache) combination."""
    threads = _stream(data_file, _cfg(fast_ingest=fast_ingest), cache)
    procs = _stream(
        data_file, _cfg(fast_ingest=fast_ingest, parse_processes=2), cache
    )
    assert threads == procs
    assert threads[-1] == ("mark", 1)  # both epochs end in their marker
    assert ("mark", 0) in threads


def test_cache_replays_epoch0_batches(data_file):
    """Cache on: epoch 1 is a permutation of epoch 0's parsed batches;
    cache off: epoch 1 reshuffles at LINE granularity (different
    batches).  Epoch 0 is byte-identical either way."""
    on = _stream(data_file, _cfg(), True)
    off = _stream(data_file, _cfg(), False)
    m = on.index(("mark", 0))
    assert on[:m + 1] == off[:m + 1]
    e1_on = [x for x in on[m + 1:] if x[0] != "mark"]
    e1_off = [x for x in off[m + 1:] if x[0] != "mark"]
    assert sorted(e1_on) == sorted(on[:m])  # replay: same batch multiset
    assert e1_on != e1_off  # ...but streaming re-mixes lines
