"""Sparse row-update path: parity with the dense optax path + semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train import sparse
from fast_tffm_tpu.train.loop import Trainer


def _unique_batch(rng, cfg, batch_size):
    """Batch with globally unique ids: sparse == dense exactly."""
    total = batch_size * cfg.max_features
    ids = rng.permutation(cfg.vocabulary_size)[:total]
    return Batch(
        labels=rng.integers(0, 2, size=(batch_size,)).astype(np.float32),
        ids=ids.reshape(batch_size, cfg.max_features).astype(np.int32),
        vals=rng.uniform(0.1, 1.0,
                         size=(batch_size, cfg.max_features)).astype(np.float32),
        fields=np.zeros((batch_size, cfg.max_features), np.int32),
        weights=np.ones((batch_size,), np.float32),
    )


def _dup_batch(rng, cfg, batch_size):
    return Batch(
        labels=rng.integers(0, 2, size=(batch_size,)).astype(np.float32),
        ids=rng.integers(0, cfg.vocabulary_size,
                         size=(batch_size, cfg.max_features)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0,
                         size=(batch_size, cfg.max_features)).astype(np.float32),
        fields=np.zeros((batch_size, cfg.max_features), np.int32),
        weights=np.ones((batch_size,), np.float32),
    )


def _cfg(tmp_path, name, **kw):
    defaults = dict(
        vocabulary_size=4096, factor_num=4, max_features=8, batch_size=32,
        model_file=str(tmp_path / name), log_steps=0, learning_rate=0.1,
        factor_lambda=0.001, bias_lambda=0.001,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.mark.parametrize("optimizer", ["adagrad", "sgd"])
def test_sparse_matches_dense_on_unique_ids(tmp_path, optimizer):
    """With no duplicate ids in the batch, sparse and dense updates are the
    same math — tables must match to float tolerance."""
    rng = np.random.default_rng(0)
    cfg_s = _cfg(tmp_path, "s", optimizer=optimizer, sparse_update=True)
    cfg_d = _cfg(tmp_path, "d", optimizer=optimizer, sparse_update=False)
    batches = [_unique_batch(rng, cfg_s, cfg_s.batch_size) for _ in range(3)]

    ts = Trainer(cfg_s)
    td = Trainer(cfg_d)
    assert ts.sparse and not td.sparse
    for b in batches:
        ts.state = ts._train_step(ts.state, ts._put(b))
        td.state = td._train_step(td.state, td._put(b))

    np.testing.assert_allclose(
        np.asarray(ts.state.params.table), np.asarray(td.state.params.table),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        float(ts.state.params.w0), float(td.state.params.w0), rtol=1e-4,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        float(ts.state.metrics.loss_sum), float(td.state.metrics.loss_sum),
        rtol=1e-4,
    )


def test_sparse_ftrl_runs_and_learns(tmp_path):
    rng = np.random.default_rng(1)
    cfg = _cfg(tmp_path, "f", optimizer="ftrl", ftrl_l1=0.001)
    t = Trainer(cfg)
    assert t.sparse
    losses = []
    for _ in range(20):
        b = _dup_batch(rng, cfg, cfg.batch_size)
        # Plant an easy signal: label = 1 iff first feature value > 0.5.
        b = b._replace(labels=(b.vals[:, 0] > 0.55).astype(np.float32))
        t.state = t._train_step(t.state, t._put(b))
        losses.append(float(t.state.metrics.loss_sum))
    # Loss sum grows sub-linearly (per-batch loss decreasing).
    first = losses[4]
    last = losses[-1] - losses[-6]
    assert last < first


def test_sparse_only_touches_batch_rows(tmp_path):
    rng = np.random.default_rng(2)
    cfg = _cfg(tmp_path, "t", optimizer="adagrad")
    t = Trainer(cfg)
    before = np.asarray(t.state.params.table).copy()
    b = _dup_batch(rng, cfg, cfg.batch_size)
    t.state = t._train_step(t.state, t._put(b))
    after = np.asarray(t.state.params.table)
    touched = np.unique(b.ids)
    untouched = np.setdiff1d(np.arange(cfg.vocabulary_size), touched)
    np.testing.assert_array_equal(before[untouched], after[untouched])
    assert np.any(before[touched] != after[touched])


def test_sparse_duplicate_id_semantics(tmp_path):
    """Duplicates: accumulator gets each occurrence's g^2; update uses the
    shared post-update denominator (documented IndexedSlices semantics)."""
    cfg = FmConfig(
        vocabulary_size=8, factor_num=2, max_features=2, batch_size=1,
        learning_rate=0.1, optimizer="adagrad", sparse_update=True,
        adagrad_initial_accumulator=0.1, model_file="/tmp/unused_dup",
    )
    params = jax.tree.map(
        jnp.asarray,
        __import__("fast_tffm_tpu.models.fm", fromlist=["fm"]).FmParams(
            w0=jnp.zeros(()),
            table=jnp.ones((8, 3)) * 0.1,
        ),
    )
    opt = sparse.init_sparse_opt_state(cfg, params)
    batch = Batch(
        labels=np.array([1.0], np.float32),
        ids=np.array([[3, 3]], np.int32),  # same id twice
        vals=np.array([[1.0, 2.0]], np.float32),
        fields=np.zeros((1, 2), np.int32),
        weights=np.ones((1,), np.float32),
    )
    before = np.asarray(params.table).copy()
    p2, o2, scores = jax.jit(
        lambda p, o, b: sparse.sparse_step(cfg, p, o, b)
    )(params, opt, batch)
    # Accumulator for row 3 = init + g1^2 + g2^2 (elementwise).
    acc3 = np.asarray(o2.acc.table[3])
    assert np.all(acc3 > cfg.adagrad_initial_accumulator)
    # Row 3 changed; all other rows untouched.
    after = np.asarray(p2.table)
    assert np.any(after[3] != before[3])
    for r in [0, 1, 2, 4, 5, 6, 7]:
        np.testing.assert_array_equal(after[r], before[r])


def test_sparse_ftrl_matches_dense_on_unique_ids(tmp_path):
    """Duplicate-free batches: sparse FTRL == dense optax-path FTRL."""
    rng = np.random.default_rng(4)
    kw = dict(optimizer="ftrl", ftrl_l1=0.001, ftrl_l2=0.001,
              learning_rate=0.1)
    cfg_s = _cfg(tmp_path, "fs", sparse_update=True, **kw)
    cfg_d = _cfg(tmp_path, "fd", sparse_update=False, **kw)
    batches = [_unique_batch(rng, cfg_s, cfg_s.batch_size) for _ in range(3)]
    ts, td = Trainer(cfg_s), Trainer(cfg_d)
    assert ts.sparse and not td.sparse
    for b in batches:
        ts.state = ts._train_step(ts.state, ts._put(b))
        td.state = td._train_step(td.state, td._put(b))
    np.testing.assert_allclose(
        np.asarray(ts.state.params.table), np.asarray(td.state.params.table),
        rtol=1e-4, atol=1e-6,
    )


def test_sparse_ftrl_stable_under_heavy_duplicates(tmp_path):
    """Regression: per-occurrence -sigma*w scatter double-counted duplicate
    rows and diverged to NaN within a few hundred steps."""
    rng = np.random.default_rng(5)
    cfg = _cfg(tmp_path, "fdup", optimizer="ftrl", learning_rate=0.5,
               vocabulary_size=50)  # tiny vocab -> heavy duplicates
    t = Trainer(cfg)
    for _ in range(150):
        b = _dup_batch(rng, cfg, cfg.batch_size)
        t.state = t._train_step(t.state, t._put(b))
    table = np.asarray(t.state.params.table)
    assert np.all(np.isfinite(table))
    assert np.abs(table).max() < 10.0
    assert np.isfinite(float(t.state.metrics.loss_sum))


@pytest.mark.parametrize("d,m", [(4, 2), (1, 8)])
def test_sparse_sharded_matches_single_device(tmp_path, d, m):
    rng = np.random.default_rng(3)
    cfg1 = _cfg(tmp_path / "a", "m1", mesh_data=1, mesh_model=1)
    cfgN = _cfg(tmp_path / "b", "mN", mesh_data=d, mesh_model=m)
    batches = [_dup_batch(rng, cfg1, cfg1.batch_size) for _ in range(3)]
    t1 = Trainer(cfg1, mesh=mesh_lib.make_mesh(cfg1, jax.devices()[:1]))
    tN = Trainer(cfgN)
    assert t1.sparse and tN.sparse
    for b in batches:
        t1.state = t1._train_step(t1.state, t1._put(b))
        tN.state = tN._train_step(tN.state, tN._put(b))
    np.testing.assert_allclose(
        np.asarray(t1.state.params.table), np.asarray(tN.state.params.table),
        rtol=1e-4, atol=1e-6,
    )
