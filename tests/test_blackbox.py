"""Incident flight recorder + serve traffic capture (ISSUE 20
tentpole).

Pins:

  * the TFC1 capture container: write/read roundtrip byte-for-byte,
    sampling gate, rotation to ``<path>.1``, the in-memory tail
    rendered as a standalone capture, truncated-final-record drop;
  * the :class:`Blackbox` bundle contract: artifact set + the
    ``record: incident`` manifest schema, rings stay FIXED-memory
    under unbounded load, same-second collisions ordinal-retry,
    rank/replica suffixes never collide, the bundle cap, the disabled
    recorder is a no-op;
  * alert integration: an ``AlertEngine`` breach through ``on_alert``
    dumps an ``alert_<rule>`` bundle that CONTAINS the breaching
    record (ring-before-observe ordering), and ``active_snapshot``'s
    ``alerts`` block renders as ``tffm_alert_active{rule="..."}``;
  * resource vitals: ``uptime_s`` + ``open_fds`` in the basic block,
    and their alert aliases gated on ``resource_metrics`` like the
    rest of the resource plane;
  * serving e2e: capture OFF is byte-identical to capture ON
    (both transports), a capture replays BITWISE against a fresh
    server via ``tools/replay.py``, and ``POST /incident`` dumps a
    bundle live (503 with the blackbox off).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.alerts import AlertEngine, parse_rules
from fast_tffm_tpu.obs.blackbox import (
    Blackbox, NULL_BLACKBOX, _sanitize_reason,
)
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.serve.server import serve
from fast_tffm_tpu.train.loop import Trainer

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import replay  # noqa: E402

V = 256
F = 4


def _cfg(tmp_path, **kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=F, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        predict_files=[str(tmp_path / "train.libsvm")],
        score_path=str(tmp_path / "scores.txt"),
        model_file=str(tmp_path / "model"),
        epoch_num=1, log_steps=0, thread_num=1, seed=3,
        serve_batch_sizes="32,64", max_batch_wait_ms=1.0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _write_data(path, rng, lines=256, vocab=V):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5 "
                f"{rng.integers(0, vocab)}:0.25\n"
            )


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One trained dense checkpoint shared by the serve e2e tests."""
    tmp_path = tmp_path_factory.mktemp("blackbox")
    _write_data(tmp_path / "train.libsvm", np.random.default_rng(0))
    cfg = _cfg(tmp_path)
    Trainer(cfg).train()
    return tmp_path, cfg


def _frame(rng, n=5, vocab=V, feat=F):
    ids = rng.integers(0, vocab, (n, feat)).astype(np.int32)
    vals = rng.uniform(0.1, 1.0, (n, feat)).astype(np.float32)
    return wire.encode_bin_request(ids, vals, None)


def _post(url, body, timeout=60):
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout).read()


# ----------------------------------------------------------------------
# TFC1 capture container (no jax, no sockets)
# ----------------------------------------------------------------------


class TestCaptureContainer:
    def test_roundtrip_bitwise(self, tmp_path):
        path = str(tmp_path / "req.capture")
        w = wire.CaptureWriter(path, sample=1.0, clock=lambda: 123.5)
        pairs = [(b"req-%d" % i * 3, b"resp-%d" % i) for i in range(7)]
        for req, resp in pairs:
            assert w.sample()
            w.write(req, resp)
        assert w.count == 7
        w.close()
        got = list(wire.read_capture(path))
        assert [(r, p) for _, r, p in got] == pairs
        assert all(t == 123.5 for t, _, _ in got)

    def test_sampling_gate(self, tmp_path):
        w = wire.CaptureWriter(str(tmp_path / "c"), sample=0.0)
        assert not any(w.sample() for _ in range(200))
        w.close()
        w = wire.CaptureWriter(str(tmp_path / "c2"), sample=1.0)
        assert all(w.sample() for _ in range(200))
        w.close()
        assert not w.sample()  # closed writer never samples

    def test_rotation_keeps_one_generation(self, tmp_path):
        path = str(tmp_path / "rot.capture")
        w = wire.CaptureWriter(path, sample=1.0, rotate_bytes=256)
        for i in range(40):
            w.write(b"q" * 16, bytes([i]) * 16)
        w.close()
        assert os.path.exists(path + ".1")
        # Both generations are valid standalone TFC1 files holding a
        # contiguous NEWEST-records window (older generations are
        # gone — a capture is a sliding window, not an archive).
        old = list(wire.read_capture(path + ".1"))
        new = list(wire.read_capture(path))
        assert old and len(old) + len(new) < 40
        got = [resp for _, _, resp in old + new]
        assert got == [bytes([i]) * 16 for i in
                       range(40 - len(got), 40)]

    def test_tail_bytes_is_a_standalone_capture(self, tmp_path):
        path = str(tmp_path / "t.capture")
        w = wire.CaptureWriter(path, sample=1.0, tail=4)
        for i in range(10):
            w.write(b"r%d" % i, b"s%d" % i)
        blob = w.tail_bytes()
        w.close()
        tail_path = str(tmp_path / "tail.capture")
        with open(tail_path, "wb") as f:
            f.write(blob)
        got = list(wire.read_capture(tail_path))
        assert [r for _, r, _ in got] == [b"r6", b"r7", b"r8", b"r9"]

    def test_truncated_final_record_dropped(self, tmp_path):
        path = str(tmp_path / "trunc.capture")
        w = wire.CaptureWriter(path, sample=1.0)
        for i in range(5):
            w.write(b"req" * 10, b"resp" * 10)
        w.close()
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 7)  # the writer died mid-append
        got = list(wire.read_capture(path))
        assert len(got) == 4  # intact prefix survives, no exception

    def test_bad_header_raises(self, tmp_path):
        path = str(tmp_path / "bad")
        with open(path, "wb") as f:
            f.write(b"NOPE\x01\x00\x00\x00")
        with pytest.raises(ValueError, match="magic"):
            list(wire.read_capture(path))

    def test_telemetry_counts_appends(self, tmp_path):
        tel = obs.Telemetry()
        w = wire.CaptureWriter(
            str(tmp_path / "c.capture"), sample=1.0, telemetry=tel
        )
        for _ in range(3):
            w.write(b"a", b"b")
        w.close()
        snap = tel.snapshot()
        assert snap["counters"]["serve.capture_requests"] == 3


# ----------------------------------------------------------------------
# Blackbox: bundle schema, rings, collisions, cap
# ----------------------------------------------------------------------


def _bb(tmp_path, **kw):
    kw.setdefault("suffix", "rank0")
    return Blackbox(str(tmp_path / "incidents"), **kw)


class TestBlackbox:
    def test_sanitize_reason(self):
        assert _sanitize_reason("alert_rss_mb>40000") == "alert_rss_mb_40000"
        assert _sanitize_reason("../../etc/passwd") == "etc_passwd"
        assert _sanitize_reason("") == "incident"
        assert len(_sanitize_reason("x" * 500)) == 64

    def test_bundle_schema(self, tmp_path):
        rows = []

        class W:
            def write(self, rec):
                rows.append(rec)

        bb = _bb(
            tmp_path,
            run_header={"record": "run_header", "batch_size": 32},
            metrics_render=lambda: "tffm_up 1\n",
            trace_tail_fn=lambda n: [{"ph": "X", "name": "t", "dur": 5}],
            capture_tail_fn=lambda: wire.CAPTURE_MAGIC + b"\x01\x00\x00\x00",
            writer=W(),
        )
        bb.observe_record({"record": "heartbeat", "step": 1})
        bb.observe_alert({"record": "alert", "rule": "r"})
        out = bb.incident("manual_test")
        assert out is not None and os.path.isdir(out)
        assert "_rank0" in os.path.basename(out)
        man = json.load(open(os.path.join(out, "manifest.json")))
        assert man["record"] == "incident"
        assert man["reason"] == "manual_test"
        assert man["suffix"] == "rank0"
        assert man["records"] == 1 and man["alerts"] == 1
        for name in ("records.jsonl", "alerts.jsonl", "threadz.txt",
                     "run_header.json", "trace_tail.json", "metrics.prom",
                     "requests.capture"):
            assert man["files"][name] is True
            assert os.path.exists(os.path.join(out, name)), name
        recs = [json.loads(ln) for ln in
                open(os.path.join(out, "records.jsonl"))]
        assert recs == [{"record": "heartbeat", "step": 1}]
        assert "--- thread" in open(os.path.join(out, "threadz.txt")).read()
        hdr = json.loads(open(os.path.join(out, "run_header.json")).read())
        assert hdr["batch_size"] == 32
        # The manifest is ALSO a metrics-stream record.
        assert rows and rows[-1]["record"] == "incident"

    def test_rings_fixed_memory(self, tmp_path):
        bb = _bb(tmp_path, records=16, alerts=8)
        for i in range(5000):
            bb.observe_record({"record": "heartbeat", "step": i})
            bb.observe_alert({"record": "alert", "i": i})
        assert len(bb._records) == 16
        assert len(bb._alerts) == 8
        out = bb.incident("load")
        recs = [json.loads(ln) for ln in
                open(os.path.join(out, "records.jsonl"))]
        # Oldest-first, and only the newest 16 survive.
        assert [r["step"] for r in recs] == list(range(4984, 5000))

    def test_same_second_collision_gets_ordinal(self, tmp_path):
        bb = _bb(tmp_path, clock=lambda: 1754000000.0)
        a = bb.incident("flap")
        b = bb.incident("flap")
        assert a != b and os.path.isdir(a) and os.path.isdir(b)
        assert os.path.basename(b) == os.path.basename(a) + "-2"

    def test_rank_replica_suffixes_never_collide(self, tmp_path):
        clock = lambda: 1754000000.0  # noqa: E731 - frozen clock
        dirs = set()
        for sfx in ("rank0", "rank1", "pid7", "router"):
            bb = Blackbox(
                str(tmp_path / "incidents"), suffix=sfx, clock=clock
            )
            out = bb.incident("oom")
            assert out is not None and sfx in os.path.basename(out)
            dirs.add(out)
        assert len(dirs) == 4

    def test_bundle_cap(self, tmp_path):
        bb = _bb(tmp_path, max_bundles=3, clock=lambda: 1754000000.0)
        outs = [bb.incident(f"r{i}") for i in range(6)]
        assert sum(o is not None for o in outs) == 3
        assert outs[3] is None and bb.dumped == 3

    def test_disabled_is_noop(self, tmp_path):
        bb = Blackbox(str(tmp_path / "inc"), enabled=False)
        bb.observe_record({"record": "heartbeat"})
        bb.on_alert({"record": "alert", "rule": "r"})
        assert bb.incident("nope") is None
        assert not os.path.exists(str(tmp_path / "inc"))
        assert NULL_BLACKBOX.incident("x") is None

    def test_broken_artifact_degrades_not_propagates(self, tmp_path):
        def boom():
            raise RuntimeError("metrics renderer died")

        bb = _bb(tmp_path, metrics_render=boom)
        bb.observe_record({"record": "heartbeat", "step": 9})
        out = bb.incident("partial")
        man = json.load(open(os.path.join(out, "manifest.json")))
        assert man["files"]["metrics.prom"] is False
        assert man["files"]["records.jsonl"] is True


# ----------------------------------------------------------------------
# Alert integration: breach -> bundle; the alerts block surface
# ----------------------------------------------------------------------


class TestAlertIntegration:
    def test_breach_dumps_bundle_with_evidence(self, tmp_path):
        bb = _bb(tmp_path)
        eng = AlertEngine(
            parse_rules("ingest_wait_frac > 0.5 : warn"),
            on_alert=bb.on_alert,
        )
        rec = {"record": "heartbeat", "step": 3,
               "ingest_wait_frac": 0.9, "time": 1.0}
        # Ring-before-observe: the breaching record must be IN the
        # bundle (the ordering every heartbeat loop follows).
        bb.observe_record(rec)
        fired = eng.observe(rec)
        assert len(fired) == 1
        inc_root = str(tmp_path / "incidents")
        bundles = os.listdir(inc_root)
        assert len(bundles) == 1
        assert bundles[0].split("_", 1)[1].startswith("alert_")
        out = os.path.join(inc_root, bundles[0])
        recs = [json.loads(ln) for ln in
                open(os.path.join(out, "records.jsonl"))]
        assert recs[-1]["step"] == 3
        alerts = [json.loads(ln) for ln in
                  open(os.path.join(out, "alerts.jsonl"))]
        assert alerts[-1]["rule"] == "ingest_wait_frac>0.5"

    def test_active_snapshot_shape(self):
        eng = AlertEngine(parse_rules(
            "ingest_wait_frac > 0.5 for 3 : warn ; rss_mb > 1 : halt"
        ))
        snap = eng.active_snapshot()
        assert snap["armed"] == 2
        assert snap["fired_total"] == 0 and snap["halted"] == 0
        assert [r["action"] for r in snap["rules"]] == ["warn", "halt"]
        beat = {"record": "heartbeat", "ingest_wait_frac": 0.9,
                "time": 1.0}
        eng.observe(beat)
        rule = eng.active_snapshot()["rules"][0]
        # Sustain 3: one breaching beat advances the streak but the
        # episode is not live yet.
        assert rule["active"] == 0 and rule["streak"] == 1
        eng.observe(beat)
        eng.observe(beat)
        rule = eng.active_snapshot()["rules"][0]
        assert rule["active"] == 1 and rule["streak"] == 3

    def test_alert_active_renders_labeled_gauge(self):
        eng = AlertEngine(parse_rules("ingest_wait_frac > 0.5 : warn"))
        eng.observe({"record": "heartbeat", "ingest_wait_frac": 0.9,
                     "time": 1.0})
        rec = {"record": "status", "alerts": eng.active_snapshot()}
        text = obs.render_prometheus(rec)
        assert ('tffm_alert_active{rule="ingest_wait_frac>0.5"} 1'
                in text)
        # The block scalars render like every other block's.
        assert "tffm_alerts_armed 1" in text
        assert "tffm_alerts_fired_total 1" in text

    def test_vitals_aliases_gated_on_resource_metrics(self, tmp_path):
        _write_data(tmp_path / "train.libsvm", np.random.default_rng(1), 8)
        ok = _cfg(tmp_path, heartbeat_secs=1.0,
                  alert_rules="uptime_s > 3600 : warn ; open_fds > 4096 : warn")
        assert ok.alert_rules  # resolves with the plane on (default)
        with pytest.raises(ValueError, match="resource_metrics"):
            _cfg(tmp_path, heartbeat_secs=1.0, resource_metrics=False,
                 alert_rules="uptime_s > 3600 : warn")


class TestResourceVitals:
    def test_read_open_fds(self):
        n = obs.read_open_fds()
        if not os.path.isdir("/proc/self/fd"):
            assert n == -1
        else:
            assert n > 0

    def test_basic_block(self):
        blk = obs.basic_block(0.0)
        assert blk["uptime_s"] > 0
        assert blk["rss_mb"] >= 0
        if os.path.isdir("/proc/self/fd"):
            assert blk["open_fds"] > 0


# ----------------------------------------------------------------------
# Serving e2e: capture off == on (byte-identical), capture -> replay
# bitwise, POST /incident
# ----------------------------------------------------------------------


class TestServeCapture:
    def test_capture_off_is_byte_identical(self, trained, rng):
        """The acceptance pin: turning capture + blackbox ON must not
        perturb a single response byte, on either transport."""
        tmp_path, cfg = trained
        cap_cfg = dataclasses.replace(
            cfg,
            serve_capture_sample=1.0,
            serve_capture_file=str(tmp_path / "cap_parity.capture"),
            incident_dir=str(tmp_path / "inc_parity"),
        )
        frames = [_frame(rng, n) for n in (1, 5, 17)]
        text = "1 5:0.5 9:0.25\n0 7:1 3:0.5\n"
        off = serve(cfg, port=0)
        try:
            plain_bin = [
                _post(f"http://127.0.0.1:{off.port}/score_bin", fr)
                for fr in frames
            ]
            plain_txt = _post(
                f"http://127.0.0.1:{off.port}/score", text.encode()
            )
            assert off.capture is None  # off = the feature does not exist
        finally:
            off.close()
        on = serve(cap_cfg, port=0)
        try:
            for fr, want in zip(frames, plain_bin):
                got = _post(f"http://127.0.0.1:{on.port}/score_bin", fr)
                assert got == want  # byte-identical
            got_txt = _post(
                f"http://127.0.0.1:{on.port}/score", text.encode()
            )
            assert got_txt == plain_txt
            assert on.capture is not None and on.capture.count >= 4
        finally:
            on.close()

    def test_capture_replays_bitwise(self, trained, rng):
        tmp_path, cfg = trained
        cap_path = str(tmp_path / "replayme.capture")
        cap_cfg = dataclasses.replace(
            cfg, serve_capture_sample=1.0, serve_capture_file=cap_path,
        )
        handle = serve(cap_cfg, port=0)
        try:
            for n in (1, 3, 9, 30):
                _post(f"http://127.0.0.1:{handle.port}/score_bin",
                      _frame(rng, n))
            # A TEXT request captures too — as a canonical binary
            # frame, replayable through /score_bin.
            _post(f"http://127.0.0.1:{handle.port}/score",
                  b"1 5:0.5 9:0.25\n")
        finally:
            handle.close()
        records = list(wire.read_capture(cap_path))
        assert len(records) == 5
        # Replay against a FRESH capture-off server: bitwise parity.
        fresh = serve(cfg, port=0)
        try:
            rc = replay.replay(
                cap_path, f"http://127.0.0.1:{fresh.port}",
                out=sys.stderr,
            )
            assert rc == 0
            # And a corrupted response must be CAUGHT (exit 2).
            t, req, resp = records[0]
            bad = bytearray(resp)
            bad[-1] ^= 0x01
            bad_path = str(tmp_path / "bad.capture")
            with open(bad_path, "wb") as f:
                f.write(wire.CAPTURE_MAGIC)
                f.write((1).to_bytes(4, "little"))
                f.write(wire._CAP_REC.pack(t, len(req), len(bad)))
                f.write(req)
                f.write(bytes(bad))
            assert replay.replay(
                bad_path, f"http://127.0.0.1:{fresh.port}",
                out=sys.stderr,
            ) == 2
        finally:
            fresh.close()

    def test_post_incident_route(self, trained, rng):
        tmp_path, cfg = trained
        inc_root = str(tmp_path / "inc_manual")
        bb_cfg = dataclasses.replace(cfg, incident_dir=inc_root)
        handle = serve(bb_cfg, port=0)
        try:
            _post(f"http://127.0.0.1:{handle.port}/score_bin",
                  _frame(rng, 2))
            doc = json.loads(_post(
                f"http://127.0.0.1:{handle.port}/incident?reason=smoke",
                b"",
            ))
            out = doc["incident_dir"]
            assert os.path.isdir(out)
            assert "smoke" in os.path.basename(out)
            assert "_pid" in os.path.basename(out)
            man = json.load(open(os.path.join(out, "manifest.json")))
            assert man["record"] == "incident"
        finally:
            handle.close()
        # Blackbox off -> the route answers 503, and nothing dumps.
        off_cfg = dataclasses.replace(cfg, blackbox=False)
        handle = serve(off_cfg, port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{handle.port}/incident", b"")
            assert ei.value.code == 503
        finally:
            handle.close()
