"""Quantized embedding tables (ISSUE 11 tentpole): bf16 / int8 storage
with fp32 scales across the codec, the tiered cold store, checkpoints,
and the serving ladder.

The pinned guarantees:

  * codec — int8/bf16 round trips stay inside closed-form error bounds,
    zero rows reproduce exactly, an adversarial outlier row degrades
    only its own scale chunk, packed rows unpack bitwise;
  * tiered — training with a quantized cold store stays within a pinned
    tolerance of the fp32 run (adagrad/ftrl, eviction churn, K-step
    dispatch, warm restart), overlay checkpoints carry the storage
    dtype and refuse a mismatched restore;
  * checkpoints — dense <-> quant conversion round-trips within the
    format's error bound, training refuses to warm-start from
    quant.npz, serving refuses a dtype/chunk-mismatched quant.npz;
  * serving — bf16/int8 ladders serve within a pinned tolerance of
    fp32 with ZERO steady-state compiles and working hot-swap, and the
    server measures per-request parse time (serve.parse).
"""

from __future__ import annotations

import urllib.request

import numpy as np
import pytest

import jax

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import quant
from fast_tffm_tpu.train import checkpoint, tiered
from fast_tffm_tpu.train.loop import Trainer

V = 256

# Pinned served-score tolerances (|served_quant - served_fp32|, sigmoid
# outputs) at the test shapes.  Measured headroom is ~10x: bf16 lands
# around 1e-3 at adversarially scaled tables, int8 around 2e-3.
BF16_SERVE_TOL = 5e-3
INT8_SERVE_TOL = 2e-2
# Pinned end-of-training table drift vs the fp32 run at the tiny-V
# config below (values of magnitude ~1e-2; only rows that cycled
# through an eviction carry quantization error).
TRAIN_TOL = 5e-2


def _write_data(path, rng, lines=256, vocab=V):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5 "
                f"{rng.integers(0, vocab)}:0.25\n"
            )


def _cfg(tmp_path, model, **kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / model),
        epoch_num=2, log_steps=0, thread_num=1, seed=3,
        steps_per_dispatch=2,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _logical_table(trainer) -> np.ndarray:
    trainer.tiered.sync_from_device(trainer._hot_host_tables())
    return trainer.tiered.gather_logical(np.arange(V, dtype=np.int64))


# ------------------------------------------------------------- codec


def test_int8_roundtrip_error_bound(rng):
    rows = (rng.standard_normal((200, 9)) * np.exp(
        rng.uniform(-6, 4, (200, 1))
    )).astype(np.float32)
    codes, scales = quant.quantize_int8(rows, 0)
    assert codes.dtype == np.int8 and scales.shape == (200,)
    back = quant.dequantize_int8(codes, scales, 0)
    amax = np.abs(rows).max(axis=1)
    # Symmetric 127-level quantization: error <= scale/2 = amax/254.
    bound = amax / 254.0 + 1e-12
    assert (np.abs(back - rows).max(axis=1) <= bound).all()


def test_quant_zero_rows_exact():
    rows = np.zeros((5, 9), np.float32)
    rows[2, 3] = 1.0  # one nonzero row between zeros
    for dtype in ("bf16", "int8"):
        c = quant.RowCodec(dtype, 9)
        back = c.decode(c.encode(rows))
        assert (back[0] == 0).all() and (back[4] == 0).all()
        assert back[2, 3] == 1.0
    qt = quant.quantize_table(rows, "int8", 2)
    assert (quant.dequantize_table(qt)[0] == 0).all()


def test_int8_outlier_row_isolated_to_chunk(rng):
    rows = rng.uniform(-0.01, 0.01, (64, 9)).astype(np.float32)
    rows[10] *= 1e4  # adversarial outlier row in chunk 10//4 == 2
    qt = quant.quantize_table(rows, "int8", 4)
    back = quant.dequantize_table(qt)
    err = np.abs(back - rows).max(axis=1)
    chunk_mates = [8, 9, 11]
    others = [i for i in range(64) if i // 4 != 2]
    fine_bound = 0.01 / 254 + 1e-9  # scale/2 of an outlier-free chunk
    # Chunk-mates pay for the outlier's scale (they quantize to ~0 and
    # lose essentially their whole magnitude); every OTHER chunk keeps
    # its own fine-grained precision — the isolation the chunking buys.
    assert err[chunk_mates].min() > 5 * fine_bound
    assert err[others].max() <= fine_bound
    # Per-row scales (the cold-store packing) isolate completely: every
    # non-outlier row keeps its own amax/254 bound.
    c = quant.RowCodec("int8", 9)
    err_pr = np.abs(c.decode(c.encode(rows)) - rows).max(axis=1)
    keep = chunk_mates + others
    bound_pr = np.abs(rows[keep]).max(axis=1) / 254 + 1e-9
    assert (err_pr[keep] <= bound_pr).all()


def test_bf16_roundtrip_relative_error(rng):
    rows = (rng.standard_normal((100, 9)) * np.exp(
        rng.uniform(-10, 10, (100, 1))
    )).astype(np.float32)
    c = quant.RowCodec("bf16", 9)
    back = c.decode(c.encode(rows))
    # bf16 keeps 8 mantissa bits: relative error <= 2^-8.
    assert (np.abs(back - rows) <= np.abs(rows) * 2.0 ** -8 + 1e-30).all()


def test_rowcodec_pack_shapes_and_identity(rng):
    rows = rng.normal(0, 0.5, (32, 9)).astype(np.float32)
    fp = quant.RowCodec("fp32", 9)
    assert fp.width == 9 and fp.bytes_per_row == 36
    enc = fp.encode(rows)
    assert enc is not rows and np.array_equal(enc, rows)
    assert fp.decode(enc) is enc  # identity decode, no copy
    bf = quant.RowCodec("bf16", 9)
    assert bf.encode(rows).shape == (32, 18)
    i8 = quant.RowCodec("int8", 9)
    p = i8.encode(rows)
    assert p.shape == (32, 13) and p.dtype == np.uint8
    # decode(encode(x)) twice is stable (quantization is idempotent on
    # already-quantized values under per-row scales' exact amax).
    once = i8.decode(p)
    assert np.array_equal(i8.decode(i8.encode(once)), once)
    assert i8.empty(0).shape == (0, 13)


def test_dequant_gathered_matches_numpy(rng):
    import jax.numpy as jnp

    table = rng.normal(0, 1, (100, 9)).astype(np.float32)
    qt = quant.quantize_table(table, "int8", 8)
    ids = rng.integers(0, 100, (4, 6))
    got = np.asarray(quant.dequant_gathered(
        jnp.asarray(qt.codes)[jnp.asarray(ids)],
        jnp.asarray(qt.scales)[jnp.asarray(ids) // 8],
    ))
    want = quant.dequantize_table(qt)[ids]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_quant_table_bytes_and_serialization(rng):
    table = rng.normal(0, 0.01, (4096, 9)).astype(np.float32)
    qt = quant.quantize_table(table, "int8", 64)
    # ~4x: 9 code bytes + 4/64 scale bytes per row vs 36 fp32 bytes.
    assert qt.nbytes / 4096 < 36 / 3.8
    bf = quant.quantize_table(table, "bf16")
    assert bf.nbytes == 4096 * 18
    for t in (qt, bf):
        back = quant.table_from_arrays(
            t.descriptor(), quant.table_to_arrays(t)
        )
        assert back.dtype == t.dtype and back.chunk == t.chunk
        np.testing.assert_array_equal(
            quant.dequantize_table(back), quant.dequantize_table(t)
        )


# ------------------------------------------------- tiered cold store


def test_cold_store_quant_scatter_gather(rng, monkeypatch):
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)  # force virtual
    sizes = {}
    for dtype in ("fp32", "bf16", "int8"):
        cfg = FmConfig(
            vocabulary_size=V, factor_num=4, max_features=4,
            table_tiering="on", hot_rows=64, cold_dtype=dtype, seed=3,
        )
        store = tiered._virtual_store(cfg, "table")
        ids = np.arange(0, 200, 2, dtype=np.int64)
        init = store.gather(ids)
        # Never-written rows are the f32 init, NOT quantized.
        np.testing.assert_array_equal(
            init, tiered._hash_uniform(ids, cfg.embedding_dim, 3, 0.01)
        )
        rows = rng.normal(0, 0.02, init.shape).astype(np.float32)
        store.scatter(ids, rows)
        got = store.gather(ids)
        err = np.abs(got - rows)
        if dtype == "fp32":
            assert err.max() == 0.0
        elif dtype == "bf16":
            assert (err <= np.abs(rows) * 2.0 ** -8 + 1e-30).all()
        else:  # per-row scale: err <= row amax / 254
            bound = np.abs(rows).max(axis=1, keepdims=True) / 254
            assert (err <= bound + 1e-9).all()
        store._compact()
        sizes[dtype] = store._rows.nbytes  # row storage (excl. the
        # id index, which every mode pays identically)
    d = 5  # embedding_dim at factor_num=4
    assert sizes["fp32"] == 100 * 4 * d
    assert sizes["bf16"] == sizes["fp32"] // 2
    assert sizes["int8"] == 100 * (d + 4)  # codes + per-row fp32 scale


@pytest.mark.parametrize("optimizer", ["adagrad", "ftrl"])
@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_tiered_quant_parity_within_tolerance(tmp_path, rng, monkeypatch,
                                              optimizer, dtype):
    """Quantized-cold training tracks the fp32 run inside TRAIN_TOL —
    with eviction churn (hot_rows < V), K-step dispatch, and the
    virtual store forced so quantization REALLY engages."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)
    _write_data(tmp_path / "train.libsvm", rng)
    rd = Trainer(_cfg(tmp_path, "dense", optimizer=optimizer)).train()
    tq = Trainer(_cfg(
        tmp_path, f"tq_{dtype}", optimizer=optimizer,
        table_tiering="on", hot_rows=160, cold_dtype=dtype,
    ))
    rq = tq.train()
    assert rq["train"]["tiered"]["rows_evicted"] > 0  # churn exercised
    assert rq["train"]["tiered"]["cold_dtype"] == dtype
    assert abs(rq["train"]["loss"] - rd["train"]["loss"]) < TRAIN_TOL
    # Compare the merged logical tables: within tolerance, NOT equal
    # (identical tables would mean quantization never engaged).
    d_table = Trainer(_cfg(
        tmp_path, "dense2", optimizer=optimizer,
        table_tiering="on", hot_rows=160,  # fp32 tiered == dense
    ))
    d_table.train()
    ref = _logical_table(d_table)
    got = _logical_table(tq)
    diff = np.abs(got - ref).max()
    assert 0.0 < diff < TRAIN_TOL


def test_tiered_quant_overlay_resume(tmp_path, rng, monkeypatch):
    """A quantized overlay checkpoint restores (descriptor match) and
    the warm-started run stays inside tolerance of the uninterrupted
    fp32 reference."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)
    _write_data(tmp_path / "train.libsvm", rng)
    kw = dict(table_tiering="on", hot_rows=160, cold_dtype="int8")
    Trainer(_cfg(tmp_path, "q", epoch_num=1, **kw)).train()
    assert checkpoint.exists_tiered(str(tmp_path / "q"))
    # Descriptor carries the storage dtype.
    _, _, stores = checkpoint.restore_tiered(str(tmp_path / "q"))
    assert stores["table"]["descriptor"]["dtype"] == "int8"
    t2 = Trainer(_cfg(tmp_path, "q", epoch_num=2, **kw))
    assert t2._restored_step > 0
    t2.train()
    ref = Trainer(_cfg(tmp_path, "ref", epoch_num=2,
                       table_tiering="on", hot_rows=160))
    ref.train()
    diff = np.abs(_logical_table(t2) - _logical_table(ref)).max()
    assert diff < TRAIN_TOL


def test_overlay_quant_descriptor_mismatch_refused(tmp_path, rng,
                                                   monkeypatch):
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)
    _write_data(tmp_path / "train.libsvm", rng)
    Trainer(_cfg(
        tmp_path, "q", epoch_num=1, table_tiering="on", hot_rows=160,
        cold_dtype="int8",
    )).train()
    with pytest.raises(ValueError, match="different init"):
        Trainer(_cfg(
            tmp_path, "q", table_tiering="on", hot_rows=160,
            cold_dtype="bf16",
        ))


def test_cold_dtype_requires_tiering():
    with pytest.raises(ValueError, match="table_tiering"):
        FmConfig(cold_dtype="bf16")
    with pytest.raises(ValueError, match="cold_dtype"):
        FmConfig(cold_dtype="fp16", table_tiering="on")


# ------------------------------------------------ checkpoint / convert


def test_quant_checkpoint_refusals_and_roundtrip(tmp_path, rng):
    from tools import convert_checkpoint as cc

    model = str(tmp_path / "model")
    cfg = _cfg(tmp_path, "model", epoch_num=1)
    _write_data(tmp_path / "train.libsvm", rng)
    Trainer(cfg).train()
    table0, _, _ = _dense_params(model, cfg)
    # In-place LOSSY conversion refuses without --force (it deletes
    # the fp32 params + optimizer state).
    with pytest.raises(SystemExit, match="--force"):
        cc.main([model, "--to", "int8", "--chunk", "16"])
    assert checkpoint.exists(model)  # refused: source intact
    assert cc.main(
        [model, "--to", "int8", "--chunk", "16", "--force"]
    ) == 0
    assert checkpoint.exists_quant(model) and not checkpoint.exists(model)
    assert checkpoint.read_manifest(model)["format"] == "quant"
    # Training refuses the quantized serving format, loudly.
    with pytest.raises(ValueError, match="quant.npz"):
        Trainer(cfg)
    with pytest.raises(ValueError, match="quant.npz"):
        Trainer(_cfg(tmp_path, "model", table_tiering="on",
                     hot_rows=160))
    # Serving refuses a dtype mismatch, loudly.
    from fast_tffm_tpu.serve import scorer as scorer_lib

    with pytest.raises(ValueError, match="serve_table_dtype"):
        scorer_lib.load_model(cfg)  # cfg wants fp32
    with pytest.raises(ValueError, match="serve_table_dtype"):
        scorer_lib.load_model(
            _cfg(tmp_path, "model", serve_table_dtype="bf16")
        )
    # chunk mismatch refused at placement.
    step, w0, qt = checkpoint.restore_quant(model)
    with pytest.raises(ValueError, match="quant_chunk"):
        scorer_lib.FixedShapeScorer(
            _cfg(tmp_path, "model", serve_table_dtype="int8",
                 quant_chunk=64),
            (w0, qt),
        )
    # Convert back to fp32: a trainer warm-starts from it again.
    assert cc.main([model, "--to", "fp32"]) == 0
    assert checkpoint.exists(model) and not checkpoint.exists_quant(model)
    table1, _, step1 = _dense_params(model, cfg)
    assert step1 > 0
    assert np.abs(table1 - table0).max() <= (
        np.abs(table0).max() / 254 + 1e-9
    )
    Trainer(cfg)  # restores without raising


def _dense_params(model_file, cfg):
    from functools import partial

    tmpl = jax.eval_shape(
        partial(fm.init_params, cfg=cfg), jax.random.PRNGKey(0)
    )
    params, step = checkpoint.restore_params(model_file, tmpl)
    return np.asarray(params[1]), np.asarray(params[0]), step


def test_convert_bf16_roundtrip_tolerance(tmp_path, rng):
    from tools import convert_checkpoint as cc

    model = str(tmp_path / "model")
    cfg = _cfg(tmp_path, "model", epoch_num=1)
    _write_data(tmp_path / "train.libsvm", rng)
    Trainer(cfg).train()
    table0, _, _ = _dense_params(model, cfg)
    out = str(tmp_path / "model_bf16")
    assert cc.main([model, "--to", "bf16", "--out", out]) == 0
    assert checkpoint.exists(model)  # --out leaves the source intact
    _, _, qt = checkpoint.restore_quant(out)
    back = quant.dequantize_table(qt)
    assert (np.abs(back - table0)
            <= np.abs(table0) * 2.0 ** -8 + 1e-30).all()
    # Converting BACK over a dir that still holds an older dense
    # checkpoint must clear its opt/ dir: dequantized params paired
    # with stale accumulators would warm-start with wrong effective
    # learning rates, silently.
    import os

    assert os.path.isdir(os.path.join(model, "opt"))  # from training
    assert cc.main([out, "--to", "fp32", "--out", model]) == 0
    assert not os.path.isdir(os.path.join(model, "opt"))


# --------------------------------------------------------- serving


def _probe(rng, n=64, f=4):
    ids = rng.integers(0, V, (n, f)).astype(np.int32)
    vals = rng.uniform(0.1, 1.0, (n, f)).astype(np.float32)
    return ids, vals


def _serve_cfg(dtype, **kw):
    return FmConfig(
        vocabulary_size=V, factor_num=4, max_features=4,
        serve_batch_sizes="16,64", serve_table_dtype=dtype,
        quant_chunk=32, **kw,
    )


def test_served_quant_vs_fp32_tolerance_pinned(rng):
    from fast_tffm_tpu.serve.scorer import FixedShapeScorer

    params = fm.init_params(jax.random.PRNGKey(1), _serve_cfg("fp32"))
    # Adversarial magnitudes: scale the table well beyond init range.
    params = fm.FmParams(w0=params.w0, table=params.table * 50)
    ids, vals = _probe(rng, 100)
    out = {}
    tels = {}
    for dtype in ("fp32", "bf16", "int8"):
        tels[dtype] = obs.Telemetry()
        sc = FixedShapeScorer(
            _serve_cfg(dtype), params, telemetry=tels[dtype]
        )
        sc.warmup()
        out[dtype] = sc.score(ids, vals)
        assert sc.steady_compiles == 0
    assert np.abs(out["bf16"] - out["fp32"]).max() <= BF16_SERVE_TOL
    assert np.abs(out["int8"] - out["fp32"]).max() <= INT8_SERVE_TOL
    g32 = tels["fp32"].snapshot()["gauges"]
    g16 = tels["bf16"].snapshot()["gauges"]
    g8 = tels["int8"].snapshot()["gauges"]
    assert g16["serve.table_bytes"] == g32["serve.table_bytes"] / 2
    assert g8["serve.table_bytes"] < g32["serve.table_bytes"] / 3
    assert g32["serve.quant_error_max"] == 0.0
    assert 0 < g16["serve.quant_error_max"] <= BF16_SERVE_TOL
    assert 0 < g8["serve.quant_error_max"] <= INT8_SERVE_TOL


def test_quant_ladder_steady_compiles_zero_and_hot_swap(rng):
    """The zero-steady-compile contract and the hot-swap protocol are
    dtype-independent: a quantized ladder warms up, serves mixed
    sizes, and swaps a NEW fp32 checkpoint (re-quantized off-traffic)
    without a single additional compile."""
    from fast_tffm_tpu.serve.scorer import FixedShapeScorer

    cfg = _serve_cfg("int8")
    p1 = fm.init_params(jax.random.PRNGKey(1), cfg)
    sc = FixedShapeScorer(cfg, p1)
    n_warm = sc.warmup()
    assert n_warm == 2  # one per rung
    ids, vals = _probe(rng, 100)
    s1 = sc.score(ids, vals)
    sc.score(ids[:3], vals[:3])
    p2 = fm.init_params(jax.random.PRNGKey(9), cfg)
    sc.swap(p2, step=5)
    s2 = sc.score(ids, vals)
    assert sc.step == 5
    assert not np.allclose(s1, s2)  # genuinely new table
    assert sc.steady_compiles == 0
    assert sc.compiles == n_warm  # swap compiled NOTHING


def test_quant_checkpoint_serves_within_tolerance(tmp_path, rng):
    """quant.npz end-to-end: save dense -> convert -> make_scorer loads
    the quantized table directly and serves within tolerance of the
    fp32 scorer on the source checkpoint."""
    from tools import convert_checkpoint as cc

    from fast_tffm_tpu.serve import scorer as scorer_lib

    model = str(tmp_path / "model")
    cfg = _cfg(tmp_path, "model", epoch_num=1,
               serve_batch_sizes="16,64")
    _write_data(tmp_path / "train.libsvm", rng)
    Trainer(cfg).train()
    qdir = str(tmp_path / "model_q")
    assert cc.main([model, "--to", "int8", "--out", qdir]) == 0
    sc32 = scorer_lib.make_scorer(cfg)
    tel = obs.Telemetry()
    scq = scorer_lib.make_scorer(_cfg(
        tmp_path, "model_q", serve_batch_sizes="16,64",
        serve_table_dtype="int8",
    ), telemetry=tel)
    assert isinstance(scq, scorer_lib.FixedShapeScorer)
    assert scq.table_dtype == "int8" and scq.step == sc32.step
    # A pre-quantized placement has no fp32 reference: the error gauge
    # must read UNKNOWN (-1), not 0 ("exact") or a stale number.
    assert tel.snapshot()["gauges"]["serve.quant_error_max"] == -1.0
    scq.warmup()
    assert scq.steady_compiles == 0
    ids, vals = _probe(rng, 50)
    np.testing.assert_allclose(
        scq.score(ids, vals), sc32.score(ids, vals),
        atol=INT8_SERVE_TOL,
    )


def test_watcher_baselines_unservable_quant_checkpoint(tmp_path, rng):
    """An in-place conversion to a dtype the running server cannot
    serve is warned about ONCE and baselined — not an unbounded
    reload-the-table-every-poll retry loop.  The next compatible save
    still swaps."""
    from tools import convert_checkpoint as cc

    from fast_tffm_tpu.serve.scorer import FixedShapeScorer
    from fast_tffm_tpu.serve.server import CheckpointWatcher

    model = str(tmp_path / "model")
    cfg = _cfg(tmp_path, "model", epoch_num=1,
               serve_batch_sizes="16,64")
    _write_data(tmp_path / "train.libsvm", rng)
    Trainer(cfg).train()  # dense checkpoint + manifest
    params = fm.init_params(jax.random.PRNGKey(1), cfg)
    sc = FixedShapeScorer(cfg, params, step=1)  # fp32 server
    watcher = CheckpointWatcher(cfg, sc, poll_secs=3600)
    try:
        # Operator converts in place to int8: the fp32 server cannot
        # serve it (load_model raises ValueError on the dtype).
        assert cc.main([model, "--to", "int8", "--force"]) == 0
        man = checkpoint.read_manifest(model)
        assert man["format"] == "quant"
        watcher._check_once()
        assert sc.step == 1  # still serving the old params
        assert watcher._seen == man  # baselined: no retry loop
        watcher._check_once()  # second poll is a no-op, not a reload
        # Converting back republishes a dense manifest: the NEXT save
        # swaps normally.
        assert cc.main([model, "--to", "fp32"]) == 0
        watcher._check_once()
        assert sc.step > 1
    finally:
        watcher.close()


def test_serve_parse_timer_records(rng):
    """The per-request libsvm parse cost is measured (serve.parse) and
    surfaces in the serve record block."""
    from fast_tffm_tpu.serve.batcher import ServeBatcher
    from fast_tffm_tpu.serve.scorer import FixedShapeScorer
    from fast_tffm_tpu.serve.server import ServeServer, _serve_block

    cfg = _serve_cfg("fp32", max_batch_wait_ms=0.0)
    params = fm.init_params(jax.random.PRNGKey(1), cfg)
    tel = obs.Telemetry()
    sc = FixedShapeScorer(cfg, params, telemetry=tel)
    sc.warmup()
    batcher = ServeBatcher(sc, max_batch_wait_ms=0.0, queue_size=8,
                           telemetry=tel)
    server = ServeServer(
        0, batcher, cfg, lambda: {"record": "status"}, telemetry=tel
    )
    try:
        body = b"0 1:1 2:0.5\n1 7:0.25\n"
        resp = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{server.port}/score", data=body,
            method="POST",
        ), timeout=30)
        assert len(resp.read().splitlines()) == 2
        snap = tel.snapshot()
        parse = snap["timers"].get("serve.parse")
        assert parse and parse["count"] >= 1
        block = _serve_block(snap, sc, batcher, wall=1.0)
        assert "parse_p50_ms" in block
        assert block["table_mb"] > 0
        assert block["quant_error_max"] == 0.0  # fp32 serving
    finally:
        server.close()
        batcher.close()
