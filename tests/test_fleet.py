"""Training-fleet observability (ISSUE 18): the shared fleet
aggregation core, the TrainFleet live plane, and its alert wiring.

Pins:

  * ``merge_blocks`` — the one merge implementation both planes
    consume: sums / weighted means / MAX tails / plain means /
    same-name MAX + int-sum groups, staleness age, the empty-scrape
    shape, absent-key discipline (no lying zeros);
  * the serve router's ``_FLEET_SPEC`` reproduces the legacy
    ``_fleet_aggregates`` output exactly (regression pin for the
    extraction — the two planes cannot drift);
  * ``labeled_lines`` — the one labeled-series renderer (header +
    escaping + skip-when-empty);
  * ``TrainFleet`` — straggler attribution with an injected slow rank,
    rank_step_skew, exchange_frac max-merge, target death degrading to
    staleness (never a crash) against REAL StatusServers;
  * the alert plane — ``straggler_ratio`` fires on breach and stays
    quiet at parity; ``fleet_scrape_age_max_s`` resolves through the
    fleet block (and still through serve.*);
  * config — fleet-plane alert rules are refused while
    ``train_fleet_scrape`` is unset (the inert-rule discipline), bad
    targets and heartbeat_secs=0 are rejected;
  * ``rank_suffix_path`` — per-rank file suffixing (the writer
    double-count fix);
  * the cross-rank exchange probe builds and reduces correctly on the
    8-device mesh for both lookup impls;
  * fleet plane off -> training state is bitwise identical.
"""

import json
import threading
import time
import urllib.error

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs import fleet as fleet_lib
from fast_tffm_tpu.obs.alerts import AlertEngine, parse_rules
from fast_tffm_tpu.obs.status import StatusServer


# ---------------------------------------------------------------------------
# merge_blocks semantics
# ---------------------------------------------------------------------------


_SPEC = obs.MergeSpec(
    sums=("requests",),
    weighted=("p50_ms",),
    weight_key="requests",
    tails=("p99_ms",),
    means=("batch_fill",),
    max_same=("skew_psi_max",),
    sum_same_int=("skew_examples",),
)


class TestMergeBlocks:
    def test_empty_scrape_shape(self):
        assert obs.merge_blocks(_SPEC, [], now=10.0) == {
            "replicas_scraped": 0
        }

    def test_sums_weighted_tails_means(self):
        now = 100.0
        blocks = [
            (99.0, {"requests": 100, "p50_ms": 10.0, "p99_ms": 50.0,
                    "batch_fill": 0.5, "skew_psi_max": 0.1,
                    "skew_examples": 7}),
            (98.0, {"requests": 300, "p50_ms": 20.0, "p99_ms": 40.0,
                    "batch_fill": 0.7, "skew_psi_max": 0.3,
                    "skew_examples": 5}),
        ]
        out = obs.merge_blocks(_SPEC, blocks, now)
        assert out["replicas_scraped"] == 2
        assert out["fleet_requests"] == 400
        # Request-weighted p50: (10*100 + 20*300) / 400.
        assert out["fleet_p50_ms"] == 17.5
        # Tails MAX-merge (a merged p99 can't be computed from
        # per-member percentiles).
        assert out["fleet_p99_ms"] == 50.0
        assert out["fleet_batch_fill"] == pytest.approx(0.6)
        # Same-name groups: PSI is max-merged, mass is summed.
        assert out["skew_psi_max"] == 0.3
        assert out["skew_examples"] == 12
        # Staleness: the OLDEST member's age.
        assert out["fleet_scrape_age_max_s"] == 2.0

    def test_absent_keys_contribute_nothing(self):
        out = obs.merge_blocks(
            _SPEC, [(9.0, {"requests": 4})], now=10.0
        )
        assert "fleet_p50_ms" not in out
        assert "fleet_p99_ms" not in out
        assert "fleet_batch_fill" not in out
        assert "skew_psi_max" not in out
        assert out["fleet_requests"] == 4

    def test_non_numeric_values_skipped(self):
        out = obs.merge_blocks(
            _SPEC,
            [(9.0, {"requests": "lots", "p99_ms": 5.0}),
             (9.5, {"requests": 3, "p99_ms": "slow"})],
            now=10.0,
        )
        assert out["fleet_requests"] == 3
        assert out["fleet_p99_ms"] == 5.0

    def test_idle_member_still_weighs_one(self):
        # weight max(1, requests): an idle member (0 requests) cannot
        # zero out its p50 contribution.
        out = obs.merge_blocks(
            _SPEC,
            [(9.0, {"requests": 0, "p50_ms": 30.0}),
             (9.0, {"requests": 0, "p50_ms": 10.0})],
            now=10.0,
        )
        assert out["fleet_p50_ms"] == 20.0


class TestRouterSpecRegression:
    """The extracted spec reproduces the legacy router aggregation
    byte-for-byte — the drift pin the shared core exists for."""

    def _legacy(self, blocks, now):
        # The pre-extraction serve/router.py _fleet_aggregates body,
        # kept verbatim as the regression oracle.
        if not blocks:
            return {"replicas_scraped": 0}
        out = {"replicas_scraped": len(blocks)}
        for key in ("requests", "examples", "batches", "qps",
                    "steady_compiles", "recompiles_unexpected"):
            vals = [b.get(key) for _t, b in blocks]
            vals = [v for v in vals if isinstance(v, (int, float))]
            if vals:
                out[f"fleet_{key}"] = round(sum(vals), 2)
        weights = [
            max(1, int((b.get("requests") or 0))) for _t, b in blocks
        ]
        p50s = [
            (b.get("p50_ms"), w)
            for (_t, b), w in zip(blocks, weights)
            if isinstance(b.get("p50_ms"), (int, float))
        ]
        if p50s:
            out["fleet_p50_ms"] = round(
                sum(v * w for v, w in p50s) / sum(w for _, w in p50s),
                4,
            )
        for key in ("p95_ms", "p99_ms", "max_ms"):
            vals = [
                b.get(key) for _t, b in blocks
                if isinstance(b.get(key), (int, float))
            ]
            if vals:
                out[f"fleet_{key}"] = round(max(vals), 4)
        fills = [
            b.get("batch_fill") for _t, b in blocks
            if isinstance(b.get("batch_fill"), (int, float))
        ]
        if fills:
            out["fleet_batch_fill"] = round(sum(fills) / len(fills), 6)
        for key in ("skew_psi_values", "skew_psi_lengths",
                    "skew_psi_ids", "skew_psi_scores", "skew_psi_max"):
            vals = [
                b.get(key) for _t, b in blocks
                if isinstance(b.get(key), (int, float))
            ]
            if vals:
                out[key] = round(max(vals), 6)
        skew_n = [
            b.get("skew_examples") for _t, b in blocks
            if isinstance(b.get("skew_examples"), (int, float))
        ]
        if skew_n:
            out["skew_examples"] = int(sum(skew_n))
        out["fleet_scrape_age_max_s"] = round(
            max(now - t for t, _b in blocks), 3
        )
        return out

    def test_matches_legacy_on_rich_blocks(self):
        from fast_tffm_tpu.serve.router import ServeRouter

        rng = np.random.default_rng(7)
        now = 1000.0
        blocks = []
        for i in range(5):
            b = {
                "requests": int(rng.integers(0, 500)),
                "examples": int(rng.integers(0, 9000)),
                "batches": int(rng.integers(0, 200)),
                "qps": round(float(rng.uniform(0, 900)), 2),
                "steady_compiles": int(rng.integers(0, 3)),
                "recompiles_unexpected": int(rng.integers(0, 2)),
                "p50_ms": round(float(rng.uniform(1, 20)), 4),
                "p95_ms": round(float(rng.uniform(20, 40)), 4),
                "p99_ms": round(float(rng.uniform(40, 80)), 4),
                "max_ms": round(float(rng.uniform(80, 200)), 4),
                "batch_fill": round(float(rng.uniform(0, 1)), 6),
                "skew_psi_values": round(float(rng.uniform(0, 1)), 6),
                "skew_psi_max": round(float(rng.uniform(0, 1)), 6),
                "skew_examples": int(rng.integers(0, 4000)),
            }
            # Member 3 is sparse: only a counter (absent-key paths).
            if i == 3:
                b = {"requests": b["requests"]}
            blocks.append((now - float(rng.uniform(0, 5)), b))
        legacy = self._legacy(blocks, now)
        shared = obs.merge_blocks(ServeRouter._FLEET_SPEC, blocks, now)
        assert shared == legacy

    def test_matches_legacy_empty(self):
        from fast_tffm_tpu.serve.router import ServeRouter

        assert obs.merge_blocks(
            ServeRouter._FLEET_SPEC, [], 5.0
        ) == self._legacy([], 5.0)


class TestLabeledLines:
    def test_header_and_samples(self):
        lines = obs.labeled_lines(
            "tffm_x", "gauge",
            [({"rank": 0}, 1.5), ({"rank": 1, "port": 80}, 2)],
        )
        assert lines == [
            "# TYPE tffm_x gauge",
            'tffm_x{rank="0"} 1.5',
            'tffm_x{rank="1",port="80"} 2',
        ]

    def test_empty_renders_nothing(self):
        assert obs.labeled_lines("tffm_x", "gauge", []) == []

    def test_label_escaping(self):
        lines = obs.labeled_lines(
            "tffm_x", "gauge", [({"host": 'a"b\\c'}, 1)]
        )
        assert lines[1] == 'tffm_x{host="a\\"b\\\\c"} 1'


# ---------------------------------------------------------------------------
# TrainFleet
# ---------------------------------------------------------------------------


def _rank_status(rank, step, dispatch_mean_ms, dispatch_count=10,
                 wait_mean_ms=1.0, examples=1000, elapsed=50.0,
                 exchange_total_s=None):
    """A /status record shaped like the trainer's heartbeat record."""
    total_s = dispatch_mean_ms * dispatch_count / 1000.0
    timers = {
        "train.dispatch": {
            "count": dispatch_count, "total_s": round(total_s, 6),
            "mean_ms": dispatch_mean_ms,
            "p99_ms": dispatch_mean_ms * 1.2,
        },
        "train.wait_input": {
            "count": dispatch_count, "total_s": 0.01,
            "mean_ms": wait_mean_ms, "p99_ms": wait_mean_ms * 2,
        },
    }
    if exchange_total_s is not None:
        timers["train.exchange"] = {
            "count": dispatch_count, "total_s": exchange_total_s,
            "mean_ms": exchange_total_s / dispatch_count * 1000,
            "p99_ms": exchange_total_s / dispatch_count * 1200,
        }
    return {
        "record": "status", "rank": rank, "step": step,
        "elapsed": elapsed, "examples_in": examples,
        "ingest_wait_frac": 0.01,
        "stages": {"timers": timers},
    }


def _fake_fleet(records):
    """A TrainFleet over fake targets served from a dict."""
    return obs.TrainFleet(
        list(records), fetch=lambda t: records[t], start=False
    )


class TestTrainFleet:
    def test_straggler_attribution(self):
        # Rank 1 dispatches 3x slower than the other two.
        records = {
            "r0": _rank_status(0, 40, dispatch_mean_ms=100.0),
            "r1": _rank_status(1, 38, dispatch_mean_ms=300.0),
            "r2": _rank_status(2, 40, dispatch_mean_ms=100.0),
        }
        fl = _fake_fleet(records)
        assert fl.scrape_once() == 3
        block = fl.block()
        fleet_mean = (100 + 300 + 100) / 3
        assert block["ranks_scraped"] == 3
        assert block["straggler_ratio"] == pytest.approx(
            300 / fleet_mean, abs=1e-4
        )
        assert block["slowest_rank"] == 1
        assert block["slowest_rank_share"] == pytest.approx(
            0.6, abs=1e-4
        )
        assert block["dispatch_skew_ms"] == pytest.approx(200.0)
        assert block["rank_step_skew"] == 2
        assert block["examples_in"] == 3000

    def test_parity_reads_one(self):
        records = {
            f"r{i}": _rank_status(i, 40, dispatch_mean_ms=100.0)
            for i in range(3)
        }
        fl = _fake_fleet(records)
        fl.scrape_once()
        block = fl.block()
        assert block["straggler_ratio"] == 1.0
        assert block["rank_step_skew"] == 0
        assert block["dispatch_skew_ms"] == 0.0

    def test_exchange_frac_is_worst_rank(self):
        records = {
            "r0": _rank_status(0, 10, 100.0, elapsed=100.0,
                               exchange_total_s=1.0),
            "r1": _rank_status(1, 10, 100.0, elapsed=100.0,
                               exchange_total_s=30.0),
        }
        fl = _fake_fleet(records)
        fl.scrape_once()
        block = fl.block()
        # max(1/100, 30/100) — one rank stuck at the barrier IS the
        # signal; a mean would dilute it.
        assert block["exchange_frac"] == pytest.approx(0.3)
        assert "exchange_p99_ms" in block

    def test_metrics_lines_labeled_per_rank(self):
        records = {
            "r0": _rank_status(0, 40, 100.0),
            "r1": _rank_status(1, 38, 300.0),
        }
        fl = _fake_fleet(records)
        fl.scrape_once()
        text = fl.metrics_lines()
        assert "# TYPE tffm_train_rank_step gauge" in text
        assert 'tffm_train_rank_step{rank="0"} 40' in text
        assert 'tffm_train_rank_step{rank="1"} 38' in text
        assert (
            'tffm_train_rank_dispatch_mean_ms{rank="1"} 300.0' in text
        )
        assert 'tffm_train_rank_examples_total{rank="0"} 1000' in text

    def test_failed_fetch_keeps_previous_and_counts_error(self):
        tel = obs.Telemetry()
        calls = {"n": 0}

        def fetch(target):
            calls["n"] += 1
            if calls["n"] > 1:
                raise OSError("rank died")
            return _rank_status(0, 40, 100.0)

        fl = obs.TrainFleet(
            ["r0"], telemetry=tel, fetch=fetch, start=False
        )
        assert fl.scrape_once() == 1
        t_first = fl._latest["r0"][0]
        assert fl.scrape_once() == 0  # death -> kept, not crashed
        assert fl._latest["r0"][0] == t_first
        block = fl.block(now=t_first + 30.0)
        assert block["ranks_scraped"] == 1
        assert block["scrape_age_max_s"] == pytest.approx(30.0)
        snap = tel.snapshot()
        assert snap["counters"]["train.fleet_scrape_errors"] == 1
        assert snap["timers"]["train.fleet_scrape"]["count"] == 2

    def test_real_statusserver_death_degrades_to_staleness(self):
        recs = [_rank_status(i, 40, 100.0) for i in range(2)]
        servers = [
            StatusServer(0, (lambda r: (lambda: r))(r)) for r in recs
        ]
        try:
            fl = obs.TrainFleet(
                [f"127.0.0.1:{s.port}" for s in servers], start=False,
                timeout=2.0,
            )
            assert fl.scrape_once() == 2
            assert fl.block()["ranks_scraped"] == 2
            servers[1].close()  # rank 1 dies
            assert fl.scrape_once() == 1
            block = fl.block()
            # Still two ranks in the view; the dead one only ages.
            assert block["ranks_scraped"] == 2
            assert block["scrape_age_max_s"] >= 0
        finally:
            for s in servers:
                s.close()

    def test_scrape_thread_lifecycle(self):
        records = {"r0": _rank_status(0, 1, 100.0)}
        fl = obs.TrainFleet(
            ["r0"], interval_s=0.01, fetch=lambda t: records[t]
        )
        deadline = time.time() + 5
        while time.time() < deadline and not fl.rank_rows():
            time.sleep(0.01)
        assert fl.rank_rows(), "scrape thread never populated state"
        fl.close()
        assert fl._thread is None


# ---------------------------------------------------------------------------
# Alert wiring
# ---------------------------------------------------------------------------


def _fleet_rec(**fleet):
    return {"record": "heartbeat", "step": 5, "fleet": fleet}


class TestFleetAlerts:
    def test_straggler_rule_fires_and_stays_quiet(self):
        eng = AlertEngine(
            parse_rules("straggler_ratio > 1.4 for 2 : warn")
        )
        # Parity: quiet.
        assert eng.observe(_fleet_rec(straggler_ratio=1.0)) == []
        assert eng.observe(_fleet_rec(straggler_ratio=1.05)) == []
        # Breach must sustain 2 records.
        assert eng.observe(_fleet_rec(straggler_ratio=2.0)) == []
        fired = eng.observe(_fleet_rec(straggler_ratio=2.1))
        assert len(fired) == 1
        assert fired[0]["signal"] == "straggler_ratio"
        assert fired[0]["value"] == 2.1

    def test_rank_step_skew_and_exchange_frac_resolve(self):
        eng = AlertEngine(parse_rules(
            "rank_step_skew > 3 : warn; exchange_frac > 0.5 : warn"
        ))
        fired = eng.observe(
            _fleet_rec(rank_step_skew=8, exchange_frac=0.9)
        )
        assert {a["signal"] for a in fired} == {
            "rank_step_skew", "exchange_frac"
        }

    def test_scrape_age_resolves_fleet_and_serve(self):
        eng = AlertEngine(
            parse_rules("fleet_scrape_age_max_s > 10 : warn")
        )
        # Training fleet spelling (fleet.scrape_age_max_s fallback).
        assert len(eng.observe(
            _fleet_rec(scrape_age_max_s=60.0)
        )) == 1
        # Serving spelling (the primary alias) still works.
        eng2 = AlertEngine(
            parse_rules("fleet_scrape_age_max_s > 10 : warn")
        )
        rec = {"record": "heartbeat", "step": 1,
               "serve": {"fleet_scrape_age_max_s": 60.0}}
        assert len(eng2.observe(rec)) == 1

    def test_missing_fleet_block_is_quiet(self):
        eng = AlertEngine(
            parse_rules("straggler_ratio > 1.4 : warn")
        )
        assert eng.observe({"record": "heartbeat", "step": 1}) == []


# ---------------------------------------------------------------------------
# Config discipline
# ---------------------------------------------------------------------------


def _base_cfg(tmp_path, **kw):
    defaults = dict(
        vocabulary_size=64, factor_num=4, max_features=4,
        batch_size=16, model_file=str(tmp_path / "model"),
        log_steps=0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


class TestConfig:
    def test_fleet_rules_refused_when_plane_off(self, tmp_path):
        for rule in ("straggler_ratio > 1.5 : warn",
                     "rank_step_skew > 4 : halt",
                     "exchange_frac > 0.5 : warn"):
            with pytest.raises(ValueError, match="train_fleet_scrape"):
                _base_cfg(
                    tmp_path, heartbeat_secs=5, alert_rules=rule
                )

    def test_fleet_rules_accepted_when_plane_on(self, tmp_path):
        cfg = _base_cfg(
            tmp_path,
            heartbeat_secs=5,
            train_fleet_scrape="127.0.0.1:8100,127.0.0.1:8101",
            alert_rules="straggler_ratio > 1.5 for 2 : warn",
        )
        assert cfg.train_fleet_scrape.count(",") == 1

    def test_scrape_needs_heartbeat(self, tmp_path):
        with pytest.raises(ValueError, match="heartbeat_secs"):
            _base_cfg(tmp_path, train_fleet_scrape="127.0.0.1:8100")

    def test_bad_targets_rejected(self, tmp_path):
        for bad in ("localhost", "127.0.0.1:notaport",
                    "127.0.0.1:0", ":9", "127.0.0.1:70000"):
            with pytest.raises(ValueError, match="train_fleet_scrape"):
                _base_cfg(
                    tmp_path, heartbeat_secs=5, train_fleet_scrape=bad
                )

    def test_age_rule_stays_serve_gated(self, tmp_path):
        # fleet_scrape_age_max_s primarily aliases the SERVE plane —
        # it must stay accepted with serve fleet config and no
        # train_fleet_scrape (back-compat for PR 13 rule files).
        cfg = _base_cfg(
            tmp_path, heartbeat_secs=5, serve_replicas=2,
            alert_rules="fleet_scrape_age_max_s > 30 : warn",
        )
        assert cfg.serve_replicas == 2


# ---------------------------------------------------------------------------
# rank-suffixed writer paths
# ---------------------------------------------------------------------------


class TestRankSuffix:
    def test_rank_zero_and_empty_unchanged(self):
        assert obs.rank_suffix_path("/tmp/m.jsonl", 0) == "/tmp/m.jsonl"
        assert obs.rank_suffix_path("", 3) == ""

    def test_nonzero_ranks_suffixed(self):
        assert (
            obs.rank_suffix_path("/tmp/m.jsonl", 1) == "/tmp/m.jsonl.rank1"
        )
        assert (
            obs.rank_suffix_path("/tmp/m.jsonl", 7) == "/tmp/m.jsonl.rank7"
        )


# ---------------------------------------------------------------------------
# Exchange probe + bitwise-off parity (the jax-touching part)
# ---------------------------------------------------------------------------


class TestExchangeProbe:
    @pytest.mark.parametrize("impl", ["gspmd", "shardmap"])
    def test_probe_reduces_to_device_count(self, tmp_path, impl):
        import jax

        from fast_tffm_tpu.parallel import mesh as mesh_lib

        cfg = _base_cfg(tmp_path, mesh_data=4, mesh_model=2)
        mesh = mesh_lib.make_mesh(cfg)
        if impl == "gspmd":
            from fast_tffm_tpu.train import sparse as lib
        else:
            from fast_tffm_tpu.train import shardmap_step as lib
        probe = lib.make_exchange_probe(mesh)
        out = probe()
        jax.block_until_ready(out)
        assert float(out) == float(mesh.size)
        # Repeat dispatches reuse the compiled probe.
        assert float(probe()) == float(mesh.size)


class TestFleetOffBitwise:
    def test_fleet_plane_off_is_bitwise_identical(self, tmp_path):
        """train_fleet_scrape on (scraping itself, exchange probe
        live) vs off: identical final table bits."""
        import jax

        from fast_tffm_tpu.train.loop import Trainer

        def _write_data(path):
            rng = np.random.default_rng(0)
            with open(path, "w") as f:
                for _ in range(256):
                    feats = rng.choice(50, size=3, replace=False)
                    toks = " ".join(
                        f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats
                    )
                    f.write(f"{rng.integers(0, 2)} {toks}\n")
            return str(path)

        data = _write_data(tmp_path / "train.libsvm")
        tables = {}
        for tag in ("on", "off"):
            kw = dict(
                vocabulary_size=50, factor_num=4, max_features=4,
                batch_size=32, epoch_num=1, thread_num=2,
                steps_per_dispatch=4, seed=3, log_steps=0,
                model_file=str(tmp_path / f"model_{tag}"),
                train_files=[data],
            )
            if tag == "on":
                port = _free_port()
                kw.update(
                    status_port=port, heartbeat_secs=0.2,
                    train_fleet_scrape=f"127.0.0.1:{port}",
                )
            t = Trainer(kw.pop("_unused", None) or FmConfig(**kw))
            t.train()
            tables[tag] = np.asarray(t.state.params.table)
        np.testing.assert_array_equal(tables["on"], tables["off"])


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
