"""Host-side sort metadata must match the device-side prep exactly.

native.sort_meta re-derives, in C++, everything ops/sparse_apply._prep
computes from the batch ids on device (stable sort permutation, unique
positions, chunk/tile boundary metadata).  Both sorts are stable, so
every integer output — and therefore the K1/K2 numerics downstream —
must agree BIT-EXACTLY, not approximately.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fast_tffm_tpu.data import native
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.ops import sparse_apply

V, D = 2048, 9


def _device_meta(ids, vocab):
    """The device-side quantities, via the same code sort_meta mirrors."""
    g = jnp.zeros((ids.shape[0], D), jnp.float32)
    payload, upos, starts, firsts, ends, sidx, n_pad = sparse_apply._prep(
        jnp.asarray(ids), g, vocab
    )
    tile_start = sparse_apply._tile_starts(
        sidx, upos,
        jnp.arange(0, vocab + 1, sparse_apply.TILE, dtype=sidx.dtype),
    )
    # perm is recoverable from payload only indirectly; recompute it the
    # way _prep does.
    n = ids.shape[0]
    ids_pad = np.concatenate(
        [ids, np.full((n_pad - n,), vocab, ids.dtype)]
    )
    _, perm = jax.lax.sort_key_val(
        jnp.asarray(ids_pad), jnp.arange(n_pad, dtype=jnp.int32)
    )
    lrow_last = payload[:, 2 * D]  # the metadata column, pre-128-pad slot
    return {
        "perm": np.asarray(perm),
        "upos": np.asarray(upos),
        "lrow_last": np.asarray(lrow_last),
        "starts": np.asarray(starts),
        "firsts": np.asarray(firsts),
        "ends": np.asarray(ends),
        "tile_start": np.asarray(tile_start),
    }


def _ids(seed, n, hot=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, V, (n,)).astype(np.int32)
    if hot:
        ids[:hot] = 7  # a hot id spanning chunks
    return ids


@pytest.mark.parametrize(
    "n,hot",
    [
        (1200, 0),        # padded tail (n not a CHUNK multiple)
        (1024, 600),      # hot id spanning chunks, exact CHUNK multiple
        (4096, 1500),     # multiple chunks, duplicates everywhere
        (64, 64),         # single-id batch, heavy padding
    ],
)
def test_sort_meta_matches_device_prep(n, hot):
    ids = _ids(3, n, hot)
    meta = native.sort_meta(ids, V, sparse_apply.CHUNK, sparse_apply.TILE)
    dev = _device_meta(ids, V)
    for name in dev:
        np.testing.assert_array_equal(
            np.asarray(getattr(meta, name)), dev[name], err_msg=name
        )


@pytest.mark.parametrize("vocab", [1 << 13, 1 << 24])
def test_sort_meta_matches_device_prep_large_vocab(vocab):
    """Large vocabularies exercise the per-bucket low-bit sort passes
    (vocab 2^13: one cache-hot pass; 2^24: two, covering the ping-pong
    buffer normalization) — the default V=2048 cases have lo_bits == 0
    and skip that code entirely."""
    rng = np.random.default_rng(5)
    ids = rng.integers(0, vocab, (3000,)).astype(np.int32)
    ids[:800] = 123  # a hot id spanning chunks
    meta = native.sort_meta(ids, vocab, sparse_apply.CHUNK,
                            sparse_apply.TILE)
    dev = _device_meta(ids, vocab)
    for name in dev:
        np.testing.assert_array_equal(
            np.asarray(getattr(meta, name)), dev[name], err_msg=name
        )


def test_sort_meta_is_stable_for_duplicates():
    ids = np.asarray([5, 3, 5, 5, 3, 7], np.int32)
    meta = native.sort_meta(ids, V, sparse_apply.CHUNK, sparse_apply.TILE)
    n = len(ids)
    # Sorted order: 3(idx1), 3(idx4), 5(idx0), 5(idx2), 5(idx3), 7(idx5),
    # then sentinel slots in position order.
    expect = [1, 4, 0, 2, 3, 5] + list(range(n, sparse_apply.CHUNK))
    np.testing.assert_array_equal(meta.perm, expect)


def test_apply_with_meta_bit_identical():
    """Same stable order -> the kernels see identical inputs, so the
    host-meta path must reproduce the device-sort path bit for bit."""
    rng = np.random.default_rng(9)
    ids = _ids(9, 3000, hot=700)
    g = jnp.asarray(rng.uniform(-1, 1, (3000, D)), jnp.float32)
    table = jnp.asarray(rng.uniform(-1, 1, (V, D)), jnp.float32)
    acc = jnp.full((V, D), 0.1, jnp.float32)
    meta = native.sort_meta(ids, V, sparse_apply.CHUNK, sparse_apply.TILE)
    t0, a0 = sparse_apply.adagrad_apply(
        table, acc, jnp.asarray(ids), g, lr=0.1, eps=1e-7
    )
    t1, a1 = sparse_apply.adagrad_apply(
        table, acc, jnp.asarray(ids), g, lr=0.1, eps=1e-7, meta=meta
    )
    np.testing.assert_array_equal(np.asarray(t0), np.asarray(t1))
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))


def test_meta_shape_drift_raises():
    ids = _ids(1, 1024)
    meta = native.sort_meta(ids, V, sparse_apply.CHUNK, sparse_apply.TILE)
    bad = meta._replace(tile_start=meta.tile_start[:-2])
    g = jnp.zeros((1024, D), jnp.float32)
    with pytest.raises(ValueError, match="sort_meta shapes"):
        sparse_apply.adagrad_apply(
            jnp.zeros((V, D), jnp.float32), jnp.zeros((V, D), jnp.float32),
            jnp.asarray(ids), g, lr=0.1, eps=1e-7, meta=bad,
        )


def test_trainer_attaches_meta_and_matches(tmp_path, monkeypatch):
    """Full sparse_step through the Trainer: host_sort on/off must agree
    bit-exactly, and the on path must actually attach meta.

    Pinned to a one-device mesh (the conftest's 8 virtual devices would
    select the sharded apply, where host meta deliberately stays off) —
    this mirrors the single-chip TPU bench configuration."""
    from jax.sharding import Mesh

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.parallel import mesh as mesh_lib
    from fast_tffm_tpu.train.loop import Trainer

    monkeypatch.setattr(
        mesh_lib, "make_mesh",
        lambda cfg, devices=None: Mesh(
            np.array(jax.devices()[:1]).reshape(1, 1),
            (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
        ),
    )
    rng = np.random.default_rng(4)
    B, F = 64, 8
    batch = Batch(
        labels=rng.integers(0, 2, (B,)).astype(np.float32),
        ids=rng.integers(0, V, (B, F)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (B, F)).astype(np.float32),
        fields=np.zeros((B, F), np.int32),
        weights=np.ones((B,), np.float32),
    )
    states = {}
    for host_sort in (True, False):
        cfg = FmConfig(
            vocabulary_size=V, factor_num=D - 1, max_features=F,
            batch_size=B, learning_rate=0.1, sparse_apply="tile",
            host_sort=host_sort,
            model_file=str(tmp_path / f"m{int(host_sort)}"),
        )
        tr = Trainer(cfg)
        put = tr._put(batch)
        assert (put.sort_meta is not None) == host_sort
        tr.state = tr._train_step(tr.state, put)
        states[host_sort] = np.asarray(tr.state.params.table)
    np.testing.assert_array_equal(states[True], states[False])


def test_pipeline_workers_attach_meta(tmp_path):
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import BatchPipeline

    path = tmp_path / "data.libsvm"
    rng = np.random.default_rng(0)
    lines = [
        "1 " + " ".join(
            f"{rng.integers(0, V)}:0.5" for _ in range(4)
        )
        for _ in range(32)
    ]
    path.write_text("\n".join(lines) + "\n")
    cfg = FmConfig(
        vocabulary_size=V, factor_num=D - 1, max_features=8, batch_size=16,
    )
    spec = (V, sparse_apply.CHUNK, sparse_apply.TILE)
    batches = list(BatchPipeline(
        [str(path)], cfg, epochs=1, shuffle=False, sort_meta_spec=spec
    ))
    assert batches and all(b.sort_meta is not None for b in batches)
    b = batches[0]
    dev = _device_meta(b.ids.reshape(-1), V)
    np.testing.assert_array_equal(b.sort_meta.perm, dev["perm"])


@pytest.mark.parametrize("bad_id", [-1, V, V + 17, np.iinfo(np.int32).min])
def test_sort_meta_rejects_out_of_range_ids(bad_id):
    """An id outside [0, vocab) must fail loud (-1 -> ValueError), never
    index the native histogram/scatter out of bounds.  The normal parser
    mods ids into range, but sort_meta is also called on arbitrary
    Batch.ids via Trainer._put."""
    ids = _ids(2, 1024)
    ids[37] = bad_id
    with pytest.raises(ValueError, match="out-of-range"):
        native.sort_meta(ids, V, sparse_apply.CHUNK, sparse_apply.TILE)


def test_pipeline_worker_sort_meta_failure_degrades(tmp_path, monkeypatch):
    """A sort_meta failure inside a pipeline worker must degrade to the
    device-sort path (sort_meta=None + one warning), not kill the epoch —
    the same contract Trainer._put documents for its own fallback."""
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data import native as native_mod
    from fast_tffm_tpu.data.pipeline import BatchPipeline

    path = tmp_path / "data.libsvm"
    rng = np.random.default_rng(1)
    lines = [
        "1 " + " ".join(f"{rng.integers(0, V)}:0.5" for _ in range(4))
        for _ in range(32)
    ]
    path.write_text("\n".join(lines) + "\n")
    cfg = FmConfig(
        vocabulary_size=V, factor_num=D - 1, max_features=8, batch_size=16,
    )

    def boom(*a, **kw):
        raise ValueError("injected sort_meta failure")

    monkeypatch.setattr(native_mod, "sort_meta", boom)
    spec = (V, sparse_apply.CHUNK, sparse_apply.TILE)
    batches = list(BatchPipeline(
        [str(path)], cfg, epochs=1, shuffle=False, sort_meta_spec=spec
    ))
    assert len(batches) == 2  # the epoch completed
    assert all(b.sort_meta is None for b in batches)
