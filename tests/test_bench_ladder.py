"""The bench degradation ladder must survive kernel failures.

Round 3's hardware window produced 0.0 ex/s because the (then-broken)
Pallas tile path crashed the first step and bench.py had no fallback.
These tests inject failures at each rung and assert the ladder walks down
to a working configuration, recording what failed.
"""

from __future__ import annotations

import numpy as np
import pytest

import bench
from fast_tffm_tpu.config import FmConfig


class _State:
    """Just enough state surface for bench._drain."""

    def __init__(self):
        class P:
            table = np.zeros((2, 2), np.float32)

        class M:
            loss_sum = np.float32(0)

        self.params = P()
        self.metrics = M()
        self.step = np.int32(0)


def _make_cfg(**overrides):
    return FmConfig(
        vocabulary_size=1024, factor_num=4, max_features=8, batch_size=64,
        **overrides,
    )


class _FakeTrainer:
    """Raises in _train_step unless the cfg matches ``works_when``."""

    works_when: dict = {}

    def __init__(self, cfg):
        self.cfg = cfg
        self.state = _State()

    def _put(self, batch):
        return batch

    def _train_step(self, state, batch):
        for key, val in type(self).works_when.items():
            if getattr(self.cfg, key) != val:
                raise NotImplementedError(
                    f"injected Mosaic failure ({key}={getattr(self.cfg, key)})"
                )
        return state


def test_ladder_walks_to_scatter():
    class T(_FakeTrainer):
        works_when = {"sparse_apply": "scatter"}

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(_make_cfg, T)
    assert rung == "scatter"
    assert cfg.sparse_apply == "scatter"
    assert len(errors) == 1 and "default" in errors[0]
    assert "injected Mosaic failure" in errors[0]


def test_ladder_walks_to_no_pallas():
    class T(_FakeTrainer):
        works_when = {"sparse_apply": "scatter", "use_pallas": False}

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(_make_cfg, T)
    assert rung == "no_pallas"
    assert not cfg.use_pallas
    assert len(errors) == 2


def test_ladder_default_passes_first():
    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(
        _make_cfg, _FakeTrainer
    )
    assert rung == "default"
    assert errors == []


def test_ladder_total_failure_reports_all():
    class T(_FakeTrainer):
        works_when = {"sparse_apply": "never-matches"}

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(_make_cfg, T)
    assert rung is None and trainer is None
    assert len(errors) == 3


def test_ladder_real_trainer_injected_step_failure(tmp_path):
    """Integration: a real Trainer whose tile path is sabotaged falls back
    to scatter and still trains."""
    from fast_tffm_tpu.train.loop import Trainer

    class SabotagedTrainer(Trainer):
        def __init__(self, cfg):
            super().__init__(cfg)
            if cfg.sparse_apply != "scatter":
                inner = self._train_step

                def boom(state, batch):
                    raise NotImplementedError(
                        "Unimplemented primitive in Pallas TPU lowering"
                    )

                self._train_step = boom

    def make_cfg(**overrides):
        overrides.setdefault("sparse_apply", "tile")
        return FmConfig(
            vocabulary_size=1024, factor_num=4, max_features=8,
            batch_size=64, model_file=str(tmp_path / "m"), **overrides,
        )

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(
        make_cfg, SabotagedTrainer
    )
    assert rung == "scatter"
    assert trainer is not None
    assert any("Pallas TPU lowering" in e for e in errors)
