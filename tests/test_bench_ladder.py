"""The bench degradation ladder must survive kernel failures.

Round 3's hardware window produced 0.0 ex/s because the (then-broken)
Pallas tile path crashed the first step and bench.py had no fallback.
These tests inject failures at each rung and assert the ladder walks down
to a working configuration, recording what failed.
"""

from __future__ import annotations

import numpy as np
import pytest

import bench
from fast_tffm_tpu.config import FmConfig


class _State:
    """Just enough state surface for bench._drain."""

    def __init__(self):
        class P:
            table = np.zeros((2, 2), np.float32)

        class M:
            loss_sum = np.float32(0)

        self.params = P()
        self.metrics = M()
        self.step = np.int32(0)


def _make_cfg(**overrides):
    return FmConfig(
        vocabulary_size=1024, factor_num=4, max_features=8, batch_size=64,
        **overrides,
    )


class _FakeTrainer:
    """Raises in _train_step unless the cfg matches ``works_when``."""

    works_when: dict = {}

    def __init__(self, cfg):
        self.cfg = cfg
        self.state = _State()

    def _put(self, batch):
        return batch

    def _train_step(self, state, batch):
        for key, val in type(self).works_when.items():
            if getattr(self.cfg, key) != val:
                raise NotImplementedError(
                    f"injected Mosaic failure ({key}={getattr(self.cfg, key)})"
                )
        return state


def test_ladder_walks_to_scatter():
    class T(_FakeTrainer):
        works_when = {"sparse_apply": "scatter"}

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(_make_cfg, T)
    assert rung == "scatter"
    assert cfg.sparse_apply == "scatter"
    assert len(errors) == 1 and "default" in errors[0]
    assert "injected Mosaic failure" in errors[0]


def test_ladder_walks_to_no_pallas():
    class T(_FakeTrainer):
        works_when = {"sparse_apply": "scatter", "use_pallas": False}

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(_make_cfg, T)
    assert rung == "no_pallas"
    assert not cfg.use_pallas
    assert len(errors) == 2


def test_ladder_default_passes_first():
    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(
        _make_cfg, _FakeTrainer
    )
    assert rung == "default"
    assert errors == []


def test_ladder_total_failure_reports_all():
    class T(_FakeTrainer):
        works_when = {"sparse_apply": "never-matches"}

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(_make_cfg, T)
    assert rung is None and trainer is None
    assert len(errors) == 3


def test_ladder_real_trainer_injected_step_failure(tmp_path):
    """Integration: a real Trainer whose tile path is sabotaged falls back
    to scatter and still trains."""
    from fast_tffm_tpu.train.loop import Trainer

    class SabotagedTrainer(Trainer):
        def __init__(self, cfg):
            super().__init__(cfg)
            if cfg.sparse_apply != "scatter":
                inner = self._train_step

                def boom(state, batch):
                    raise NotImplementedError(
                        "Unimplemented primitive in Pallas TPU lowering"
                    )

                self._train_step = boom

    def make_cfg(**overrides):
        overrides.setdefault("sparse_apply", "tile")
        return FmConfig(
            vocabulary_size=1024, factor_num=4, max_features=8,
            batch_size=64, model_file=str(tmp_path / "m"), **overrides,
        )

    rung, trainer, cfg, errors = bench.build_trainer_with_ladder(
        make_cfg, SabotagedTrainer
    )
    assert rung == "scatter"
    assert trainer is not None
    assert any("Pallas TPU lowering" in e for e in errors)


def test_probe_short_circuits_on_cpu_pin(monkeypatch):
    """JAX_PLATFORMS=cpu means there is no tunnel to probe: the probe
    must return instantly WITHOUT spawning a subprocess (a CPU-only box
    used to burn the probe timeout dialing a dead tunnel and pollute
    the result JSON with a timeout error — BENCH_r05)."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    def no_spawn(*a, **kw):  # pragma: no cover - the assertion
        raise AssertionError("probe spawned a subprocess despite cpu pin")

    monkeypatch.setattr(bench.subprocess, "run", no_spawn)
    plat, _n, err = bench._probe_backend()
    assert plat == "cpu" and err is None


def test_probe_timeout_single_attempt_sane_deadline(monkeypatch):
    """A hung tunnel gets ONE bounded probe (90 s default, down from
    240) and no full-timeout retries."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    calls = []

    def fake_run(cmd, **kw):
        calls.append(kw.get("timeout"))
        raise bench.subprocess.TimeoutExpired(cmd=cmd, timeout=kw["timeout"])

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    plat, _n, err = bench._probe_backend()
    assert plat is None and "timed out" in err
    assert calls == [90]


def test_watchdog_kills_hung_child_and_reports(tmp_path, monkeypatch):
    """A child that never returns (mid-run tunnel death) must be killed at
    the deadline, not waited on forever; the reason reaches the caller."""
    monkeypatch.setattr(bench, "WATCHDOG_S", 1)
    # Point the child at a script that sleeps past the deadline.
    hang = tmp_path / "hang.py"
    hang.write_text("import time; time.sleep(60)\n")
    monkeypatch.setattr(bench.os.path, "abspath", lambda _: str(hang))
    line, reason = bench._run_watchdog_child([])
    assert line is None
    assert "watchdog killed" in reason


def test_watchdog_returns_child_json(tmp_path, monkeypatch):
    """The parent must forward exactly the child's JSON result line."""
    child = tmp_path / "ok.py"
    child.write_text(
        "print('noise')\nprint('{\"value\": 42}')\nprint('done')\n"
    )
    monkeypatch.setattr(bench.os.path, "abspath", lambda _: str(child))
    line, reason = bench._run_watchdog_child([])
    assert reason is None
    assert bench.json.loads(line) == {"value": 42}


def test_watchdog_reports_json_less_child(tmp_path, monkeypatch):
    """A child that dies before printing JSON yields a reason, not a hang.

    Its stderr is NOT captured (it streams through live for diagnosis);
    the reason is built from the stdout tail only."""
    child = tmp_path / "die.py"
    child.write_text(
        "import sys; print('partial progress'); "
        "print('crash', file=sys.stderr); sys.exit(3)\n"
    )
    monkeypatch.setattr(bench.os.path, "abspath", lambda _: str(child))
    line, reason = bench._run_watchdog_child([])
    assert line is None
    assert "exited 3" in reason and "partial progress" in reason
