"""Live status endpoint (ISSUE 7 tentpole, layer 1) + the obs
metric-name drift lint.

Pins the endpoint guarantees:

  * ``render_prometheus`` emits valid Prometheus text exposition for
    every instrument class (counter / gauge / timer / depth histogram)
    plus the health/tiered blocks and record scalars;
  * ``StatusServer`` serves ``/metrics`` + ``/status`` + ``/healthz``
    from its own threads, degrades builder failures to 500 (never
    dies), observes its own scrape load, and closes cleanly;
  * wired through ``status_port``, the endpoint answers DURING a real
    training run with Prometheus-parseable text and the heartbeat-
    shaped JSON record — and the server is gone once train() returns;
  * ``status_port`` unset -> no server exists and training is
    bit-identical to a run with the endpoint up (read-only contract);
  * tools/check_obs.py keeps the code's instrument registry and the
    OBSERVABILITY.md schema table in lockstep.
"""

import json
import os
import re
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train.loop import Trainer

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import check_obs  # noqa: E402
from obs_smoke import check_prometheus  # noqa: E402


def _get(port: int, route: str, timeout: float = 5.0) -> tuple:
    """(http status, body bytes) for one local GET."""
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=timeout
        )
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_RECORD = {
    "record": "status",
    "step": 12,
    "elapsed": 3.25,
    "ingest_wait_frac": 0.02,
    "ingest_cache": "off",  # non-numeric scalar: must be skipped
    "stages": {
        "counters": {"ingest.examples": 4096, "ingest.batches": 4},
        "gauges": {"ingest.oor_batches": 0},
        "timers": {
            "train.dispatch": {
                "count": 3, "total_s": 0.5, "mean_ms": 166.7,
                "p50_ms": 160.0, "p95_ms": 180.0, "max_ms": 181.0,
            },
            "never.fired": {"count": 0, "total_s": 0.0},
        },
        "depths": {
            "ingest.out_q_depth": {
                "count": 10, "mean": 1.5, "max": 4,
                "buckets": {"0": 4, "1": 6},
            },
            "empty.hist": {"count": 0},
        },
    },
    "health": {"grad_norm": 0.5, "nonfinite_steps": 0},
    "tiered": {"hot_hit_frac": 0.99, "resident_rows": 128},
}


class TestRenderPrometheus:
    def test_output_is_prometheus_parseable(self):
        text = obs.render_prometheus(_RECORD)
        assert check_prometheus(text) > 0

    def test_every_instrument_class_represented(self):
        text = obs.render_prometheus(_RECORD)
        for series in (
            "tffm_step 12",
            "tffm_ingest_wait_frac 0.02",
            "tffm_counter_ingest_examples_total 4096",
            "tffm_gauge_ingest_oor_batches 0",
            "tffm_timer_train_dispatch_count 3",
            "tffm_timer_train_dispatch_seconds_total 0.5",
            "tffm_timer_train_dispatch_p95_ms 180.0",
            'tffm_depth_ingest_out_q_depth_bucket{band="0"} 4',
            "tffm_health_grad_norm 0.5",
            "tffm_tiered_hot_hit_frac 0.99",
        ):
            assert series in text, series

    def test_type_lines_and_sanitized_names(self):
        text = obs.render_prometheus(_RECORD)
        assert "# TYPE tffm_counter_ingest_examples_total counter" in text
        assert "# TYPE tffm_step gauge" in text
        # Dots sanitize to underscores; no dotted name leaks through.
        for line in text.splitlines():
            if line and not line.startswith("#"):
                name = line.split("{")[0].split(" ")[0]
                assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name), \
                    line

    def test_non_numeric_scalars_skipped(self):
        text = obs.render_prometheus(_RECORD)
        assert "ingest_cache" not in text
        assert "tffm_record" not in text

    def test_empty_record_renders_empty_but_valid(self):
        text = obs.render_prometheus({})
        assert text == "\n"


class TestStatusServer:
    def test_serves_status_metrics_healthz(self):
        server = obs.StatusServer(0, lambda: dict(_RECORD))
        try:
            code, body = _get(server.port, "/status")
            assert code == 200
            rec = json.loads(body)
            assert rec["record"] == "status" and rec["step"] == 12
            code, body = _get(server.port, "/metrics")
            assert code == 200
            assert check_prometheus(body.decode()) > 0
            code, body = _get(server.port, "/healthz")
            assert code == 200 and body == b"ok\n"
        finally:
            server.close()

    def test_unknown_route_404(self):
        server = obs.StatusServer(0, lambda: {})
        try:
            code, _ = _get(server.port, "/nope")
            assert code == 404
        finally:
            server.close()

    def test_none_record_serves_empty(self):
        """Before the owner has anything to report, the endpoint is up
        and well-formed rather than erroring."""
        server = obs.StatusServer(0, lambda: None)
        try:
            code, body = _get(server.port, "/status")
            assert code == 200 and json.loads(body) == {}
            code, _ = _get(server.port, "/metrics")
            assert code == 200
        finally:
            server.close()

    def test_builder_exception_degrades_to_500(self):
        def bad():
            raise RuntimeError("torn down")

        server = obs.StatusServer(0, bad)
        try:
            code, body = _get(server.port, "/status")
            assert code == 500 and b"torn down" in body
        finally:
            server.close()

    def test_scrape_load_is_observable(self):
        tel = obs.Telemetry(enabled=True)
        server = obs.StatusServer(0, lambda: {}, telemetry=tel)
        try:
            for _ in range(3):
                _get(server.port, "/metrics")
            assert tel.counter("status.requests").value == 3
        finally:
            server.close()

    def test_close_is_idempotent_and_frees_port(self):
        server = obs.StatusServer(0, lambda: {})
        port = server.port
        server.close()
        server.close()
        with pytest.raises((urllib.error.URLError, OSError)):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=1
            )


# ---------------------------------------------------------------------------
# Endpoint under concurrent training
# ---------------------------------------------------------------------------


def _write_libsvm(path, n_lines, vocab=50, n_feat=3, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.choice(vocab, size=n_feat, replace=False)
            toks = " ".join(f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats)
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


def _cfg(data, tmp_path, tag, **kw):
    defaults = dict(
        vocabulary_size=50,
        factor_num=4,
        model_file=str(tmp_path / f"model_{tag}"),
        train_files=[data],
        epoch_num=1,
        batch_size=32,
        max_features=4,
        log_steps=0,
        thread_num=2,
        steps_per_dispatch=4,
        seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.fixture(scope="module")
def train_file(tmp_path_factory):
    out = tmp_path_factory.mktemp("status_data")
    return _write_libsvm(out / "train.libsvm", 640)


def _throttle(trainer, delay_s: float):
    """Slow each dispatch so the endpoint has a guaranteed mid-run
    window to answer in (CPU runs of this size finish in well under a
    second otherwise)."""
    real = trainer._scan_train_step

    def slow(state, batches):
        time.sleep(delay_s)
        return real(state, batches)

    trainer._scan_train_step = slow


class TestEndpointDuringTraining:
    def test_serves_metrics_and_status_mid_run(self, train_file,
                                               tmp_path):
        port = _free_port()
        cfg = _cfg(train_file, tmp_path, "live", status_port=port)
        trainer = Trainer(cfg)
        _throttle(trainer, 0.05)
        got: dict = {}

        def poll():
            deadline = time.time() + 60
            while time.time() < deadline and "metrics" not in got:
                try:
                    code, sbody = _get(port, "/status", timeout=1)
                    if code != 200:
                        continue
                    code, mbody = _get(port, "/metrics", timeout=1)
                    if code != 200:
                        continue
                    got["status"] = sbody
                    got["metrics"] = mbody
                except Exception:
                    time.sleep(0.02)

        poller = threading.Thread(target=poll)
        poller.start()
        trainer.train()
        poller.join()
        assert "metrics" in got, "endpoint never answered mid-run"
        rec = json.loads(got["status"])
        assert rec["record"] == "status"
        # The heartbeat-record shape, on demand.
        for key in ("step", "elapsed", "health", "stages",
                    "truncated_features"):
            assert key in rec, key
        # Wall-clock attribution only once there is a dispatch to
        # attribute against — a pre-first-dispatch scrape says
        # warming_up instead of reporting startup as starvation.
        if rec["step"] == 0:
            assert rec.get("warming_up") is True
            assert "ingest_wait_frac" not in rec
        else:
            assert "ingest_wait_frac" in rec
        text = got["metrics"].decode()
        assert check_prometheus(text) > 0
        assert "tffm_counter_ingest_examples_total" in text
        assert "tffm_timer_train_dispatch_count" in text
        # The server died with the run.
        with pytest.raises((urllib.error.URLError, OSError)):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=1
            )

    def test_taken_port_warns_and_trains_anyway(self, train_file,
                                                tmp_path, caplog):
        blocker = socket.socket()
        blocker.bind(("0.0.0.0", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        try:
            cfg = _cfg(train_file, tmp_path, "taken", status_port=port)
            with caplog.at_level(
                "WARNING", logger="fast_tffm_tpu.train.loop"
            ):
                result = Trainer(cfg).train()
            assert result["train"]["steps"] == 20
            assert any(
                "status endpoint failed to bind" in r.message
                for r in caplog.records
            )
        finally:
            blocker.close()

    def test_endpoint_off_is_bit_identical_to_on(self, train_file,
                                                 tmp_path):
        """The endpoint is read-only: training with it up (and being
        scraped) produces bitwise-identical state to status_port=0."""
        import jax

        states = {}
        for tag, port in (("on", _free_port()), ("off", 0)):
            cfg = _cfg(
                train_file, tmp_path, f"bit_{tag}", status_port=port
            )
            t = Trainer(cfg)
            stop = threading.Event()
            scraper = None
            if port:
                def scrape():
                    while not stop.wait(0.01):
                        try:
                            _get(port, "/metrics", timeout=1)
                        except Exception:
                            pass

                scraper = threading.Thread(target=scrape, daemon=True)
                scraper.start()
            t.train()
            if scraper is not None:
                stop.set()
                scraper.join()
            states[tag] = t.state
        eq = jax.tree.map(
            lambda a, b: bool(np.array_equal(np.asarray(a),
                                             np.asarray(b))),
            states["on"], states["off"],
        )
        assert all(jax.tree.leaves(eq))


# ---------------------------------------------------------------------------
# tools/check_obs.py — the metric-name drift lint verify.sh runs
# ---------------------------------------------------------------------------


class TestCheckObs:
    def test_real_repo_passes(self):
        repo = os.path.dirname(_TOOLS)
        result = check_obs.audit(
            os.path.join(repo, "fast_tffm_tpu"),
            os.path.join(repo, "OBSERVABILITY.md"),
        )
        assert result["ok"], (
            result["undocumented"], result["stale"],
        )
        # The live plane's own instrument is part of the contract.
        assert "status.requests" in result["registered"]

    def _fixture(self, tmp_path, code: str, rows: list) -> dict:
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(code)
        md = tmp_path / "OBS.md"
        table = "\n".join(
            f"| `{name}` | counter | x | y |" for name in rows
        )
        md.write_text(
            "# X\n\n## Metric schema\n\n| metric | kind | stage | "
            "meaning |\n|---|---|---|---|\n" + table + "\n"
        )
        return check_obs.audit(str(pkg), str(md))

    def test_undocumented_registration_fails(self, tmp_path):
        result = self._fixture(
            tmp_path,
            'tel.counter("a.b")\ntel.timer("c.d")\n', ["a.b"],
        )
        assert not result["ok"]
        assert result["undocumented"] == ["c.d"]
        assert result["stale"] == []

    def test_stale_table_row_fails(self, tmp_path):
        result = self._fixture(
            tmp_path, 'tel.counter("a.b")\n', ["a.b", "ghost.metric"],
        )
        assert not result["ok"]
        assert result["stale"] == ["ghost.metric"]

    def test_agreement_passes_and_empty_names_ignored(self, tmp_path):
        result = self._fixture(
            tmp_path,
            'tel.counter("a.b")\nobs.NULL.counter("")\n'
            'tel.depth_hist("q.d")\n',
            ["a.b", "q.d"],
        )
        assert result["ok"], (result["undocumented"], result["stale"])

    def test_missing_schema_table_fails(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text("x = 1\n")
        md = tmp_path / "OBS.md"
        md.write_text("# no table here\n")
        assert not check_obs.audit(str(pkg), str(md))["ok"]
