"""Resource & compile observability (ISSUE 8 tentpole).

Pins the resource plane's guarantees:

  * ``obs.read_rss`` reports sane process RSS / peak-RSS;
  * the ``CompileSentinel`` accounts compiles, flags unexpected ones
    (telemetry counter + JSONL ``record: compile`` entries), and the
    trainer's AOT cache classifies the documented epoch-tail K'
    compile as EXPECTED while a shape-drift recompile is flagged and
    fires the ``recompiles_unexpected`` alert alias;
  * a ``resource`` block rides every heartbeat / final record (crash
    path included) and train results;
  * ``resource_metrics = off`` is bit-identical training (no sentinel,
    no block — the same contract as every other obs knob);
  * the component memory-ledger gauges reconcile with the actual
    allocation sizes (epoch cache, SHM ring, staging pool);
  * ``tools/report.py`` loads streams WITHOUT the block cleanly and
    ``--compare`` gates the new resource keys in the right direction.
"""

import json

import numpy as np
import pytest

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.data.pipeline import (
    BatchPipeline, _batch_nbytes, _StagingPool, stack_batches,
)
from fast_tffm_tpu.train.loop import Trainer

import os
import sys

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import report  # noqa: E402


def _write_libsvm(path, n_lines, vocab=50, n_feat=3, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.choice(vocab, size=n_feat, replace=False)
            toks = " ".join(f"{i}:{rng.uniform(0.1, 1):.3f}" for i in feats)
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    return str(path)


def _cfg(data, tmp_path, tag, **kw):
    defaults = dict(
        vocabulary_size=50,
        factor_num=4,
        model_file=str(tmp_path / f"model_{tag}"),
        train_files=[data],
        epoch_num=1,
        batch_size=32,
        max_features=4,
        log_steps=0,
        thread_num=2,
        steps_per_dispatch=4,
        seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


@pytest.fixture(scope="module")
def train_file(tmp_path_factory):
    out = tmp_path_factory.mktemp("res_data")
    # 320 lines / batch 32 = 10 batches; K=4 -> two full dispatches +
    # one epoch-tail dispatch at K'=2 (the whitelisted extra compile).
    return _write_libsvm(out / "train.libsvm", 320)


def _batch(rng, b=32, f=4, vocab=50):
    return Batch(
        labels=rng.integers(0, 2, b).astype(np.float32),
        ids=rng.integers(0, vocab, (b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (b, f)).astype(np.float32),
        fields=np.zeros((b, f), np.int32),
        weights=np.ones((b,), np.float32),
    )


# ------------------------------------------------------------- read_rss


class TestReadRss:
    def test_reports_sane_values(self):
        rss, peak = obs.read_rss()
        assert rss > 1 << 20  # a python + jax process is >> 1 MiB
        assert peak >= rss

    def test_peak_is_monotonic(self):
        _, peak0 = obs.read_rss()
        _, peak1 = obs.read_rss()
        assert peak1 >= peak0


# ------------------------------------------------------- sentinel (unit)


class _ListWriter:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


class TestCompileSentinel:
    def test_accounting_and_instruments(self):
        tel = obs.Telemetry()
        w = _ListWriter()
        s = obs.CompileSentinel(telemetry=tel, expected_k=4)
        s.set_writer(w)
        s.record(0.5, 4, True, cost={"flops": 100.0,
                                     "bytes_accessed": 400.0}, step=0)
        s.record(0.25, 2, True, cost={"flops": 50.0}, step=8)
        s.record(0.125, 4, False, step=12)
        snap = s.snapshot()
        assert snap["compiles"] == 3
        assert snap["compile_s"] == pytest.approx(0.875)
        assert snap["recompiles_unexpected"] == 1
        # Steady-state cost keeps the LARGEST-k compile's numbers.
        assert snap["flops_per_dispatch"] == 100.0
        assert snap["arithmetic_intensity"] == pytest.approx(0.25)
        # Registry instruments: timer count == compiles; the
        # unexpected counter is the alert signal's source.
        assert tel.timer("train.compile").count == 3
        assert tel.counter("train.recompiles_unexpected").value == 1
        # JSONL entries are self-describing.
        assert [r["record"] for r in w.records] == ["compile"] * 3
        assert [r["expected"] for r in w.records] == [True, True, False]
        assert w.records[0]["flops"] == 100.0

    def test_reset_is_per_run(self):
        s = obs.CompileSentinel(expected_k=2)
        s.record(1.0, 2, True, cost={"flops": 10.0})
        s.reset()
        snap = s.snapshot()
        assert snap["compiles"] == 0 and snap["compile_s"] == 0.0
        # The cached executable's cost still describes what dispatches.
        assert snap["flops_per_dispatch"] == 10.0

    def test_writer_failure_never_raises(self):
        class Bad:
            def write(self, rec):
                raise OSError("full volume")

        s = obs.CompileSentinel()
        s.set_writer(Bad())
        s.record(0.1, 1, True)  # must not raise
        assert s.snapshot()["compiles"] == 1


# --------------------------------------------------- trainer integration


class TestTrainerResource:
    def test_resource_block_and_tail_whitelist(self, train_file,
                                               tmp_path):
        """The full-run contract: resource block in heartbeat + final +
        results, `record: compile` entries, and the epoch-tail K'
        compile whitelisted (no unexpected recompile, no alert)."""
        mf = str(tmp_path / "metrics.jsonl")
        cfg = _cfg(train_file, tmp_path, "res", metrics_file=mf,
                   heartbeat_secs=0.05)
        trainer = Trainer(cfg)
        result = trainer.train()

        records = [json.loads(line) for line in open(mf)]
        beats = [r for r in records if r["record"] == "heartbeat"]
        final = [r for r in records if r["record"] == "final"][-1]
        compiles = [r for r in records if r["record"] == "compile"]

        # Two compiles: the K=4 primary and the K'=2 epoch tail, both
        # expected.
        assert [c["k"] for c in compiles] == [4, 2]
        assert all(c["expected"] for c in compiles)
        assert all(c["compile_s"] > 0 for c in compiles)

        for rec in beats + [final]:
            res = rec.get("resource")
            assert res, f"record {rec['record']} lacks resource block"
            assert res["rss_mb"] > 1
            assert res["peak_rss_mb"] >= res["rss_mb"]
            assert res["device_bytes_est"] > 0
        assert final["resource"]["compiles"] == 2
        assert final["resource"]["recompiles_unexpected"] == 0
        assert final["resource"]["compile_s"] > 0
        # XLA cost analysis captured at compile time feeds throughput
        # attribution (CPU backend reports flops, so these exist here).
        assert final["resource"]["flops_per_dispatch"] > 0
        assert final["resource"]["model_flops_per_s"] > 0
        # Run header records the knob; results carry the block.
        header = records[0]
        assert header["resource_metrics"] is True
        assert result["train"]["resource"]["compiles"] == 2

        # The alert alias resolves into the block: a rule on the
        # unexpected counter stays SILENT on this clean run...
        engine = obs.AlertEngine(
            obs.parse_rules("recompiles_unexpected > 0 : warn")
        )
        for rec in beats + [final]:
            assert engine.observe(rec) == []
        # ...and fires once the counter moves.
        poisoned = dict(final)
        poisoned["resource"] = dict(
            final["resource"], recompiles_unexpected=1
        )
        fired = engine.observe(poisoned)
        assert len(fired) == 1 and fired[0]["action"] == "warn"
        assert fired[0]["signal"] == "recompiles_unexpected"

    def test_shape_drift_recompile_flagged(self, train_file, tmp_path):
        """A mid-run batch-shape change (here: a foreign K > the
        configured steps_per_dispatch) is an UNEXPECTED recompile: the
        sentinel counts it and the warn fires in the log."""
        rng = np.random.default_rng(0)
        cfg = _cfg(train_file, tmp_path, "drift", steps_per_dispatch=2)
        trainer = Trainer(cfg)
        put = trainer._put_super

        sb2 = put(stack_batches([_batch(rng) for _ in range(2)]))
        trainer.state = trainer._scan_train_step(trainer.state, sb2)
        assert trainer._sentinel.unexpected == 0

        # Epoch-tail K' < K: whitelisted.
        sb1 = put(stack_batches([_batch(rng)]))
        trainer.state = trainer._scan_train_step(trainer.state, sb1)
        assert trainer._sentinel.unexpected == 0

        # Foreign K > configured: flagged.
        sb3 = put(stack_batches([_batch(rng) for _ in range(3)]))
        trainer.state = trainer._scan_train_step(trainer.state, sb3)
        assert trainer._sentinel.compiles == 3
        assert trainer._sentinel.unexpected == 1
        assert trainer.telemetry.counter(
            "train.recompiles_unexpected"
        ).value == 1

    def test_short_k_tail_needs_epoch_boundary(self, train_file,
                                               tmp_path):
        """The tail whitelist is confirmed, not assumed: a short-k
        compile followed by ANOTHER super-batch (not an EpochEnd /
        end of stream) is reclassified unexpected — the mid-epoch
        short-group drift class; a boundary-confirmed tail stays
        whitelisted."""
        from fast_tffm_tpu.data.pipeline import EpochEnd

        rng = np.random.default_rng(1)
        cfg = _cfg(train_file, tmp_path, "prob", steps_per_dispatch=2)
        trainer = Trainer(cfg)
        put = trainer._put_super

        sb2 = put(stack_batches([_batch(rng) for _ in range(2)]))
        trainer.state = trainer._scan_train_step(trainer.state, sb2)
        assert trainer._tail_probation is None  # startup, whatever K

        # Short-k compile -> probation armed; an EpochEnd confirms it.
        sb1 = put(stack_batches([_batch(rng)]))
        trainer.state = trainer._scan_train_step(trainer.state, sb1)
        assert trainer._tail_probation is not None
        trainer._resolve_tail_probation(EpochEnd(epoch=0))
        assert trainer._tail_probation is None
        assert trainer._sentinel.unexpected == 0

        # Same short-k dispatch again: cached (no compile), so no new
        # probation — repeat dispatches are not repeat compiles.
        trainer.state = trainer._scan_train_step(trainer.state, sb1)
        assert trainer._tail_probation is None

        # A DIFFERENT short k compiling mid-epoch: the next item is a
        # super-batch, so the provisional whitelist is revoked.
        trainer2 = Trainer(
            _cfg(train_file, tmp_path, "prob2", steps_per_dispatch=3)
        )
        put2 = trainer2._put_super
        sbp = put2(stack_batches([_batch(rng) for _ in range(3)]))
        trainer2.state = trainer2._scan_train_step(trainer2.state, sbp)
        sbs = put2(stack_batches([_batch(rng)]))
        trainer2.state = trainer2._scan_train_step(trainer2.state, sbs)
        assert trainer2._tail_probation is not None
        trainer2._resolve_tail_probation((sbp, 3))  # another super-batch
        assert trainer2._sentinel.unexpected == 1
        assert trainer2.telemetry.counter(
            "train.recompiles_unexpected"
        ).value == 1
        # End of stream (None) also confirms: re-arm and resolve clean.
        trainer2._tail_probation = (1, 9)
        trainer2._resolve_tail_probation(None)
        assert trainer2._sentinel.unexpected == 1

    def test_resource_off_is_bit_identical(self, train_file, tmp_path):
        """resource_metrics=off (no sentinel, plain jit dispatch) trains
        bit-identically to on — the same contract as telemetry/trace/
        status knobs."""
        import jax

        r_on = Trainer(
            _cfg(train_file, tmp_path, "on", resource_metrics=True)
        ).train()
        t_off = Trainer(
            _cfg(train_file, tmp_path, "off", resource_metrics=False)
        )
        r_off = t_off.train()
        assert t_off._sentinel is None
        assert "resource" not in r_off["train"]
        assert r_on["train"]["loss"] == r_off["train"]["loss"]
        assert r_on["train"]["auc"] == r_off["train"]["auc"]

        # And the params agree bitwise (fresh trainers, same seed).
        t_on2 = Trainer(
            _cfg(train_file, tmp_path, "on2", resource_metrics=True)
        )
        t_off2 = Trainer(
            _cfg(train_file, tmp_path, "off2", resource_metrics=False)
        )
        t_on2.train()
        t_off2.train()
        for a, b in zip(jax.tree.leaves(t_on2.state.params),
                        jax.tree.leaves(t_off2.state.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_crash_truthful_final_carries_resource(self, train_file,
                                                   tmp_path):
        """A run that dies mid-flight still closes its stream with a
        final record carrying the resource block (the block is built in
        the same try/finally as the crash banner)."""
        mf = str(tmp_path / "metrics.jsonl")
        cfg = _cfg(train_file, tmp_path, "crash", metrics_file=mf)
        trainer = Trainer(cfg)
        real = trainer._scan_train_step
        calls = []

        def dies(state, batches):
            if calls:
                raise RuntimeError("injected mid-run death")
            calls.append(1)
            return real(state, batches)

        trainer._scan_train_step = dies
        with pytest.raises(RuntimeError, match="injected"):
            trainer.train()
        records = [json.loads(line) for line in open(mf)]
        final = [r for r in records if r["record"] == "final"][-1]
        assert final["exception"] == "RuntimeError"
        res = final["resource"]
        assert res["rss_mb"] > 1 and res["compiles"] == 1

    def test_telemetry_off_omits_gauge_ledger(self, train_file,
                                              tmp_path):
        """With telemetry=off the owner-maintained ledger gauges are
        no-op instruments — the block OMITS ring/staging/cache bytes
        (report prints n/a) instead of reporting a lying 0 next to a
        real RSS; directly-read components stay present."""
        mf = str(tmp_path / "metrics.jsonl")
        cfg = _cfg(train_file, tmp_path, "notel", telemetry=False,
                   metrics_file=mf)
        Trainer(cfg).train()
        records = [json.loads(line) for line in open(mf)]
        final = [r for r in records if r["record"] == "final"][-1]
        res = final["resource"]
        assert res["rss_mb"] > 1
        for absent in ("ring_bytes", "staging_bytes", "cache_bytes"):
            assert absent not in res
        for present in ("cold_store_bytes", "trace_buffer_bytes",
                        "compiles"):
            assert present in res


# ----------------------------------------------------- ledger gauges


class TestLedgerGauges:
    def test_cache_bytes_reconcile(self, tmp_path):
        """ingest.cache_bytes == the summed nbytes of exactly the
        batches the epoch cache retained."""
        data = _write_libsvm(tmp_path / "t.libsvm", 192)
        cfg = FmConfig(
            vocabulary_size=50, factor_num=4, batch_size=32,
            max_features=4, thread_num=2, cache_epochs=True,
        )
        tel = obs.Telemetry()
        pipe = BatchPipeline(
            [data], cfg, epochs=2, shuffle=True, ordered=True,
            cache_epochs=True, telemetry=tel,
        )
        epoch0 = []
        for i, b in enumerate(pipe):
            if i < 6:  # 192/32 = 6 epoch-0 batches, then replays
                epoch0.append(b)
        expect = sum(_batch_nbytes(b) for b in epoch0)
        got = tel.snapshot()["gauges"]["ingest.cache_bytes"]
        assert got == expect
        assert pipe.cache_result == "cached"

    def test_cache_overflow_zeroes_gauge(self, tmp_path):
        data = _write_libsvm(tmp_path / "t.libsvm", 192)
        cfg = FmConfig(
            vocabulary_size=50, factor_num=4, batch_size=32,
            max_features=4, thread_num=2, cache_epochs=True,
        )
        tel = obs.Telemetry()
        pipe = BatchPipeline(
            [data], cfg, epochs=2, shuffle=True, ordered=True,
            cache_epochs=True, cache_max_bytes=64, telemetry=tel,
        )
        for _ in pipe:
            pass
        assert pipe.cache_result == "overflow"
        assert tel.snapshot()["gauges"]["ingest.cache_bytes"] == 0

    def test_prestacked_cache_bytes_reconcile(self, tmp_path):
        data = _write_libsvm(tmp_path / "t.libsvm", 192)
        cfg = FmConfig(
            vocabulary_size=50, factor_num=4, batch_size=32,
            max_features=4, thread_num=2, cache_epochs=True,
            cache_prestacked=True, steps_per_dispatch=2,
        )
        tel = obs.Telemetry()
        pipe = BatchPipeline(
            [data], cfg, epochs=2, shuffle=True, ordered=True,
            cache_epochs=True, prestack_k=2, telemetry=tel,
        )
        supers = []
        for item in pipe:
            if len(supers) < 3:  # 6 batches / K=2 = 3 epoch-0 groups
                supers.append(item)
        expect = sum(_batch_nbytes(sb.batch) for sb in supers)
        assert tel.snapshot()["gauges"]["ingest.cache_bytes"] == expect

    def test_ring_bytes_reconcile(self, tmp_path):
        """ingest.ring_bytes == slots x slot capacity while the SHM
        ring lives, 0 after teardown."""
        data = _write_libsvm(tmp_path / "t.libsvm", 256)
        cfg = FmConfig(
            vocabulary_size=50, factor_num=4, batch_size=32,
            max_features=4, parse_processes=1, ring_slots=2,
            shuffle_buffer=64,
        )
        tel = obs.Telemetry()
        pipe = BatchPipeline(
            [data], cfg, epochs=1, shuffle=True, ordered=True,
            telemetry=tel,
        )
        seen_live = 0
        for _ in pipe:
            g = tel.snapshot()["gauges"].get("ingest.ring_bytes", 0)
            if g:
                seen_live = g
        assert seen_live == 2 * pipe._ring_slot_bytes()
        # The generator is exhausted -> the finally ran -> gauge zeroed.
        assert tel.snapshot()["gauges"]["ingest.ring_bytes"] == 0

    def test_staging_bytes_reconcile(self, rng):
        """prefetch.staging_bytes tracks exactly the buffers the pool
        owns: alloc adds, reuse doesn't, alias-mode handoff subtracts."""
        tel = obs.Telemetry()
        gauge = tel.gauge("prefetch.staging_bytes")
        pool = _StagingPool(4, bytes_gauge=gauge)
        group = [_batch(rng) for _ in range(2)]
        bufs = pool.acquire(group)
        assert gauge.value == _batch_nbytes(bufs)
        # Retire behind a plain-numpy "device" batch (no aliasing with
        # the staging buffers) -> stays pool-owned, then reuses.
        dev = stack_batches(group)
        pool.retire(dev, group, bufs)
        assert gauge.value == _batch_nbytes(bufs)
        # Drain in-flight and reacquire: reuse allocates nothing new.
        for _ in range(4):
            g2 = [_batch(rng) for _ in range(2)]
            b2 = pool.acquire(g2)
            pool.retire(stack_batches(g2), g2, b2)
        assert gauge.value <= 5 * _batch_nbytes(bufs)

    def test_staging_alias_handoff_subtracts(self, rng):
        tel = obs.Telemetry()
        gauge = tel.gauge("prefetch.staging_bytes")
        pool = _StagingPool(2, bytes_gauge=gauge)
        pool._alias_mode = True  # zero-copy backend: pool gives away
        group = [_batch(rng) for _ in range(2)]
        bufs = pool.acquire(group)
        assert gauge.value == _batch_nbytes(bufs)
        pool.retire(None, group, bufs)
        assert gauge.value == 0


# ------------------------------------------------------ status routes


class TestCaptureRoutes:
    def test_threadz_dumps_all_threads(self):
        import threading
        import urllib.request

        server = obs.StatusServer(0, lambda: {"record": "status"})
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/threadz",
                timeout=5,
            ).read().decode()
        finally:
            server.close()
        assert "--- thread" in body
        assert "MainThread" in body
        assert threading.current_thread().name in body

    def test_profile_busy_guard(self):
        import threading
        import time
        import urllib.error
        import urllib.request

        started = threading.Event()

        def slow_profile(secs):
            started.set()
            time.sleep(0.5)
            return "/tmp/out"

        server = obs.StatusServer(
            0, lambda: {"record": "status"}, profile=slow_profile
        )
        try:
            base = f"http://127.0.0.1:{server.port}"
            results = {}

            def req_a():
                results["a"] = json.loads(urllib.request.urlopen(
                    f"{base}/profile?secs=9", timeout=10
                ).read())

            t = threading.Thread(target=req_a)
            t.start()
            assert started.wait(5)
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{base}/profile", timeout=10)
            assert exc.value.code == 409
            t.join()
            assert results["a"]["profile_dir"] == "/tmp/out"
            # The lock released: a later request succeeds again.
            doc = json.loads(urllib.request.urlopen(
                f"{base}/profile?secs=0.2", timeout=10
            ).read())
            assert doc["profile_dir"] == "/tmp/out"
        finally:
            server.close()

    def test_profile_404_without_callable(self):
        import urllib.error
        import urllib.request

        server = obs.StatusServer(0, lambda: {"record": "status"})
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/profile", timeout=5
                )
            assert exc.value.code == 404
        finally:
            server.close()

    def test_build_info_renders_as_info_gauge(self):
        text = obs.render_prometheus({
            "record": "status",
            "step": 3,
            "resource": {"rss_mb": 12.5, "compiles": 1},
            "build_info": {
                "jax_version": "0.4.37", "backend": "cpu",
                "mesh": "data1xmodel1", "steps_per_dispatch": "8",
            },
        })
        assert "tffm_resource_rss_mb 12.5" in text
        assert "tffm_resource_compiles 1" in text
        line = [
            ln for ln in text.splitlines()
            if ln.startswith("tffm_build_info{")
        ]
        assert len(line) == 1
        assert 'backend="cpu"' in line[0]
        assert 'steps_per_dispatch="8"' in line[0]
        assert line[0].endswith("} 1")


# ----------------------------------------------------- report tooling


class TestReportResource:
    def _stream(self, path, resource=None):
        recs = [
            {"record": "run_header", "rank": 0,
             "config_fingerprint": "x"},
            {"record": "train", "step": 8, "examples": 256.0,
             "loss": 0.5, "auc": 0.6, "examples_per_sec": 1000.0},
        ]
        final = {
            "record": "final", "step": 8, "elapsed": 2.0,
            "wait_input_s": 0.1, "dispatch_s": 1.0,
            "ingest_wait_frac": 0.05, "examples_in": 256,
        }
        if resource is not None:
            final["resource"] = resource
        recs.append(final)
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return str(path)

    def test_stream_without_resource_loads_cleanly(self, tmp_path,
                                                   capsys):
        """Backward compatibility: pre-resource streams summarize with
        an n/a note, never a KeyError."""
        p = self._stream(tmp_path / "old.jsonl")
        assert report.main([p]) == 0
        out = capsys.readouterr().out
        assert "memory & compile: n/a" in out

    def test_stream_with_resource_summarizes(self, tmp_path, capsys):
        p = self._stream(tmp_path / "new.jsonl", resource={
            "rss_mb": 100.0, "peak_rss_mb": 120.0, "cache_bytes": 1024,
            "compiles": 2, "compile_s": 1.5,
            "recompiles_unexpected": 1, "model_flops_per_s": 1e9,
        })
        assert report.main([p]) == 0
        out = capsys.readouterr().out
        assert "memory & compile (resource block):" in out
        assert "UNEXPECTED recompile" in out

    def test_compare_directions(self, tmp_path, capsys):
        """peak_rss_mb/compile_s/recompiles_unexpected regress when
        they RISE; model_flops_per_s when it FALLS — and a resource-less
        baseline never KeyErrors."""
        a = self._stream(tmp_path / "a.jsonl", resource={
            "peak_rss_mb": 100.0, "compile_s": 1.0,
            "recompiles_unexpected": 0, "model_flops_per_s": 1e9,
            "rss_mb": 90.0, "compiles": 2,
        })
        b = self._stream(tmp_path / "b.jsonl", resource={
            "peak_rss_mb": 200.0, "compile_s": 2.0,
            "recompiles_unexpected": 3, "model_flops_per_s": 5e8,
            "rss_mb": 90.0, "compiles": 2,
        })
        rc = report.main(["--compare", a, b])
        out = capsys.readouterr().out
        assert rc == 2
        for key in ("resource.peak_rss_mb", "resource.compile_s",
                    "resource.model_flops_per_s"):
            assert any(
                key in ln and "REGRESSION" in ln
                for ln in out.splitlines()
            ), key
        # The reverse comparison is all improvements (memory/compile
        # fell, FLOP/s rose, recompiles vanished): exit 0.
        rc2 = report.main(["--compare", b, a])
        capsys.readouterr()
        assert rc2 == 0

    def test_compare_old_vs_new_no_keyerror(self, tmp_path):
        a = self._stream(tmp_path / "old.jsonl")  # no resource block
        b = self._stream(tmp_path / "new.jsonl", resource={
            "peak_rss_mb": 100.0, "compile_s": 1.0,
        })
        # Shared keys only; resource.* drops out silently.
        assert report.main(["--compare", a, b]) == 0
