"""Input-pipeline tests: epochs, shuffling, weights, ordering."""

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import BatchPipeline, iter_lines


@pytest.fixture
def data_files(tmp_path):
    a = tmp_path / "a.libsvm"
    a.write_text("".join(f"1 {i}:1.0\n" for i in range(10)))
    b = tmp_path / "b.libsvm"
    b.write_text("".join(f"0 {i}:2.0\n" for i in range(10, 15)))
    return [str(a), str(b)]


def _cfg(**kw):
    defaults = dict(
        vocabulary_size=100, batch_size=4, max_features=4, thread_num=2,
        queue_size=4, shuffle_buffer=8,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def test_iter_lines_all_files(data_files):
    lines = list(iter_lines(data_files))
    assert len(lines) == 15
    assert all(w == 1.0 for _, w in lines)


def test_iter_lines_weight_files(data_files, tmp_path):
    wa = tmp_path / "wa.txt"
    wa.write_text("".join(f"{0.1 * (i + 1):.2f}\n" for i in range(10)))
    wb = tmp_path / "wb.txt"
    wb.write_text("".join("2.0\n" for _ in range(5)))
    lines = list(iter_lines(data_files, [str(wa), str(wb)]))
    ws = [w for _, w in lines]
    np.testing.assert_allclose(ws[:10], [0.1 * (i + 1) for i in range(10)])
    np.testing.assert_allclose(ws[10:], [2.0] * 5)


def test_iter_lines_weights_align_past_blank_lines(tmp_path):
    """Regression: weight line i pairs with data line i even when the data
    file has blank/comment lines (which are skipped with their weights)."""
    data = tmp_path / "d.libsvm"
    data.write_text("1 1:1\n\n# comment\n0 2:1\n")
    wf = tmp_path / "w.txt"
    wf.write_text("0.5\n\n\n2.0\n")
    lines = list(iter_lines([str(data)], [str(wf)]))
    assert [w for _, w in lines] == [0.5, 2.0]


def test_iter_lines_short_weight_file_raises(tmp_path):
    data = tmp_path / "d.libsvm"
    data.write_text("1 1:1\n0 2:1\n")
    wf = tmp_path / "w.txt"
    wf.write_text("0.5\n")
    with pytest.raises(ValueError, match="does not pair"):
        list(iter_lines([str(data)], [str(wf)]))


def test_pipeline_covers_all_examples(data_files):
    pipe = BatchPipeline(data_files, _cfg(), epochs=1, shuffle=False)
    batches = list(pipe)
    total = sum(int(np.sum(b.weights > 0)) for b in batches)
    assert total == 15
    # All batches padded to the static shape.
    assert all(b.ids.shape == (4, 4) for b in batches)


def test_pipeline_epochs(data_files):
    pipe = BatchPipeline(data_files, _cfg(), epochs=3, shuffle=False)
    total = sum(int(np.sum(b.weights > 0)) for b in pipe)
    assert total == 45


def test_pipeline_shuffle_changes_order(data_files):
    cfg = _cfg(thread_num=1)
    ordered = BatchPipeline(data_files, cfg, epochs=1, shuffle=False, ordered=True)
    shuffled = BatchPipeline(
        data_files, cfg, epochs=1, shuffle=True, seed=7, ordered=True
    )
    ids_a = np.concatenate([b.ids[b.vals > 0] for b in ordered])
    ids_b = np.concatenate([b.ids[b.vals > 0] for b in shuffled])
    assert sorted(ids_a.tolist()) == sorted(ids_b.tolist())
    assert ids_a.tolist() != ids_b.tolist()


def test_pipeline_ordered_preserves_input_order(data_files):
    pipe = BatchPipeline(data_files, _cfg(), epochs=1, shuffle=False, ordered=True)
    ids = np.concatenate([b.ids[b.vals > 0] for b in pipe])
    assert ids.tolist() == list(range(15))


def test_pipeline_raises_on_malformed_line(tmp_path):
    """Regression: a bad line must raise promptly, not hang the pipeline."""
    bad = tmp_path / "bad.libsvm"
    bad.write_text("1 3:0.5 bad::token:extra\n")
    pipe = BatchPipeline([str(bad)], _cfg(), epochs=1, shuffle=False)
    with pytest.raises(ValueError):
        list(pipe)


def test_pipeline_raises_on_missing_weight_file(data_files):
    pipe = BatchPipeline(
        data_files, _cfg(), weight_files=["/nonexistent_w.txt", "/nope.txt"],
        epochs=1, shuffle=False,
    )
    with pytest.raises(FileNotFoundError):
        list(pipe)


def test_raw_groups_cross_chunk_boundaries(tmp_path):
    """Fast-ingest chunking must carry partial lines/groups across reads."""
    from fast_tffm_tpu.data.pipeline import _iter_raw_groups
    from fast_tffm_tpu.data import native

    path = tmp_path / "d.libsvm"
    lines = [f"1 {i}:1.0" for i in range(257)]
    path.write_text("\n".join(lines) + "\n")
    # Absurdly small chunk size forces many boundary crossings.
    groups = list(_iter_raw_groups([str(path)], batch_size=10, chunk_bytes=17))
    parser = native.NativeParser(1000, 4, num_threads=1)
    got = []
    for buf, starts, ends in groups:
        assert len(starts) <= 10
        b = parser.parse_raw(buf, starts, ends, 10)
        got.extend(b.ids[b.vals > 0].tolist())
    assert got == list(range(257))


def test_raw_groups_pack_across_file_boundaries(tmp_path):
    """Batches pack across files (like the line path); a missing trailing
    newline at a file boundary must not merge lines."""
    from fast_tffm_tpu.data.pipeline import _iter_raw_groups
    from fast_tffm_tpu.data import native

    a = tmp_path / "a.libsvm"
    a.write_bytes(b"1 0:1.0\n1 1:1.0\n1 2:1.0")  # no trailing newline
    b = tmp_path / "b.libsvm"
    b.write_bytes(b"1 3:1.0\n1 4:1.0\n1 5:1.0\n1 6:1.0\n")
    groups = list(_iter_raw_groups([str(a), str(b)], batch_size=4))
    parser = native.NativeParser(1000, 4, num_threads=1)
    batches = [parser.parse_raw(buf, s, e, 4) for buf, s, e in groups]
    # 7 lines -> one full group of 4 (spanning the file boundary) + tail 3.
    assert [int((bb.weights > 0).sum()) for bb in batches] == [4, 3]
    got = [i for bb in batches for i in bb.ids[bb.vals > 0].tolist()]
    assert got == list(range(7))


def test_raw_parse_blank_and_comment_weight_zero(tmp_path):
    from fast_tffm_tpu.data import native

    buf = b"1 5:1.0\n\n# comment\n0 7:2.0\n"
    starts = native.find_line_offsets(buf)
    ends = np.append(starts[1:], len(buf))
    parser = native.NativeParser(100, 4, num_threads=1)
    b = parser.parse_raw(buf, starts, ends, 8)
    np.testing.assert_array_equal(b.weights[:4], [1, 0, 0, 1])
    assert b.ids[0, 0] == 5 and b.ids[3, 0] == 7


def test_raw_pipeline_matches_line_pipeline(tmp_path):
    """Fast ingest and line path parse identical batches (unshuffled)."""
    path = tmp_path / "d.libsvm"
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(100):
            toks = " ".join(
                f"{rng.integers(0, 99)}:{rng.uniform(0, 2):.4f}"
                for _ in range(rng.integers(1, 5))
            )
            f.write(f"{rng.integers(0, 2)} {toks}\n")
    cfg_fast = _cfg(fast_ingest=True)
    cfg_line = _cfg(fast_ingest=False)
    fast = list(BatchPipeline([str(path)], cfg_fast, epochs=1, shuffle=False,
                              ordered=True))
    line = list(BatchPipeline([str(path)], cfg_line, epochs=1, shuffle=False,
                              ordered=True))
    assert len(fast) == len(line)
    for bf, bl in zip(fast, line):
        np.testing.assert_array_equal(bf.ids, bl.ids)
        np.testing.assert_array_equal(bf.vals, bl.vals)
        np.testing.assert_array_equal(bf.labels, bl.labels)
        np.testing.assert_array_equal(bf.weights, bl.weights)


def test_fast_ingest_line_level_shuffle_mixes_sorted_labels(tmp_path):
    """A label-sorted file (the norm for CTR logs) must yield label-mixed
    batches under fast ingest: the shuffle permutes LINES within a
    shuffle_buffer window, not just batch-group order — group-granularity
    shuffling would deliver single-label batches no matter the order."""
    path = tmp_path / "sorted.libsvm"
    n = 4096
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"{0 if i < n // 2 else 1} {i % 97}:1.0\n")
    cfg = _cfg(batch_size=64, shuffle_buffer=2048, thread_num=2)
    assert cfg.fast_ingest
    mixed = 0
    total = 0
    for b in BatchPipeline([str(path)], cfg, epochs=1, shuffle=True, seed=3):
        labels = b.labels[b.weights > 0]
        total += 1
        if 0 < labels.sum() < len(labels):
            mixed += 1
    assert total == n // 64
    # With line-level mixing virtually every batch holds both labels.
    assert mixed / total > 0.9


def test_pipeline_ordered_parallel_matches_single_thread(data_files):
    """ordered=True must deliver identical batches in identical order
    regardless of thread_num (model-axis-spanning hosts rely on this) —
    parsing fans out to workers, delivery reorders by sequence number."""
    one = _keys(BatchPipeline(
        data_files, _cfg(thread_num=1), epochs=2, shuffle=True, seed=5,
        ordered=True,
    ))
    four = _keys(BatchPipeline(
        data_files, _cfg(thread_num=4), epochs=2, shuffle=True, seed=5,
        ordered=True,
    ))
    assert one == four


def test_pipeline_drop_remainder(data_files):
    pipe = BatchPipeline(
        data_files, _cfg(), epochs=1, shuffle=False, drop_remainder=True
    )
    batches = list(pipe)
    assert all(int(np.sum(b.weights > 0)) == 4 for b in batches)
    assert len(batches) == 3  # 15 // 4


def _keys(pipe):
    return [
        (b.labels.tobytes(), b.ids.tobytes(), b.vals.tobytes())
        for b in pipe
    ]


def test_pipeline_shard_disjoint_and_complete(tmp_path):
    """Host-sharded input: shards partition the identically-seeded stream
    batch-for-batch (shard s takes items s, n+s, 2n+s, ...)."""
    path = tmp_path / "data.libsvm"
    path.write_text("".join(f"{i % 2} {i % 90}:1.0\n" for i in range(40)))
    cfg = _cfg(thread_num=1)  # deterministic batch order
    full = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=True))
    assert len(full) == 10
    s0 = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=True,
                             shard=(0, 2)))
    s1 = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=True,
                             shard=(1, 2)))
    assert s0 == full[0::2]
    assert s1 == full[1::2]


def test_pipeline_shard_drops_partial_round(tmp_path):
    """Every shard must emit the SAME batch count (a host with one extra
    step would deadlock the others), so the tail round is dropped when the
    stream length is not a multiple of num_shards."""
    path = tmp_path / "data.libsvm"
    path.write_text("".join(f"1 {i % 90}:1.0\n" for i in range(20)))
    cfg = _cfg(thread_num=1)  # 5 groups (last one partial)
    s0 = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=False,
                             ordered=True, shard=(0, 2)))
    s1 = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=False,
                             ordered=True, shard=(1, 2)))
    assert len(s0) == len(s1) == 2  # floor(5 / 2) rounds


def test_pipeline_shard_with_skip(tmp_path):
    """Mid-epoch resume composes with sharding: skip applies to MY share."""
    path = tmp_path / "data.libsvm"
    path.write_text("".join(f"1 {i % 90}:1.0\n" for i in range(40)))
    cfg = _cfg(thread_num=1)
    s0 = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=True,
                             shard=(0, 2)))
    s0_skip = _keys(BatchPipeline([str(path)], cfg, epochs=1, shuffle=True,
                                  shard=(0, 2), skip_batches=2))
    assert s0_skip == s0[2:]


def test_sort_meta_out_of_range_warns_per_batch(tmp_path, caplog):
    """An out-of-range-id sort_meta rejection is a data/vocabulary_size
    integrity bug, not a transient native failure: the pipeline must keep
    the spec and keep warning on EVERY bad batch instead of going quiet
    while the device path silently drops those updates (ADVICE r5)."""
    import logging

    pytest.importorskip("ctypes")
    from fast_tffm_tpu.data import native
    from fast_tffm_tpu.ops import sparse_apply

    try:
        native.sort_meta(np.zeros(4, np.int32), sparse_apply.TILE,
                         sparse_apply.CHUNK, sparse_apply.TILE)
    except native.OutOfRangeIdsError:  # pragma: no cover - impossible here
        pass
    except Exception:  # pragma: no cover - env-dependent
        pytest.skip("native lib unavailable")

    # Spec vocab SMALLER than the parser's modulus: the last two of four
    # batches hold ids out of the spec's [0, TILE) range — the shape of a
    # config/data mismatch.
    tile = sparse_apply.TILE
    path = tmp_path / "oor.libsvm"
    path.write_text("".join(
        f"1 {i}:1.0\n" for i in list(range(8)) + [tile + 5] * 8
    ))
    cfg = _cfg(thread_num=1, vocabulary_size=4 * tile)
    spec = (tile, sparse_apply.CHUNK, tile)
    pipe = BatchPipeline(
        [str(path)], cfg, epochs=1, shuffle=False, ordered=True,
        sort_meta_spec=spec,
    )
    with caplog.at_level(logging.WARNING):
        batches = list(pipe)
    assert len(batches) == 4  # batches still train (device-sort path)
    bad = [b for b in batches if b.ids.max() >= tile]
    good = [b for b in batches if b.ids.max() < tile]
    assert len(bad) == 2 and len(good) == 2
    assert all(b.sort_meta is None for b in bad)
    # The spec survives the bad batches: good ones still get host prep.
    assert all(b.sort_meta is not None for b in good)
    assert pipe._sort_meta_spec is not None
    msgs = [r.message for r in caplog.records
            if "vocabulary_size is wrong" in r.message]
    assert len(msgs) == len(bad)  # one warning PER bad batch


def test_sort_meta_transient_failure_disables_once(data_files, caplog,
                                                   monkeypatch):
    """Any OTHER native failure degrades to device sort with ONE warning
    and disables the spec for the rest of the run."""
    import logging

    from fast_tffm_tpu.data import native
    from fast_tffm_tpu.ops import sparse_apply

    def boom(*a, **kw):
        raise OSError("native lib vanished")

    monkeypatch.setattr(native, "sort_meta", boom)
    cfg = _cfg(thread_num=1)
    spec = (cfg.vocabulary_size, sparse_apply.CHUNK, sparse_apply.TILE)
    pipe = BatchPipeline(
        data_files, cfg, epochs=1, shuffle=False, ordered=True,
        sort_meta_spec=spec,
    )
    with caplog.at_level(logging.WARNING):
        batches = list(pipe)
    assert len(batches) == 4
    msgs = [r.message for r in caplog.records
            if "falling back to device sort" in r.message]
    assert len(msgs) == 1
    assert pipe._sort_meta_spec is None


def test_cache_epochs_replays_same_batches_permuted(data_files):
    """cache_epochs: epoch 0 parses, later epochs replay the SAME batches
    (bitwise) in a seeded per-epoch permutation — no re-parse, identical
    coverage."""
    cfg = _cfg(thread_num=1)
    key = lambda b: (b.labels.tobytes(), b.ids.tobytes(), b.vals.tobytes())
    plain = [key(b) for b in BatchPipeline(
        data_files, cfg, epochs=1, shuffle=True, ordered=True)]
    cached = [key(b) for b in BatchPipeline(
        data_files, cfg, epochs=3, shuffle=True, ordered=True,
        cache_epochs=True)]
    assert len(cached) == 3 * len(plain)
    assert cached[:len(plain)] == plain  # epoch 0 is the normal stream
    for e in (1, 2):
        ep = cached[e * len(plain):(e + 1) * len(plain)]
        assert sorted(ep) == sorted(plain)  # same batches...
    assert cached[len(plain):2 * len(plain)] != \
        cached[2 * len(plain):]  # ...different order per epoch


def test_cache_epochs_budget_falls_back_to_reparse(data_files):
    """Blowing the byte budget abandons the cache and re-parses later
    epochs — every epoch still delivers the full stream."""
    cfg = _cfg(thread_num=1)
    got = list(BatchPipeline(
        data_files, cfg, epochs=2, shuffle=True, ordered=True,
        cache_epochs=True, cache_max_bytes=1,
    ))
    n = sum(int(np.sum(b.weights > 0)) for b in got)
    assert n == 2 * 15  # both epochs complete


def test_cache_epochs_ignored_for_single_epoch_and_sharded(data_files):
    cfg = _cfg()
    p1 = BatchPipeline(data_files, cfg, epochs=1, cache_epochs=True)
    assert not p1._cache_epochs
    p2 = BatchPipeline(data_files, cfg, epochs=2, cache_epochs=True,
                       shard=(0, 2))
    assert not p2._cache_epochs
    # A resume position no longer disables the cache: the cached path
    # re-parses epoch 0 to rebuild the replay cache (skip applies to
    # delivery only), so resumed runs replay later epochs from memory.
    p3 = BatchPipeline(data_files, cfg, epochs=2, cache_epochs=True,
                       skip_batches=1)
    assert p3._cache_epochs
