"""Input-pipeline tests: epochs, shuffling, weights, ordering."""

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import BatchPipeline, iter_lines


@pytest.fixture
def data_files(tmp_path):
    a = tmp_path / "a.libsvm"
    a.write_text("".join(f"1 {i}:1.0\n" for i in range(10)))
    b = tmp_path / "b.libsvm"
    b.write_text("".join(f"0 {i}:2.0\n" for i in range(10, 15)))
    return [str(a), str(b)]


def _cfg(**kw):
    defaults = dict(
        vocabulary_size=100, batch_size=4, max_features=4, thread_num=2,
        queue_size=4, shuffle_buffer=8,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def test_iter_lines_all_files(data_files):
    lines = list(iter_lines(data_files))
    assert len(lines) == 15
    assert all(w == 1.0 for _, w in lines)


def test_iter_lines_weight_files(data_files, tmp_path):
    wa = tmp_path / "wa.txt"
    wa.write_text("".join(f"{0.1 * (i + 1):.2f}\n" for i in range(10)))
    wb = tmp_path / "wb.txt"
    wb.write_text("".join("2.0\n" for _ in range(5)))
    lines = list(iter_lines(data_files, [str(wa), str(wb)]))
    ws = [w for _, w in lines]
    np.testing.assert_allclose(ws[:10], [0.1 * (i + 1) for i in range(10)])
    np.testing.assert_allclose(ws[10:], [2.0] * 5)


def test_iter_lines_weights_align_past_blank_lines(tmp_path):
    """Regression: weight line i pairs with data line i even when the data
    file has blank/comment lines (which are skipped with their weights)."""
    data = tmp_path / "d.libsvm"
    data.write_text("1 1:1\n\n# comment\n0 2:1\n")
    wf = tmp_path / "w.txt"
    wf.write_text("0.5\n\n\n2.0\n")
    lines = list(iter_lines([str(data)], [str(wf)]))
    assert [w for _, w in lines] == [0.5, 2.0]


def test_iter_lines_short_weight_file_raises(tmp_path):
    data = tmp_path / "d.libsvm"
    data.write_text("1 1:1\n0 2:1\n")
    wf = tmp_path / "w.txt"
    wf.write_text("0.5\n")
    with pytest.raises(ValueError, match="does not pair"):
        list(iter_lines([str(data)], [str(wf)]))


def test_pipeline_covers_all_examples(data_files):
    pipe = BatchPipeline(data_files, _cfg(), epochs=1, shuffle=False)
    batches = list(pipe)
    total = sum(int(np.sum(b.weights > 0)) for b in batches)
    assert total == 15
    # All batches padded to the static shape.
    assert all(b.ids.shape == (4, 4) for b in batches)


def test_pipeline_epochs(data_files):
    pipe = BatchPipeline(data_files, _cfg(), epochs=3, shuffle=False)
    total = sum(int(np.sum(b.weights > 0)) for b in pipe)
    assert total == 45


def test_pipeline_shuffle_changes_order(data_files):
    cfg = _cfg(thread_num=1)
    ordered = BatchPipeline(data_files, cfg, epochs=1, shuffle=False, ordered=True)
    shuffled = BatchPipeline(
        data_files, cfg, epochs=1, shuffle=True, seed=7, ordered=True
    )
    ids_a = np.concatenate([b.ids[b.vals > 0] for b in ordered])
    ids_b = np.concatenate([b.ids[b.vals > 0] for b in shuffled])
    assert sorted(ids_a.tolist()) == sorted(ids_b.tolist())
    assert ids_a.tolist() != ids_b.tolist()


def test_pipeline_ordered_preserves_input_order(data_files):
    pipe = BatchPipeline(data_files, _cfg(), epochs=1, shuffle=False, ordered=True)
    ids = np.concatenate([b.ids[b.vals > 0] for b in pipe])
    assert ids.tolist() == list(range(15))


def test_pipeline_raises_on_malformed_line(tmp_path):
    """Regression: a bad line must raise promptly, not hang the pipeline."""
    bad = tmp_path / "bad.libsvm"
    bad.write_text("1 3:0.5 bad::token:extra\n")
    pipe = BatchPipeline([str(bad)], _cfg(), epochs=1, shuffle=False)
    with pytest.raises(ValueError):
        list(pipe)


def test_pipeline_raises_on_missing_weight_file(data_files):
    pipe = BatchPipeline(
        data_files, _cfg(), weight_files=["/nonexistent_w.txt", "/nope.txt"],
        epochs=1, shuffle=False,
    )
    with pytest.raises(FileNotFoundError):
        list(pipe)


def test_pipeline_drop_remainder(data_files):
    pipe = BatchPipeline(
        data_files, _cfg(), epochs=1, shuffle=False, drop_remainder=True
    )
    batches = list(pipe)
    assert all(int(np.sum(b.weights > 0)) == 4 for b in batches)
    assert len(batches) == 3  # 15 // 4
