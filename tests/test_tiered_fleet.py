"""Rank-sharded tiered table + overlapped sparse exchange (ISSUE 19).

The pinned guarantees:

  * parity — ``tiered_partition=shards`` training is ELEMENT-WISE
    IDENTICAL to host-global tiered training AND to dense training on
    the same mesh (merged logical table, opt tables, loss, auc), for
    Adagrad and FTRL, with and without eviction churn, across K;
  * elastic resume — per-shard overlay checkpoints re-shard across a
    fleet-size change (S=1 -> S=2 and back) bitwise, and a partial
    shard set is refused loudly;
  * overlap — ``sparse_exchange_overlap=on`` produces bitwise-identical
    params to ``off`` (the prefetched entry streams are a pure function
    of the batch ids), while an impossible ``on`` refuses at build.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train import checkpoint, tiered
from fast_tffm_tpu.train.loop import Trainer

V = 256


def _write_data(path, rng, lines=256, vocab=V):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5 "
                f"{rng.integers(0, vocab)}:0.25\n"
            )


def _cfg(tmp_path, model, **kw):
    defaults = dict(
        vocabulary_size=V, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / model),
        epoch_num=2, log_steps=0, thread_num=1, seed=3,
        steps_per_dispatch=2,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _merged(trainer):
    return trainer.tiered.merged_dense(trainer._tier_host_tables())


# ------------------------------------------------------------- parity


@pytest.mark.parametrize("optimizer,hot_rows,k", [
    ("adagrad", 160, 2),   # eviction churn
    ("adagrad", V, 2),     # no churn
    ("ftrl", 160, 2),
    ("adagrad", 160, 1),   # K=1 dispatch
    ("ftrl", V, 4),        # K=4 dispatch
])
def test_sharded_matches_global_and_dense(tmp_path, rng, optimizer,
                                          hot_rows, k):
    """The parity matrix: on one mesh (1 data x 2 model columns) the
    rank-sharded tiered run, the host-global tiered run, and the dense
    run agree element-wise — loss, auc, merged logical table."""
    _write_data(tmp_path / "train.libsvm", rng)
    mesh = dict(mesh_data=1, mesh_model=2, optimizer=optimizer,
                steps_per_dispatch=k)
    rd = Trainer(_cfg(tmp_path, "dense", **mesh)).train()
    tg = Trainer(_cfg(
        tmp_path, "tglobal", table_tiering="on", hot_rows=hot_rows,
        tiered_partition="global", **mesh,
    ))
    rg = tg.train()
    ts = Trainer(_cfg(
        tmp_path, "tshards", table_tiering="on", hot_rows=hot_rows,
        tiered_partition="shards", **mesh,
    ))
    rs = ts.train()
    assert rs["train"]["loss"] == rg["train"]["loss"] == \
        rd["train"]["loss"]
    assert rs["train"]["auc"] == rg["train"]["auc"] == rd["train"]["auc"]
    ms, mg = _merged(ts), _merged(tg)
    assert len(ms) == len(mg)
    for a, b in zip(ms, mg):  # params table + optimizer slot tables
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        np.asarray(ts.state.params.w0), np.asarray(tg.state.params.w0)
    )
    snap = rs["train"]["tiered"]
    assert snap["num_shards"] == 2 and snap["owned_shards"] == 2
    if hot_rows < V:
        assert snap["rows_evicted"] > 0  # churn actually exercised


def test_sharded_auto_resolves_global_single_process(tmp_path, rng):
    """``tiered_partition=auto`` on one process is host-global: no
    sharded coordinator, identical behavior to the pre-fleet path."""
    _write_data(tmp_path / "train.libsvm", rng)
    t = Trainer(_cfg(tmp_path, "t", table_tiering="on", hot_rows=160,
                     mesh_data=1, mesh_model=2))
    assert not t._tiering_sharded
    assert isinstance(t.tiered, tiered.TieredTable)


def test_sharded_refuses_indivisible_geometry(tmp_path, rng):
    """hot_rows (and V) must split evenly across the model columns —
    a lopsided shard would silently skew per-rank capacity."""
    _write_data(tmp_path / "train.libsvm", rng)
    with pytest.raises(ValueError, match="divis"):
        Trainer(_cfg(tmp_path, "t", table_tiering="on", hot_rows=81,
                     tiered_partition="shards",
                     mesh_data=1, mesh_model=2))


# ----------------------------------------------------- elastic resume


def test_elastic_resume_reshards_bitwise(tmp_path, rng, monkeypatch):
    """Per-shard overlay checkpoints are elastic: a save under S=1
    restores under S=2 (and back) with every touched logical row
    bitwise intact, and training continues in the new geometry."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)  # force overlay
    _write_data(tmp_path / "train.libsvm", rng)

    def cfg(s, **kw):
        return _cfg(tmp_path, "m", table_tiering="on", hot_rows=192,
                    tiered_partition="shards", epoch_num=1,
                    mesh_data=1, mesh_model=s, **kw)

    t1 = Trainer(cfg(1))
    t1.train()
    assert checkpoint.exists_tiered(str(tmp_path / "m"))
    step1, scalars1, stores1 = checkpoint.restore_tiered(
        str(tmp_path / "m"))
    assert step1 == 8
    ids = np.asarray(stores1["table"]["ids"])
    rows = np.asarray(stores1["table"]["rows"])
    assert len(ids) > 0

    # S=1 -> S=2: the merged overlay filters into two shard-local
    # cold stores; every saved logical row survives bitwise.
    t2 = Trainer(cfg(2))
    assert t2._restored_step == step1
    assert t2.tiered.num_shards == 2
    np.testing.assert_array_equal(t2.tiered.gather_logical(ids), rows)
    np.testing.assert_array_equal(
        np.asarray(t2.state.params.w0), scalars1["w0"])
    r2 = t2.train()  # continues in the new geometry
    assert r2["train"]["steps"] == 8 and np.isfinite(r2["train"]["loss"])

    # The S=2 save wrote one file per shard, with a manifest.
    step2, _, stores2 = checkpoint.restore_tiered(str(tmp_path / "m"))
    assert step2 == 16

    # S=2 -> S=1: the two shard files merge back into one store.
    t3 = Trainer(cfg(1))
    assert t3._restored_step == step2
    np.testing.assert_array_equal(
        t3.tiered.gather_logical(np.asarray(stores2["table"]["ids"])),
        np.asarray(stores2["table"]["rows"]))
    r3 = t3.train()
    assert r3["train"]["steps"] == 8


def test_elastic_restore_refuses_partial_shard_set(tmp_path, rng,
                                                   monkeypatch):
    """A torn fleet save (missing shard file) refuses loudly instead of
    silently resuming from a partial table."""
    monkeypatch.setattr(tiered, "EXACT_BYTES_MAX", 0)
    _write_data(tmp_path / "train.libsvm", rng)
    c = _cfg(tmp_path, "m", table_tiering="on", hot_rows=192,
             tiered_partition="shards", epoch_num=1,
             mesh_data=1, mesh_model=2)
    Trainer(c).train()
    shard0 = tmp_path / "m" / "tiered.shard0of2.npz"
    assert shard0.exists()
    shard0.unlink()
    with pytest.raises(ValueError):
        Trainer(c)


# ----------------------------------------------------------- overlap


def _overlap_cfg(tmp_path, model, **kw):
    defaults = dict(
        vocabulary_size=1024, factor_num=4, max_features=4,
        batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / model),
        epoch_num=2, log_steps=0, thread_num=1, seed=3,
        steps_per_dispatch=2,
        mesh_data=2, mesh_model=2,
        sparse_apply="tile", sparse_exchange="entries",
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def test_overlap_on_off_bitwise_pin(tmp_path, rng):
    """Compute-overlapped exchange changes WHEN the merged entry
    streams are built, never WHAT they contain: final params are
    bitwise identical with the overlap on and off."""
    _write_data(tmp_path / "train.libsvm", rng, vocab=1024)
    toff = Trainer(_overlap_cfg(tmp_path, "off",
                                sparse_exchange_overlap="off"))
    roff = toff.train()
    ton = Trainer(_overlap_cfg(tmp_path, "on",
                               sparse_exchange_overlap="on"))
    assert ton._overlap_active
    ron = ton.train()
    assert not toff._overlap_active
    assert ron["train"]["loss"] == roff["train"]["loss"]
    assert ron["train"]["auc"] == roff["train"]["auc"]
    np.testing.assert_array_equal(
        np.asarray(ton.state.params.table),
        np.asarray(toff.state.params.table))
    np.testing.assert_array_equal(
        np.asarray(ton.state.params.w0),
        np.asarray(toff.state.params.w0))


def test_overlap_composes_with_sharded_tiering(tmp_path, rng):
    """The full tentpole in one run: rank-sharded tiering + entries
    exchange + overlap matches the host-global, non-overlapped run
    element-wise."""
    _write_data(tmp_path / "train.libsvm", rng, vocab=1024)
    base = dict(table_tiering="on", hot_rows=512)
    tg = Trainer(_overlap_cfg(tmp_path, "g", tiered_partition="global",
                              sparse_exchange_overlap="off", **base))
    rg = tg.train()
    ts = Trainer(_overlap_cfg(tmp_path, "s", tiered_partition="shards",
                              sparse_exchange_overlap="on", **base))
    assert ts._overlap_active and ts._tiering_sharded
    rs = ts.train()
    assert rs["train"]["loss"] == rg["train"]["loss"]
    for a, b in zip(_merged(ts), _merged(tg)):
        np.testing.assert_array_equal(a, b)


def test_overlap_on_refuses_unoverlappable_run(tmp_path, rng):
    """``on`` with nothing to overlap (one data shard -> no cross-rank
    exchange) is a silently-inert knob: refuse at build time."""
    _write_data(tmp_path / "train.libsvm", rng)
    with pytest.raises(ValueError, match="overlap"):
        Trainer(_cfg(tmp_path, "t", sparse_exchange_overlap="on"))
