"""Hand-sharded step (lookup=shardmap) vs the GSPMD scatter path.

The shardmap step replaces row gathering with a partial-terms psum and
computes the backward in closed form per shard, so it must reproduce the
scatter path's numbers: scores, table, optimizer state — including L2
gradients, example weights, and both loss types.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train import shardmap_step, sparse as sparse_lib

V, K = 2048, 8


def _batch(seed, b=64, f=8, weights=None):
    rng = np.random.default_rng(seed)
    w = np.ones((b,), np.float32) if weights is None else weights
    return Batch(
        labels=rng.integers(0, 2, b).astype(np.float32),
        ids=rng.integers(0, V, (b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (b, f)).astype(np.float32),
        fields=np.zeros((b, f), np.int32),
        weights=w,
    )


def _mesh(shape):
    devs = np.array(jax.devices()[:shape[0] * shape[1]]).reshape(shape)
    return Mesh(devs, (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))


@pytest.mark.parametrize("optimizer", ["adagrad", "ftrl", "sgd"])
@pytest.mark.parametrize("mesh_shape", [(4, 2), (2, 4)])
def test_shardmap_matches_scatter(optimizer, mesh_shape):
    mesh = _mesh(mesh_shape)
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        optimizer=optimizer, learning_rate=0.05, ftrl_l1=0.01, ftrl_l2=0.1,
        lookup="shardmap",
    )
    assert shardmap_step.supports_shardmap(cfg, mesh)
    rng = np.random.default_rng(3)
    weights = rng.uniform(0.5, 2.0, 64).astype(np.float32)
    weights[-5:] = 0.0  # padded examples
    batch = jax.tree.map(jnp.asarray, _batch(1, weights=weights))

    params = fm.init_params(jax.random.PRNGKey(0), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)

    p_sm, o_sm = params, opt
    sm_scores = None
    step_sm = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(
            cfg, p, o, b, mesh
        )
    )
    for _ in range(3):
        p_sm, o_sm, sm_scores = step_sm(p_sm, o_sm, batch)

    p_sc, o_sc = params, opt
    sc_scores = None
    step_sc = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )
    for _ in range(3):
        p_sc, o_sc, sc_scores = step_sc(p_sc, o_sc, batch)

    np.testing.assert_allclose(sm_scores, sc_scores, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        float(p_sm.w0), float(p_sc.w0), rtol=1e-5, atol=1e-7
    )


def test_shardmap_with_l2_matches_scatter():
    mesh = _mesh((2, 4))
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        optimizer="adagrad", learning_rate=0.05,
        factor_lambda=0.01, bias_lambda=0.002, l2_mode="batch",
        lookup="shardmap",
    )
    batch = jax.tree.map(jnp.asarray, _batch(2))
    params = fm.init_params(jax.random.PRNGKey(1), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)

    p_sm, o_sm, _ = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )(params, opt, batch)
    p_sc, o_sc, _ = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )(params, opt, batch)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)
    # w0 is where the L2 term can silently diverge (bias_lambda*w0^2/B).
    np.testing.assert_allclose(
        float(p_sm.w0), float(p_sc.w0), rtol=1e-6, atol=1e-9
    )
    np.testing.assert_allclose(
        o_sm.acc.table, o_sc.acc.table, rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        float(o_sm.acc.w0), float(o_sc.acc.w0), rtol=1e-6, atol=1e-9
    )


def test_shardmap_l2_w0_nonzero_start():
    """bias_lambda + nonzero w0: the w0 L2 gradient must match exactly."""
    mesh = _mesh((2, 4))
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        optimizer="adagrad", learning_rate=0.05,
        bias_lambda=0.5, l2_mode="batch", lookup="shardmap",
    )
    batch = jax.tree.map(jnp.asarray, _batch(6))
    params = fm.init_params(jax.random.PRNGKey(3), cfg)._replace(
        w0=jnp.float32(0.7)
    )
    opt = sparse_lib.init_sparse_opt_state(cfg, params)
    p_sm, _, _ = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )(params, opt, batch)
    p_sc, _, _ = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )(params, opt, batch)
    np.testing.assert_allclose(
        float(p_sm.w0), float(p_sc.w0), rtol=1e-6, atol=1e-9
    )


def test_shardmap_mse_loss():
    mesh = _mesh((4, 2))
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        optimizer="sgd", learning_rate=0.05, loss_type="mse",
        lookup="shardmap",
    )
    batch = jax.tree.map(jnp.asarray, _batch(4))
    params = fm.init_params(jax.random.PRNGKey(2), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)
    p_sm, _, _ = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )(params, opt, batch)
    p_sc, _, _ = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )(params, opt, batch)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)


def test_supports_shardmap_gating():
    mesh = _mesh((4, 2))
    ok = dict(vocabulary_size=V, factor_num=K, max_features=8)
    assert shardmap_step.supports_shardmap(FmConfig(**ok), mesh)
    assert shardmap_step.supports_shardmap(  # FFM rides the same inversion
        FmConfig(field_num=3, **ok), mesh
    )
    assert not shardmap_step.supports_shardmap(
        FmConfig(optimizer="adam", **ok), mesh
    )
    assert not shardmap_step.supports_shardmap(
        FmConfig(l2_mode="full", factor_lambda=0.1, **ok), mesh
    )


def _ffm_batch(seed, p_num, b=64, f=8):
    rng = np.random.default_rng(seed)
    return Batch(
        labels=rng.integers(0, 2, b).astype(np.float32),
        ids=rng.integers(0, V, (b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (b, f)).astype(np.float32),
        fields=rng.integers(0, p_num, (b, f)).astype(np.int32),
        weights=np.ones((b,), np.float32),
    )


@pytest.mark.parametrize("optimizer", ["adagrad", "ftrl"])
def test_shardmap_ffm_matches_scatter(optimizer):
    """FFM on the shardmap path: partial-S psum + closed-form backward
    must reproduce the einsum-oracle + autodiff scatter path."""
    mesh = _mesh((2, 4))
    p_num = 4
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        field_num=p_num, optimizer=optimizer, learning_rate=0.05,
        ftrl_l1=0.01, ftrl_l2=0.1, lookup="shardmap",
    )
    assert shardmap_step.supports_shardmap(cfg, mesh)
    batch = jax.tree.map(jnp.asarray, _ffm_batch(11, p_num))
    params = fm.init_params(jax.random.PRNGKey(4), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)

    p_sm, o_sm = params, opt
    step_sm = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )
    sm_scores = None
    for _ in range(2):
        p_sm, o_sm, sm_scores = step_sm(p_sm, o_sm, batch)

    p_sc, o_sc = params, opt
    step_sc = jax.jit(lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b))
    sc_scores = None
    for _ in range(2):
        p_sc, o_sc, sc_scores = step_sc(p_sc, o_sc, batch)

    np.testing.assert_allclose(sm_scores, sc_scores, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        float(p_sm.w0), float(p_sc.w0), rtol=1e-5, atol=1e-7
    )


def test_shardmap_ffm_with_l2_matches_scatter():
    mesh = _mesh((2, 4))
    p_num = 3
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        field_num=p_num, optimizer="adagrad", learning_rate=0.05,
        factor_lambda=0.01, bias_lambda=0.002, l2_mode="batch",
        lookup="shardmap",
    )
    batch = jax.tree.map(jnp.asarray, _ffm_batch(12, p_num))
    params = fm.init_params(jax.random.PRNGKey(5), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)
    p_sm, o_sm, _ = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )(params, opt, batch)
    p_sc, o_sc, _ = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )(params, opt, batch)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        o_sm.acc.table, o_sc.acc.table, rtol=1e-4, atol=1e-5
    )


@pytest.mark.parametrize("optimizer", ["adagrad", "ftrl", "sgd"])
def test_shardmap_entries_exchange_matches_scatter(optimizer):
    """sparse_exchange=entries (batch-proportional all-gather of touched
    entries) must reproduce the scatter path like the dense psum does."""
    mesh = _mesh((4, 2))
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        optimizer=optimizer, learning_rate=0.05, ftrl_l1=0.01, ftrl_l2=0.1,
        lookup="shardmap", sparse_exchange="entries",
    )
    rng = np.random.default_rng(7)
    weights = rng.uniform(0.5, 2.0, 64).astype(np.float32)
    weights[-5:] = 0.0
    batch = jax.tree.map(jnp.asarray, _batch(5, weights=weights))
    params = fm.init_params(jax.random.PRNGKey(2), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)

    p_sm, o_sm = params, opt
    step_sm = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )
    for _ in range(3):
        p_sm, o_sm, sm_scores = step_sm(p_sm, o_sm, batch)

    p_sc, o_sc = params, opt
    step_sc = jax.jit(lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b))
    for _ in range(3):
        p_sc, o_sc, sc_scores = step_sc(p_sc, o_sc, batch)

    np.testing.assert_allclose(sm_scores, sc_scores, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)


def test_shardmap_entries_ffm_matches_scatter():
    mesh = _mesh((2, 4))
    p_num = 3
    cfg = FmConfig(
        vocabulary_size=V, factor_num=K, max_features=8, batch_size=64,
        field_num=p_num, optimizer="adagrad", learning_rate=0.05,
        lookup="shardmap", sparse_exchange="entries",
    )
    batch = jax.tree.map(jnp.asarray, _ffm_batch(13, p_num))
    params = fm.init_params(jax.random.PRNGKey(6), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)
    p_sm, o_sm, _ = jax.jit(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(cfg, p, o, b, mesh)
    )(params, opt, batch)
    p_sc, o_sc, _ = jax.jit(
        lambda p, o, b: sparse_lib.sparse_step(cfg, p, o, b)
    )(params, opt, batch)
    np.testing.assert_allclose(p_sm.table, p_sc.table, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        o_sm.acc.table, o_sc.acc.table, rtol=1e-4, atol=1e-5
    )
