"""Device-resident multi-step training: K-step fused dispatch +
double-buffered H2D prefetch (ISSUE 1 tentpole).

Pins the three guarantees the super-batch loop makes:

  * scan parity — one dispatch of ``make_scan_train_step`` over a stacked
    [K, ...] super-batch produces BIT-IDENTICAL params/metrics to K
    sequential single-step dispatches (fp32; the scan body is the same
    traced step, so nothing may reorder its math),
  * resume exactness — the checkpointed mid-epoch position only advances
    by whole dispatches, so an interrupted run resumed at a super-batch
    boundary (including through the epoch-tail remainder at K' =
    leftover) reproduces the uninterrupted run's params exactly,
  * transfer-stage hygiene — DevicePrefetcher propagates source/transfer
    exceptions to the consumer and shuts its thread down deterministically.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.data.pipeline import DevicePrefetcher, stack_batches
from fast_tffm_tpu.train.loop import Trainer, make_scan_train_step


def _write_data(path, rng, lines=320, vocab=64):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5\n"
            )


def _cfg(tmp_path, **kw):
    defaults = dict(
        vocabulary_size=64, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / "model"),
        epoch_num=1, log_steps=0, thread_num=1, seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _batch(rng, b=32, f=4, vocab=64):
    return Batch(
        labels=rng.integers(0, 2, b).astype(np.float32),
        ids=rng.integers(0, vocab, (b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (b, f)).astype(np.float32),
        fields=np.zeros((b, f), np.int32),
        weights=np.ones((b,), np.float32),
    )


def _tree_equal(a, b):
    eq = jax.tree.map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))), a, b
    )
    return all(jax.tree.leaves(eq))


# ------------------------------------------------------------- scan parity


@pytest.mark.parametrize("k", [1, 4])
def test_scan_step_parity_exact(tmp_path, rng, k):
    """scan(K) over a stacked super-batch == K sequential single steps,
    bitwise (params, optimizer state, metrics, step counter)."""
    _write_data(tmp_path / "train.libsvm", rng)
    t_scan = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_scan")))
    t_one = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_one")))

    batches = [_batch(rng) for _ in range(k)]
    stacked = t_scan._put_super(stack_batches(batches))
    t_scan.state = t_scan._scan_train_step(t_scan.state, stacked)
    for b in batches:
        t_one.state = t_one._train_step(t_one.state, t_one._put(b))

    assert int(t_scan.state.step) == k
    assert _tree_equal(t_scan.state, t_one.state)


def test_scan_parity_through_trainer_end_to_end(tmp_path, rng):
    """Full train() at K=4 (10 batches: two full dispatches + a K'=2
    tail) reproduces the K=1 run bit-for-bit."""
    _write_data(tmp_path / "train.libsvm", rng)
    t4 = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m4"),
                      steps_per_dispatch=4))
    r4 = t4.train()
    t1 = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m1")))
    r1 = t1.train()
    assert r4["train"]["steps"] == r1["train"]["steps"] == 10
    assert _tree_equal(t4.state.params, t1.state.params)
    assert _tree_equal(t4.state.metrics, t1.state.metrics)


def test_scan_parity_tile_apply_with_host_sort_meta(tmp_path, rng):
    """The stacked host sort_meta rides the scan: the tile apply consumes
    one [n_pad]-slice per step and stays bit-identical to K=1."""
    from fast_tffm_tpu.parallel import mesh as mesh_lib

    _write_data(tmp_path / "train.libsvm", rng, lines=128, vocab=512)
    kw = dict(vocabulary_size=512, sparse_apply="tile", host_sort=True)
    # Host sort prep rides the single-process, single-device tile path
    # only — pin a 1-device mesh (conftest's virtual mesh has 8).
    cfg2 = _cfg(tmp_path, model_file=str(tmp_path / "mt2"),
                steps_per_dispatch=2, **kw)
    t2 = Trainer(cfg2, mesh=mesh_lib.make_mesh(cfg2, jax.devices()[:1]))
    assert t2._sort_meta_spec() is not None  # host prep actually engaged
    t2.train()
    cfg1 = _cfg(tmp_path, model_file=str(tmp_path / "mt1"), **kw)
    t1 = Trainer(cfg1, mesh=mesh_lib.make_mesh(cfg1, jax.devices()[:1]))
    t1.train()
    assert _tree_equal(t2.state.params, t1.state.params)


def test_scan_step_retraces_per_k_only(tmp_path, rng):
    """One jitted scan wrapper serves every K (the leading axis is part
    of the input shape): the epoch tail's K' costs one retrace, not a
    rebuilt trainer."""
    _write_data(tmp_path / "train.libsvm", rng)
    t = Trainer(_cfg(tmp_path))
    for k in (3, 1, 3):  # repeat K=3: cache hit, no error
        stacked = t._put_super(stack_batches([_batch(rng) for _ in range(k)]))
        t.state = t._scan_train_step(t.state, stacked)
    assert int(t.state.step) == 7


# -------------------------------------------------- resume at K granularity


def _interrupt_after_dispatches(trainer, n):
    """Make trainer.train() raise after n completed dispatches."""
    real = trainer._scan_train_step
    count = {"n": 0}

    def wrapped(state, batch):
        if count["n"] >= n:
            raise KeyboardInterrupt("simulated preemption")
        count["n"] += 1
        return real(state, batch)

    trainer._scan_train_step = wrapped


def test_resume_lands_on_super_batch_boundary_exact(tmp_path, rng):
    """Interrupt after 2 of 3 dispatches (K=4, 10 batches); the saved
    position is the 8-batch boundary, and the resumed run — whose only
    dispatch is the K'=2 epoch tail — ends bit-identical to the
    uninterrupted run."""
    _write_data(tmp_path / "train.libsvm", rng)
    full = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_full"),
                        steps_per_dispatch=4))
    full.train()

    cfg = _cfg(tmp_path, model_file=str(tmp_path / "m_int"),
               steps_per_dispatch=4, save_steps=4)
    t = Trainer(cfg)
    _interrupt_after_dispatches(t, 2)
    with pytest.raises(KeyboardInterrupt):
        t.train()

    from fast_tffm_tpu.train import checkpoint

    ds = checkpoint.restore_data_state(cfg.model_file)
    assert ds["epoch"] == 0 and ds["batches_done"] == 8  # whole dispatches

    t2 = Trainer(cfg)
    r2 = t2.train()
    assert r2["train"]["steps"] == 2  # exactly the tail remainder
    assert _tree_equal(t2.state.params, full.state.params)


def test_resume_skips_prefetched_but_untrained_batches(tmp_path, rng):
    """batches_done counts TRAINED batches only: super-batches the
    transfer stage had already staged when the run died re-parse and
    re-train on resume (nothing is lost to the prefetch buffer)."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, steps_per_dispatch=2, save_steps=2,
               prefetch_super_batches=2)
    t = Trainer(cfg)
    _interrupt_after_dispatches(t, 1)  # die after 2 of 10 batches
    with pytest.raises(KeyboardInterrupt):
        t.train()
    from fast_tffm_tpu.train import checkpoint

    assert checkpoint.restore_data_state(cfg.model_file)["batches_done"] == 2
    r = Trainer(cfg).train()
    assert r["train"]["steps"] == 8  # the other 8 batches, once each


def test_k8_smoke_tiny_run(tmp_path, rng):
    """Tier-1 exercises the K=8 fused dispatch end-to-end on CPU: a tiny
    run completes, counts every batch once, and trains to finite loss."""
    _write_data(tmp_path / "train.libsvm", rng, lines=640)  # 20 batches
    t = Trainer(_cfg(tmp_path, steps_per_dispatch=8, log_steps=5))
    r = t.train()
    assert r["train"]["steps"] == 20  # 2 full dispatches + K'=4 tail
    assert r["train"]["examples"] == 640.0
    assert np.isfinite(r["train"]["loss"])


# --------------------------------------------------------- DevicePrefetcher


def test_prefetcher_stacks_and_tails(rng):
    batches = [_batch(rng) for _ in range(7)]
    got = list(DevicePrefetcher(batches, 3, lambda b: b, depth=2))
    assert [k for _, k in got] == [3, 3, 1]
    assert got[0][0].labels.shape == (3, 32)
    np.testing.assert_array_equal(got[2][0].ids[0], batches[6].ids)


def test_prefetcher_propagates_source_exception(rng):
    def source():
        yield _batch(rng)
        yield _batch(rng)
        raise RuntimeError("reader died")

    pf = DevicePrefetcher(source(), 2, lambda b: b, depth=2)
    it = iter(pf)
    first, k = next(it)
    assert k == 2
    with pytest.raises(RuntimeError, match="reader died"):
        list(it)
    # The transfer thread is reaped by the iterator's close-on-exit.
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()


def test_prefetcher_propagates_put_exception(rng):
    def bad_put(b):
        raise ValueError("transfer failed")

    pf = DevicePrefetcher([_batch(rng)], 1, bad_put, depth=2)
    with pytest.raises(ValueError, match="transfer failed"):
        list(pf)
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()


def test_prefetcher_close_unblocks_producer(rng):
    """close() with a full output queue and an unconsumed stream must
    stop the transfer thread (no leak, no deadlock); a second close is a
    no-op."""
    many = (_batch(rng) for _ in range(1000))
    pf = DevicePrefetcher(many, 1, lambda b: b, depth=1)
    next(iter(pf))  # consume one, then abandon the stream
    time.sleep(0.05)  # let the producer fill the bounded queue
    pf.close()
    assert not pf._thread.is_alive()
    pf.close()  # idempotent


def test_prefetcher_bounded_in_flight(rng):
    """At most depth super-batches are shipped ahead of the consumer:
    the put_fn is not called for the whole stream up front."""
    calls = []

    def put(b):
        calls.append(time.monotonic())
        return b

    pf = DevicePrefetcher([_batch(rng) for _ in range(32)], 1, put, depth=2)
    time.sleep(0.3)
    # depth queued + one being offered is the cap before any consumption.
    assert len(calls) <= 3
    pf.close()


def test_stack_batches_meta_all_or_nothing(rng):
    from fast_tffm_tpu.data.libsvm import SortMeta

    b1 = _batch(rng)
    meta = SortMeta(*[np.zeros(4, np.int32)] * 2, np.zeros(4, np.float32),
                    *[np.zeros(2, np.int32)] * 3, np.zeros(3, np.int32))
    bm = b1._replace(sort_meta=meta)
    stacked = stack_batches([bm, bm])
    assert stacked.sort_meta is not None
    assert stacked.sort_meta.perm.shape == (2, 4)
    mixed = stack_batches([bm, b1])
    assert mixed.sort_meta is None  # any meta-less member drops it


def test_prefetcher_closes_source_generator(rng):
    """Ending iteration closes the source generator deterministically so
    a BatchPipeline's worker threads get reaped, not leaked."""
    closed = threading.Event()

    def source():
        try:
            for _ in range(3):
                yield _batch(rng)
        finally:
            closed.set()

    list(DevicePrefetcher(source(), 2, lambda b: b, depth=2))
    assert closed.wait(timeout=5)
