"""Epoch-persistent ingest (ISSUE 2 tentpole): ONE BatchPipeline spans
all epochs of a run, the trainer adopts the parsed-batch cache behind
``cache_epochs``, and mid-epoch resume is cache-aware.

Pins the guarantees the restructure makes:

  * overflow fallback — blowing ``cache_max_bytes`` streams the
    remaining epochs with the SAME per-epoch seeds as an uncached run
    (byte-identical stream, not just same coverage),
  * cache-aware resume — a pipeline (and a Trainer) resumed mid-epoch of
    a cached multi-epoch run delivers exactly the uninterrupted run's
    remaining batch sequence (the Trainer check is bitwise on params),
  * marker hygiene — EpochEnd markers flush the DevicePrefetcher's
    pending group so super-batches never span epochs,
  * truncation accounting — cached replays and process workers keep the
    ``truncated_features`` counter truthful.
"""

import logging

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.data.pipeline import (
    BatchPipeline, DevicePrefetcher, EpochEnd,
)
from fast_tffm_tpu.train import checkpoint
from fast_tffm_tpu.train.loop import Trainer

from test_scan_loop import _interrupt_after_dispatches, _tree_equal


def _write_data(path, rng, lines=320, vocab=64):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5\n"
            )


def _cfg(tmp_path, **kw):
    defaults = dict(
        vocabulary_size=64, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / "model"),
        epoch_num=1, log_steps=0, thread_num=1, seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _keys(pipe):
    out = []
    for b in pipe:
        if isinstance(b, EpochEnd):
            out.append(("mark", b.epoch))
        else:
            out.append((b.labels.tobytes(), b.ids.tobytes(),
                        b.vals.tobytes(), b.weights.tobytes()))
    return out


# ------------------------------------------------------------ pipeline


def test_cache_overflow_streams_with_per_epoch_seeds(tmp_path, rng):
    """Overflow fallback must reproduce the uncached multi-epoch stream
    byte-for-byte: epoch e re-parses under seed + e exactly like a run
    that never cached (not merely 'covers the data')."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, thread_num=2)
    files = cfg.train_files
    plain = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
    ))
    over = BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
        cache_epochs=True, cache_max_bytes=1,
    )
    got = _keys(over)
    assert over.cache_result == "overflow"
    assert got == plain


def test_cached_pipeline_resume_matches_fresh_run(tmp_path, rng):
    """Resume at (epoch 1, batch 3) of a cached 3-epoch run delivers
    exactly the fresh run's stream from that position: the resumed
    pipeline re-parses epoch 0 to REBUILD the cache (delivering
    nothing), then replays the same per-epoch permutations."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, thread_num=2)
    files = cfg.train_files
    full = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True,
        cache_epochs=True, epoch_marks=True,
    ))
    resumed = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True,
        cache_epochs=True, epoch_marks=True, start_epoch=1, skip_batches=3,
    ))
    i = full.index(("mark", 0))
    assert resumed == full[i + 1 + 3:]


def test_cached_resume_with_overflow_matches_streaming_resume(
    tmp_path, rng
):
    """A resumed run whose cache rebuild ALSO overflows falls back to
    streaming the resume epoch from its own seed with the skip — the
    same stream the uninterrupted overflow run delivered there."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, thread_num=2)
    files = cfg.train_files
    plain = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
    ))
    resumed = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
        cache_epochs=True, cache_max_bytes=1, start_epoch=1,
        skip_batches=3,
    ))
    i = plain.index(("mark", 0))
    assert resumed == plain[i + 1 + 3:]


def _flat_keys(pipe):
    """Like _keys but unpacking SuperBatch items to per-batch tuples."""
    from fast_tffm_tpu.data.pipeline import SuperBatch

    out = []
    for b in pipe:
        if isinstance(b, EpochEnd):
            out.append(("mark", b.epoch))
        elif isinstance(b, SuperBatch):
            sb = b.batch
            for i in range(b.n):
                out.append((sb.labels[i].tobytes(), sb.ids[i].tobytes(),
                            sb.vals[i].tobytes(), sb.weights[i].tobytes()))
        else:
            out.append((b.labels.tobytes(), b.ids.tobytes(),
                        b.vals.tobytes(), b.weights.tobytes()))
    return out


def test_prestacked_pipeline_resume_matches_fresh_run(tmp_path, rng):
    """Prestacked cache resume: a pipeline resumed at (epoch 1, batch 4)
    re-parses epoch 0 to rebuild the STACKED cache (delivering nothing),
    then replays exactly the fresh run's remaining super-batch sequence.
    K=2 over 10 batches/epoch -> the skip is 2 whole groups."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, thread_num=2)
    files = cfg.train_files
    full = _flat_keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True,
        cache_epochs=True, prestack_k=2, epoch_marks=True,
    ))
    resumed = _flat_keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True,
        cache_epochs=True, prestack_k=2, epoch_marks=True,
        start_epoch=1, skip_batches=4,
    ))
    i = full.index(("mark", 0))
    assert resumed == full[i + 1 + 4:]


def test_prestacked_overflow_streams_with_per_epoch_seeds(tmp_path, rng):
    """Overflowing the budget mid-epoch-0 with prestacked storage falls
    back to the byte-identical uncached stream, exactly like the batch
    cache does."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, thread_num=2)
    files = cfg.train_files
    plain = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
    ))
    over = BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
        cache_epochs=True, prestack_k=2, cache_max_bytes=1,
    )
    got = _flat_keys(over)
    assert over.cache_result == "overflow"
    assert got == plain


def test_pipeline_start_epoch_streams_remaining_epochs(tmp_path, rng):
    """Uncached start_epoch: epochs e0..E-1 stream under their own
    seeds — identical to the suffix of the full run."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path)
    files = cfg.train_files
    full = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
    ))
    tail = _keys(BatchPipeline(
        files, cfg, epochs=3, shuffle=True, ordered=True, epoch_marks=True,
        start_epoch=2,
    ))
    i = full.index(("mark", 1))
    assert tail == full[i + 1:]


def test_truncation_accumulates_across_cached_replays(tmp_path, rng):
    """Cached replays deliver batches whose parse dropped features; each
    replay epoch re-adds epoch 0's truncation so the trainer's periodic
    warning reports what a re-parse would have dropped."""
    path = tmp_path / "t.libsvm"
    with open(path, "w") as f:
        for i in range(64):  # 6 features, max_features=4 -> 2 dropped
            toks = " ".join(f"{(i + j) % 64}:1.0" for j in range(6))
            f.write(f"{i % 2} {toks}\n")
    cfg = _cfg(tmp_path, max_features=4)
    pipe = BatchPipeline(
        [str(path)], cfg, epochs=3, shuffle=True, ordered=True,
        cache_epochs=True,
    )
    n = sum(1 for b in pipe if not isinstance(b, EpochEnd))
    assert n == 6  # 2 batches x 3 epochs
    assert pipe.truncated_features == 3 * 128


def test_proc_pipeline_early_close_leaves_no_shm(tmp_path, rng):
    """Abandoning a process-worker pipeline mid-stream (training
    exception, prefetcher close, cache-rebuild early break) must not
    strand segments in /dev/shm: workers unlink what teardown raced,
    the parent drains what the workers shipped."""
    import os

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, parse_processes=2, queue_size=2)
    before = {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}
    it = iter(BatchPipeline(
        cfg.train_files, cfg, epochs=2, shuffle=True, ordered=True,
    ))
    next(it)  # pool running, queues filling
    it.close()  # early teardown runs the full finally chain
    after = {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}
    assert after - before == set()


def test_truncation_counted_from_process_workers(tmp_path, rng):
    """Process workers parse in children; their drop counts must ship
    back with the batches (the parent's native counter never moves)."""
    path = tmp_path / "t.libsvm"
    with open(path, "w") as f:
        for i in range(64):
            toks = " ".join(f"{(i + j) % 64}:1.0" for j in range(6))
            f.write(f"{i % 2} {toks}\n")
    cfg = _cfg(tmp_path, max_features=4, parse_processes=1)
    pipe = BatchPipeline([str(path)], cfg, epochs=1, shuffle=False,
                         ordered=True)
    assert sum(1 for _ in pipe) == 2
    assert pipe.truncated_features == 128


# ------------------------------------------------- prefetcher + markers


def _batch(rng, b=32, f=4, vocab=64):
    return Batch(
        labels=rng.integers(0, 2, b).astype(np.float32),
        ids=rng.integers(0, vocab, (b, f)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (b, f)).astype(np.float32),
        fields=np.zeros((b, f), np.int32),
        weights=np.ones((b,), np.float32),
    )


def test_prefetcher_ships_prestacked_superbatches(rng):
    """A SuperBatch from the source skips stack_batches: the prefetcher
    ships the stacked arrays as-is (identity put -> the very objects)
    and counts the hit."""
    from fast_tffm_tpu import obs
    from fast_tffm_tpu.data.pipeline import SuperBatch, stack_batches

    batches = [_batch(rng) for _ in range(4)]
    sb = SuperBatch(stack_batches(batches[:2]), 2)
    tel = obs.Telemetry()
    src = [sb, EpochEnd(0), batches[2], batches[3], EpochEnd(1)]
    got = list(DevicePrefetcher(src, 2, lambda b: b, depth=4,
                                telemetry=tel))
    assert got[0][0] is sb.batch  # no re-stack, not even a copy
    assert got[0][1] == 2
    snap = tel.snapshot()
    assert snap["counters"]["prefetch.prestack_hits"] == 1
    assert snap["counters"]["prefetch.super_batches"] == 2
    # the stack timer only fired for the non-prestacked group
    assert snap["timers"]["prefetch.stack"]["count"] == 1


def test_staging_pool_reuses_buffers_without_corruption(rng):
    """staging=True recycles host stacking buffers; with a put_fn that
    copies (device_put's contract) every delivered super-batch keeps
    its own contents even after the buffers cycle many times."""
    from fast_tffm_tpu import obs

    tel = obs.Telemetry()
    batches = [_batch(rng) for _ in range(12)]

    def copying_put(stacked):
        return Batch(*(np.copy(x) for x in stacked[:5]), sort_meta=None)

    pf = DevicePrefetcher(list(batches), 2, copying_put, depth=1,
                          telemetry=tel, staging=True)
    got = [item for item in pf if not isinstance(item, EpochEnd)]
    assert len(got) == 6
    for j, (sb, n) in enumerate(got):
        assert n == 2
        np.testing.assert_array_equal(sb.ids[0], batches[2 * j].ids)
        np.testing.assert_array_equal(sb.ids[1], batches[2 * j + 1].ids)
    # the pool only holds depth+1 bufsets, so 6 emits must have recycled
    assert tel.snapshot()["counters"]["prefetch.staging_reuse"] >= 3


def test_device_put_copies_out_of_staging_buffers():
    """The staging pool's safety contract on this backend: device_put
    COPIES host memory, so a staging buffer mutated after the put does
    not change the device array."""
    import jax

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import stack_batches
    from fast_tffm_tpu.parallel import mesh as mesh_lib

    cfg = FmConfig(vocabulary_size=64, factor_num=4, max_features=4,
                   batch_size=8)
    mesh = mesh_lib.make_mesh(cfg)
    rng = np.random.default_rng(0)
    group = [_batch(rng, b=8) for _ in range(2)]
    stacked = stack_batches(group)
    dev = mesh_lib.shard_super_batch(stacked, mesh)
    jax.block_until_ready(dev.ids)
    expect = np.asarray(dev.ids).copy()
    stacked.ids[:] = -1  # recycle the staging buffer
    np.testing.assert_array_equal(np.asarray(dev.ids), expect)


def test_prefetcher_flushes_group_at_epoch_mark(rng):
    """An EpochEnd flushes the pending group (epoch tail at K' =
    leftover) and is forwarded in position — super-batches never span
    epochs, so every checkpointed position stays within one epoch."""
    batches = [_batch(rng) for _ in range(5)]
    src = batches[:3] + [EpochEnd(0)] + batches[3:] + [EpochEnd(1)]
    got = list(DevicePrefetcher(src, 2, lambda b: b, depth=4))
    shape = [x.epoch if isinstance(x, EpochEnd) else x[1] for x in got]
    assert shape == [2, 1, 0, 2, 1]  # K, K'=1, mark0, K, mark1
    np.testing.assert_array_equal(got[1][0].ids[0], batches[2].ids)


# --------------------------------------------------------------- trainer


def test_trainer_cache_epochs_trains_and_reports(tmp_path, rng, caplog):
    """Trainer adoption: a cached multi-epoch run trains every batch of
    every epoch, logs the cache outcome once, and surfaces it in the
    result dict."""
    _write_data(tmp_path / "train.libsvm", rng)  # 10 batches
    cfg = _cfg(tmp_path, epoch_num=3, cache_epochs=True)
    with caplog.at_level(logging.INFO):
        r = Trainer(cfg).train()
    assert r["train"]["steps"] == 30
    assert r["train"]["examples"] == 3 * 320.0
    assert r["train"]["ingest_cache"] == "cached"
    msgs = [rec.getMessage() for rec in caplog.records]
    assert any("ingest cache after epoch 0: cached" in m for m in msgs)


def test_trainer_cached_midepoch_resume_bitwise(tmp_path, rng):
    """THE acceptance check: a checkpoint written mid-epoch-1 of a
    cached 3-epoch run resumes to a bitwise-identical batch stream —
    asserted through the strictest observable, final params equality
    against the uninterrupted run."""
    _write_data(tmp_path / "train.libsvm", rng)  # 10 batches/epoch
    kw = dict(epoch_num=3, cache_epochs=True, steps_per_dispatch=2)
    full = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_full"),
                        **kw))
    rf = full.train()
    assert rf["train"]["steps"] == 30

    cfg = _cfg(tmp_path, model_file=str(tmp_path / "m_int"),
               save_steps=2, **kw)
    t = Trainer(cfg)
    _interrupt_after_dispatches(t, 7)  # 14 batches: mid-epoch 1
    with pytest.raises(KeyboardInterrupt):
        t.train()
    ds = checkpoint.restore_data_state(cfg.model_file)
    assert ds["epoch"] == 1 and ds["batches_done"] == 4

    t2 = Trainer(cfg)
    r2 = t2.train()
    assert r2["train"]["steps"] == 16  # exactly the remaining batches
    # Params are the strictest stream observable (metrics are not
    # checkpointed — a resumed run accumulates only its own steps).
    assert _tree_equal(t2.state.params, full.state.params)


def test_trainer_prestacked_trains_all_and_skips_stacks(tmp_path, rng):
    """cache_prestacked end-to-end: every batch of every epoch trains,
    the prefetcher's stack is skipped on EVERY dispatch (epoch 0 stacks
    once in the pipeline; replays reuse), and the result reports the
    cache."""
    _write_data(tmp_path / "train.libsvm", rng)  # 10 batches/epoch
    cfg = _cfg(tmp_path, epoch_num=3, cache_epochs=True,
               cache_prestacked=True, steps_per_dispatch=2)
    t = Trainer(cfg)
    r = t.train()
    assert r["train"]["steps"] == 30
    assert r["train"]["examples"] == 3 * 320.0
    assert r["train"]["ingest_cache"] == "cached"
    snap = t.telemetry.snapshot()
    assert snap["counters"]["prefetch.super_batches"] == 15
    assert snap["counters"]["prefetch.prestack_hits"] == 15
    assert snap["timers"]["ingest.prestack"]["count"] == 5  # epoch 0 only


def test_trainer_prestacked_midepoch_resume_bitwise(tmp_path, rng):
    """Prestacked acceptance: a checkpoint written mid-epoch-1 of a
    prestacked 3-epoch run resumes to a bitwise-identical batch stream
    (final params equal the uninterrupted run's)."""
    _write_data(tmp_path / "train.libsvm", rng)  # 10 batches/epoch
    kw = dict(epoch_num=3, cache_epochs=True, cache_prestacked=True,
              steps_per_dispatch=2)
    full = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "mp_full"),
                        **kw))
    rf = full.train()
    assert rf["train"]["steps"] == 30

    cfg = _cfg(tmp_path, model_file=str(tmp_path / "mp_int"),
               save_steps=2, **kw)
    t = Trainer(cfg)
    _interrupt_after_dispatches(t, 7)  # 14 batches: mid-epoch 1
    with pytest.raises(KeyboardInterrupt):
        t.train()
    ds = checkpoint.restore_data_state(cfg.model_file)
    assert ds["epoch"] == 1 and ds["batches_done"] == 4

    t2 = Trainer(cfg)
    r2 = t2.train()
    assert r2["train"]["steps"] == 16
    assert _tree_equal(t2.state.params, full.state.params)


def test_fingerprint_rejects_prestack_toggle(tmp_path, rng):
    """cache_prestacked redefines epochs > 0 (super-batch permutation);
    a saved position from the other setting must be ignored."""
    from conftest import set_data_state

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, epoch_num=2, cache_epochs=True,
               cache_prestacked=True)
    Trainer(cfg).train()
    set_data_state(cfg.model_file, epoch=1, batches_done=3)
    cfg2 = _cfg(tmp_path, epoch_num=2, cache_epochs=True)
    r = Trainer(cfg2).train()
    assert r["train"]["steps"] == 20  # position ignored: full fresh run


def test_trainer_uncached_multiepoch_unchanged(tmp_path, rng):
    """The single-pipeline restructure must not change the uncached
    stream: per-epoch reseeding inside the pipeline reproduces the old
    one-pipeline-per-epoch run's data order (checked via params against
    a resume mid-epoch-2, crossing an epoch boundary)."""
    _write_data(tmp_path / "train.libsvm", rng)
    kw = dict(epoch_num=3,)
    full = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_f"), **kw))
    full.train()

    cfg = _cfg(tmp_path, model_file=str(tmp_path / "m_i"), save_steps=1,
               **kw)
    t = Trainer(cfg)
    _interrupt_after_dispatches(t, 23)  # epoch 2, batch 3
    with pytest.raises(KeyboardInterrupt):
        t.train()
    ds = checkpoint.restore_data_state(cfg.model_file)
    assert ds["epoch"] == 2 and ds["batches_done"] == 3
    t2 = Trainer(cfg)
    r2 = t2.train()
    assert r2["train"]["steps"] == 7
    assert _tree_equal(t2.state.params, full.state.params)


def test_trainer_parse_processes_bitwise(tmp_path, rng):
    """A train() through the process-worker pool is bitwise identical
    to the in-process parse (same batches, same order, same params)."""
    _write_data(tmp_path / "train.libsvm", rng)
    tt = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_t")))
    tt.train()
    tp = Trainer(_cfg(tmp_path, model_file=str(tmp_path / "m_p"),
                      parse_processes=1))
    tp.train()
    assert _tree_equal(tt.state.params, tp.state.params)
    assert _tree_equal(tt.state.metrics, tp.state.metrics)


def test_fingerprint_rejects_cache_toggle(tmp_path, rng):
    """Toggling cache_epochs redefines every epoch > 0 (batch-permuted
    replay vs line-level reshuffle), so a saved mid-run position under
    the other setting must be ignored, not resumed into wrong data."""
    from conftest import set_data_state

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, epoch_num=2, cache_epochs=True)
    Trainer(cfg).train()
    set_data_state(cfg.model_file, epoch=1, batches_done=3)
    cfg2 = _cfg(tmp_path, epoch_num=2, cache_epochs=False)
    r = Trainer(cfg2).train()
    assert r["train"]["steps"] == 20  # position ignored: full fresh run
