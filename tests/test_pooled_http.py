"""Pooled HTTP front end (ISSUE 16): a fixed pool of persistent
handler workers (plus optional SO_REUSEPORT acceptors) replaces
thread-per-connection — same request-level discipline (keep-alive,
timeouts, Content-Length), deterministic teardown with zero leaked
threads, and the legacy server still mountable via
``serve_http_threads = 0``.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.status import (
    ObsHTTPServer, PooledHTTPServer, probe_reuseport,
)


class _EchoHandler(BaseHTTPRequestHandler):
    """Answers GET with the serving thread's name — the probe for
    which pool worker handled the request."""

    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802 - http.server API name
        body = threading.current_thread().name.encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


class _SlowHandler(_EchoHandler):
    timeout = 1.0  # slow-loris eviction horizon for the test


def _start(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


def _no_pool_threads():
    return [
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith("tffm-http-")
    ]


class TestPooledHTTPServer:
    def test_keepalive_reuses_one_worker(self):
        srv = PooledHTTPServer(("127.0.0.1", 0), _EchoHandler,
                               pool_size=4)
        st = _start(srv)
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", srv.server_address[1], timeout=10
            )
            names = []
            for _ in range(3):
                conn.request("GET", "/")
                resp = conn.getresponse()
                names.append(resp.read().decode())
                assert resp.status == 200
            conn.close()
            # A kept-alive connection pins its worker: all three
            # requests ran on the SAME pool thread, and it is a pool
            # thread, not a per-connection spawn.
            assert len(set(names)) == 1
            assert names[0].startswith("tffm-http-worker-")
        finally:
            srv.shutdown()
            st.join(timeout=10)
            srv.server_close()

    def test_slow_loris_releases_worker(self):
        """A peer that connects and sends nothing must only hold its
        worker until the handler socket timeout — with pool_size=1
        the NEXT request proves the worker came back."""
        srv = PooledHTTPServer(("127.0.0.1", 0), _SlowHandler,
                               pool_size=1)
        st = _start(srv)
        try:
            loris = socket.create_connection(
                ("127.0.0.1", srv.server_address[1]), timeout=10
            )
            loris.sendall(b"GET /")  # partial request line, then stall
            time.sleep(0.2)  # let the lone worker pick the loris up
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.server_address[1]}/",
                timeout=10,
            ).read()
            assert body.decode().startswith("tffm-http-worker-")
            loris.close()
        finally:
            srv.shutdown()
            st.join(timeout=10)
            srv.server_close()

    def test_concurrent_connections_spread_over_pool(self):
        srv = PooledHTTPServer(("127.0.0.1", 0), _EchoHandler,
                               pool_size=4)
        st = _start(srv)
        try:
            names: list = []
            lock = threading.Lock()

            def hit():
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.server_address[1], timeout=10
                )
                conn.request("GET", "/")
                name = conn.getresponse().read().decode()
                time.sleep(0.3)  # keep-alive holds the worker
                conn.close()
                with lock:
                    names.append(name)

            ts = [threading.Thread(target=hit) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert len(names) == 4
            assert len(set(names)) > 1  # not serialized on one worker
        finally:
            srv.shutdown()
            st.join(timeout=10)
            srv.server_close()

    def test_teardown_leaks_no_threads(self):
        srv = PooledHTTPServer(("127.0.0.1", 0), _EchoHandler,
                               pool_size=3, acceptors=2)
        st = _start(srv)
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.server_address[1]}/", timeout=10
        ).read()
        srv.shutdown()
        st.join(timeout=10)
        srv.server_close()
        assert _no_pool_threads() == []

    def test_close_without_serve_forever(self):
        """server_close on a never-served pool must not hang (the
        accept loops may never have started serve_forever)."""
        srv = PooledHTTPServer(("127.0.0.1", 0), _EchoHandler,
                               pool_size=2)
        srv.server_close()
        assert _no_pool_threads() == []

    def test_acceptors_smoke(self):
        srv = PooledHTTPServer(("127.0.0.1", 0), _EchoHandler,
                               pool_size=2, acceptors=2)
        st = _start(srv)
        try:
            assert isinstance(srv.reuseport, bool)
            if probe_reuseport():
                assert srv.reuseport
            for _ in range(4):
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.server_address[1]}/",
                    timeout=10,
                ).read()
                assert body.decode().startswith("tffm-http-worker-")
        finally:
            srv.shutdown()
            st.join(timeout=10)
            srv.server_close()
        assert _no_pool_threads() == []


# ----------------------------------------------------------------------
# through the serving stack: pooled mount, rid minting, router smoke
# ----------------------------------------------------------------------


_CFG_KW = dict(
    vocabulary_size=64, factor_num=4, max_features=4,
    serve_batch_sizes="8", max_batch_wait_ms=1.0,
)


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    import jax

    from fast_tffm_tpu.models import fm
    from fast_tffm_tpu.serve.batcher import ServeBatcher
    from fast_tffm_tpu.serve.scorer import FixedShapeScorer

    tmp = tmp_path_factory.mktemp("pooled_http")
    cfg = FmConfig(model_file=str(tmp / "model"), **_CFG_KW)
    params = jax.jit(
        lambda k: fm.init_params(k, cfg=cfg)
    )(jax.random.PRNGKey(0))
    scorer = FixedShapeScorer(cfg, params)
    scorer.warmup()
    batcher = ServeBatcher(
        scorer, max_batch_wait_ms=cfg.max_batch_wait_ms
    )
    yield cfg, scorer, batcher
    batcher.close()


class TestServeServerPooled:
    def test_pooled_mount_is_default(self, stack):
        from fast_tffm_tpu.serve.server import ServeServer

        cfg, scorer, batcher = stack
        server = ServeServer(
            0, batcher, cfg, lambda: {"record": "status"}
        )
        try:
            assert isinstance(server._httpd, PooledHTTPServer)
            assert server._httpd.pool_size == cfg.serve_http_threads
            body = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{server.port}/score",
                data=b"0 1:0.5 2:0.25\n", method="POST",
            ), timeout=30).read()
            assert len(body.decode().splitlines()) == 1
        finally:
            server.close()
        assert _no_pool_threads() == []

    def test_zero_threads_mounts_legacy_server(self, stack):
        import dataclasses

        from fast_tffm_tpu.serve.server import ServeServer

        cfg, scorer, batcher = stack
        lcfg = dataclasses.replace(cfg, serve_http_threads=0)
        server = ServeServer(
            0, batcher, lcfg, lambda: {"record": "status"}
        )
        try:
            assert isinstance(server._httpd, ObsHTTPServer)
            assert not isinstance(server._httpd, PooledHTTPServer)
            body = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{server.port}/score",
                data=b"0 1:0.5 2:0.25\n", method="POST",
            ), timeout=30).read()
            assert len(body.decode().splitlines()) == 1
        finally:
            server.close()

    def test_pooled_and_legacy_score_byte_identical(self, stack):
        import dataclasses

        from fast_tffm_tpu.serve.server import ServeServer

        cfg, scorer, batcher = stack
        body = b"0 1:0.5 2:0.25\n1 3:1.0\n0 5:0.125 7:0.75 9:1\n"
        lcfg = dataclasses.replace(
            cfg, serve_http_threads=0, serve_parse_mode="legacy"
        )
        outs = []
        for c in (cfg, lcfg):
            server = ServeServer(
                0, batcher, c, lambda: {"record": "status"}
            )
            try:
                outs.append(urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{server.port}/score",
                        data=body, method="POST",
                    ), timeout=30).read())
            finally:
                server.close()
        assert outs[0] == outs[1]

    def test_concurrent_rid_mint_unique(self, stack):
        """Sampled requests minted from concurrent pool workers carry
        UNIQUE X-Request-Id values — the itertools.count mint holds
        under the pooled front end's concurrency."""
        import dataclasses

        from fast_tffm_tpu import obs
        from fast_tffm_tpu.serve.server import ServeServer

        cfg, scorer, batcher = stack
        tcfg = dataclasses.replace(
            cfg, serve_trace_sample=1.0,
            trace_file=cfg.model_file + ".trace.json",
        )
        tracer = obs.Tracer(enabled=True, process_name="pooled-test")
        server = ServeServer(
            0, batcher, tcfg, lambda: {"record": "status"},
            tracer=tracer,
        )
        try:
            rids: list = []
            lock = threading.Lock()
            errs: list = []

            def hit():
                try:
                    for _ in range(8):
                        resp = urllib.request.urlopen(
                            urllib.request.Request(
                                f"http://127.0.0.1:{server.port}"
                                f"/score",
                                data=b"0 1:0.5\n", method="POST",
                            ), timeout=30)
                        resp.read()
                        rid = resp.headers.get("X-Request-Id")
                        with lock:
                            rids.append(rid)
                except Exception as e:  # noqa: BLE001 - surface below
                    errs.append(e)

            ts = [threading.Thread(target=hit) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            assert len(rids) == 32
            assert all(r for r in rids)
            assert len(set(rids)) == 32
        finally:
            server.close()
            tracer.close()

    def test_router_smoke_through_pooled_front_ends(self, stack):
        """Router -> replica with BOTH mounts pooled (the new
        default): scores round-trip and match the direct server."""
        from fast_tffm_tpu.serve.router import Replica, ServeRouter
        from fast_tffm_tpu.serve.server import ServeServer

        cfg, scorer, batcher = stack
        server = ServeServer(
            0, batcher, cfg, lambda: {"record": "status"}
        )
        router = None
        try:
            router = ServeRouter(
                0, [Replica(0, "127.0.0.1", server.port)], cfg,
                health_secs=10.0,
            )
            assert isinstance(router._httpd, PooledHTTPServer)
            body = b"0 1:0.5 2:0.25\n1 3:1.0\n"
            direct = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{server.port}/score",
                data=body, method="POST",
            ), timeout=30).read()
            routed = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{router.port}/score",
                data=body, method="POST",
            ), timeout=30).read()
            assert routed == direct
        finally:
            if router is not None:
                router.close()
            server.close()
        assert _no_pool_threads() == []

    def test_scratch_pool_drains_after_traffic(self, stack):
        """Every request's parse-scratch lease is released once its
        batch dispatches — steady traffic leaves zero leased buffers
        behind (the on_done lifecycle end to end over HTTP)."""
        from fast_tffm_tpu.serve.server import ServeServer

        cfg, scorer, batcher = stack
        server = ServeServer(
            0, batcher, cfg, lambda: {"record": "status"}
        )
        try:
            for i in range(12):
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{server.port}/score",
                    data=f"0 {i}:0.5 {i + 1}:0.25\n".encode(),
                    method="POST",
                ), timeout=30).read()
            deadline = time.time() + 10
            while time.time() < deadline and server.parse_pool.leased:
                time.sleep(0.05)
            assert server.parse_pool.leased == 0
        finally:
            server.close()

