"""Per-step communication cost model, measured from the traced jaxpr.

The reference's PS design moved only the rows a batch touched
(IndexedSlices push, SURVEY.md §3.2), so its per-step network traffic
scaled with the batch, not the vocabulary.  These tests pin the same
property onto the rebuild: the shardmap step's collective bytes are
extracted by walking the actual jaxpr (not a hand-maintained formula),
so any regression that reintroduces a vocab-proportional exchange in
entries mode fails here on CPU — no hardware needed.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import sparse_apply
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train import shardmap_step, sparse as sparse_lib

_COLLECTIVES = ("psum", "all_gather", "reduce_scatter", "all_to_all",
                "ppermute")


def _walk_jaxprs(jaxpr):
    yield jaxpr
    for eqn in jaxpr.eqns:
        for val in eqn.params.values():
            vals = val if isinstance(val, (list, tuple)) else (val,)
            for v in vals:
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    yield from _walk_jaxprs(inner)
                elif hasattr(v, "eqns"):
                    yield from _walk_jaxprs(v)


def collective_bytes(fn, *args) -> dict:
    """Total operand bytes per collective primitive in fn's jaxpr."""
    closed = jax.make_jaxpr(fn)(*args)
    out: dict = {}
    for j in _walk_jaxprs(closed.jaxpr):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if any(name.startswith(c) for c in _COLLECTIVES):
                nbytes = sum(
                    int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
                    for v in eqn.invars
                    if hasattr(v.aval, "shape")
                )
                out[name] = out.get(name, 0) + nbytes
    return out


def _step_bytes(vocab: int, exchange: str, mesh) -> int:
    cfg = FmConfig(
        vocabulary_size=vocab, factor_num=8, max_features=8, batch_size=64,
        optimizer="adagrad", learning_rate=0.05, lookup="shardmap",
        sparse_exchange=exchange,
    )
    rng = np.random.default_rng(0)
    batch = Batch(
        labels=rng.integers(0, 2, 64).astype(np.float32),
        ids=rng.integers(0, vocab, (64, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, (64, 8)).astype(np.float32),
        fields=np.zeros((64, 8), np.int32),
        weights=np.ones((64,), np.float32),
    )
    batch = jax.tree.map(jnp.asarray, batch)
    params = fm.init_params(jax.random.PRNGKey(0), cfg)
    opt = sparse_lib.init_sparse_opt_state(cfg, params)
    per_prim = collective_bytes(
        lambda p, o, b: shardmap_step.sparse_step_shardmap(
            cfg, p, o, b, mesh
        ),
        params, opt, batch,
    )
    return sum(per_prim.values())


def _mesh(shape):
    devs = np.array(jax.devices()[:shape[0] * shape[1]]).reshape(shape)
    return Mesh(devs, (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))


def test_entries_comms_independent_of_vocab():
    """Entries mode: growing the vocabulary 16x must not change per-step
    collective bytes (batch-proportional).  Dense mode: grows ~16x."""
    mesh = _mesh((2, 4))
    v_small, v_big = 1 << 14, 1 << 18
    e_small = _step_bytes(v_small, "entries", mesh)
    e_big = _step_bytes(v_big, "entries", mesh)
    d_small = _step_bytes(v_small, "dense", mesh)
    d_big = _step_bytes(v_big, "dense", mesh)
    assert e_small == e_big, (e_small, e_big)
    # Dense delta dominates: bytes scale with vocab.
    assert d_big > 8 * d_small, (d_small, d_big)
    # At the large vocab the entries exchange is far cheaper.
    assert e_big * 4 < d_big, (e_big, d_big)


def test_auto_exchange_picks_by_bytes():
    """auto == dense at small vocab / large batch, entries at large
    vocab / small batch — whichever the ring-traffic model favors."""
    mesh = _mesh((2, 4))
    small = FmConfig(
        vocabulary_size=1 << 10, factor_num=8, max_features=8,
        batch_size=64, lookup="shardmap",
    )
    big = FmConfig(
        vocabulary_size=1 << 22, factor_num=8, max_features=8,
        batch_size=64, lookup="shardmap",
    )
    n_occ = 64 // 2 * 8  # per-device occurrences on the (2, 4) mesh
    assert shardmap_step.exchange_mode(small, mesh, n_occ) == "dense"
    assert shardmap_step.exchange_mode(big, mesh, n_occ) == "entries"
    forced = FmConfig(**{**small.__dict__, "sparse_exchange": "entries",
                         "train_files": [], "weight_files": [],
                         "validation_files": [], "predict_files": []})
    assert shardmap_step.exchange_mode(forced, mesh, n_occ) == "entries"


def test_auto_exchange_allreduce_weighting():
    """Pin the corrected crossover (ADVICE r5): a ring all-reduce moves
    ~2x its buffer per device, so the dense side weighs double.  Shapes
    in the band between V*2D and 2*V*2D (where the old, unweighted
    comparison picked 'dense') must now resolve to 'entries'.

    S=2, vocab_local=1024, d=9, 512-entry cap:
      entries ring words (per (S-1)): S*cap*(2d+1)  = 2*512*19 = 19456
      old dense words:                V*2d          = 1024*18  = 18432
      corrected dense words:          2*V*2d        = 36864
    """
    assert sparse_apply.resolve_exchange(
        "auto", n_local_occ=512, vocab_local=1024, d=9, data_shards=2,
    ) == "entries"
    # Just past the corrected crossover (entries words > 2*V*2D) the pick
    # flips back to dense: same cap against a quarter of the vocab.
    assert sparse_apply.resolve_exchange(
        "auto", n_local_occ=512, vocab_local=256, d=9, data_shards=2,
    ) == "dense"


def test_entries_cap_is_batch_bounded():
    """The static exchange capacity scales with occurrences, not vocab."""
    c1 = sparse_apply.entries_cap(1000, 1 << 20)
    c2 = sparse_apply.entries_cap(1000, 1 << 28)
    assert c1 == c2  # vocab-independent once vocab > batch
    assert c1 <= -(-1000 // sparse_apply.CHUNK) * sparse_apply.CHUNK
    # Tiny vocab range bounds it the other way.
    assert sparse_apply.entries_cap(10_000, 512) <= max(
        512, sparse_apply.CHUNK
    )


def test_compact_k2_grid_scales_with_entries_not_vocab():
    """Compact K2's grid (== streamed table blocks) is bounded by the
    entry count: the streaming analogue of the comms property.  Verified
    from the traced pallas_call grid, not a formula."""

    def grid_of(vocab, n_ids):
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, vocab, n_ids), np.int32
        )
        g = jnp.ones((n_ids, 9), jnp.float32)
        table = jnp.zeros((vocab, 9), jnp.float32)
        closed = jax.make_jaxpr(
            lambda t, i, gg: sparse_apply.sgd_apply(
                t, i, gg, lr=0.1, compact=True
            )
        )(table, ids, g)
        grids = []
        for j in _walk_jaxprs(closed.jaxpr):
            for eqn in j.eqns:
                if eqn.primitive.name == "pallas_call":
                    gm = eqn.params.get("grid_mapping")
                    if gm is not None and len(gm.grid) == 1:
                        grids.append(gm.grid[0])
        # K1 + K2 both present; K2 is the table-streaming one (max grid
        # in the full-stream case, but under compact it is the one whose
        # grid is NOT the K1 chunk grid).
        return grids

    # 200 ids -> n_pad 512 entries; V=2^21 has 1024 groups of 8x256 rows,
    # so compact must engage (t_max = 512 < 1024) and the K2 grid — the
    # number of table blocks streamed — is the ENTRY bound, not the
    # vocab bound.
    vocab = 1 << 21
    grids = grid_of(vocab, 200)
    group = sparse_apply._group_for(vocab // sparse_apply.TILE)
    n_groups = vocab // (sparse_apply.TILE * group)
    assert n_groups not in grids, (grids, n_groups)  # vocab bound gone
    assert 512 in grids, grids  # the entry-bounded K2 grid
    # Growing the vocab 4x leaves the K2 grid unchanged (entry-bounded).
    grids4 = grid_of(vocab * 4, 200)
    assert 512 in grids4, grids4
    n_groups4 = (vocab * 4) // (sparse_apply.TILE * sparse_apply._group_for(
        (vocab * 4) // sparse_apply.TILE))
    assert n_groups4 not in grids4, (grids4, n_groups4)


def test_auto_exchange_pure_model_parallel():
    """With one data shard nothing is exchanged either way; auto must
    pick entries (its fast path is the plain K1+K2 apply, strictly less
    work than a dense delta materialization)."""
    assert sparse_apply.resolve_exchange(
        "auto", n_local_occ=10_000, vocab_local=1 << 12, d=9,
        data_shards=1,
    ) == "entries"
    # Same shapes with real data sharding still favor dense.
    assert sparse_apply.resolve_exchange(
        "auto", n_local_occ=10_000, vocab_local=1 << 12, d=9,
        data_shards=4,
    ) == "dense"
