"""Mid-epoch resume + periodic validation (SURVEY.md §5: checkpoint row
"resumable mid-epoch via data-iterator state"; metrics row "periodic
step/loss/validation-loss prints")."""

import json

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.data.pipeline import BatchPipeline
from fast_tffm_tpu.train import checkpoint
from fast_tffm_tpu.train.loop import Trainer


def _write_data(path, rng, lines=256, vocab=64):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(
                f"{i % 2} {rng.integers(0, vocab)}:1 "
                f"{rng.integers(0, vocab)}:0.5\n"
            )


def _cfg(tmp_path, **kw):
    defaults = dict(
        vocabulary_size=64, factor_num=4, max_features=4, batch_size=32,
        train_files=[str(tmp_path / "train.libsvm")],
        model_file=str(tmp_path / "model"),
        epoch_num=1, log_steps=0, thread_num=1, seed=3,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _batch_key(b: Batch):
    return (b.labels.tobytes(), b.ids.tobytes(), b.vals.tobytes())


def test_pipeline_skip_batches_continues_stream(tmp_path, rng):
    """skip=k with the same seed must yield exactly the full stream minus
    its first k batches (single parser thread for determinism)."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path)
    full = [
        _batch_key(b)
        for b in BatchPipeline(cfg.train_files, cfg, epochs=1, shuffle=True)
    ]
    assert len(full) == 8
    skipped = [
        _batch_key(b)
        for b in BatchPipeline(
            cfg.train_files, cfg, epochs=1, shuffle=True, skip_batches=3
        )
    ]
    assert skipped == full[3:]


def test_trainer_resumes_mid_epoch(tmp_path, rng):
    """A checkpoint carrying a pipeline position makes train() continue
    from that batch instead of replaying the epoch."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path)
    r1 = Trainer(cfg).train()
    assert r1["train"]["steps"] == 8

    # Simulate an interruption at batch 5 of epoch 0: rewrite the saved
    # data position (params/opt stay as saved).
    ds = checkpoint.restore_data_state(cfg.model_file)
    assert ds["epoch"] == 1 and ds["batches_done"] == 0  # completed run
    assert ds["fingerprint"]["seed"] == cfg.seed
    with open(f"{cfg.model_file}/data_state.json", "w") as f:
        json.dump({"epoch": 0, "batches_done": 5}, f)

    t2 = Trainer(cfg)
    assert t2._restored_step == 8  # warm start from the checkpoint
    r2 = t2.train()
    assert r2["train"]["steps"] == 3  # only the remaining 3 batches
    ds2 = checkpoint.restore_data_state(cfg.model_file)
    assert ds2["epoch"] == 1 and ds2["batches_done"] == 0


def test_stale_data_state_ignored_without_params(tmp_path, rng):
    """Clearing the params to retrain from scratch must not let a
    surviving data_state.json truncate the fresh run's stream."""
    import shutil

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path)
    Trainer(cfg).train()
    with open(f"{cfg.model_file}/data_state.json", "w") as f:
        json.dump({"epoch": 0, "batches_done": 5}, f)
    shutil.rmtree(f"{cfg.model_file}/params")
    shutil.rmtree(f"{cfg.model_file}/opt")
    r = Trainer(cfg).train()
    assert r["train"]["steps"] == 8  # full epoch, nothing skipped


def test_completed_checkpoint_warm_starts_full_epochs(tmp_path, rng):
    """Warm-starting from a COMPLETED run trains epoch_num fresh epochs
    (the Adagrad-vs-FTRL sweep relies on this)."""
    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path)
    Trainer(cfg).train()
    r2 = Trainer(cfg).train()
    assert r2["train"]["steps"] == 8


def test_resume_position_ignored_on_config_change(tmp_path, rng, caplog):
    """A saved data position under a DIFFERENT input config (seed, batch
    size, file list) must be ignored with a warning — skipping N batches
    of a differently-defined stream lands on the wrong data."""
    import logging

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path)
    from conftest import set_data_state

    Trainer(cfg).train()
    set_data_state(cfg.model_file, epoch=0, batches_done=5)  # fp: seed=3

    cfg2 = _cfg(tmp_path, seed=99)  # stream redefined
    with caplog.at_level(logging.WARNING):
        r = Trainer(cfg2).train()
    assert r["train"]["steps"] == 8  # full epoch, position ignored
    assert any("different input config" in rec.message for rec in caplog.records)


def test_resume_exact_with_parallel_parsing(tmp_path, rng):
    """Mid-epoch resume with thread_num>1: training pipelines are ordered
    (sequence-numbered delivery), so batches_done identifies exactly the
    trained prefix — no boundary batch is doubled or skipped."""
    from conftest import set_data_state

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, thread_num=4)
    Trainer(cfg).train()
    set_data_state(cfg.model_file, epoch=0, batches_done=5)
    r2 = Trainer(cfg).train()
    assert r2["train"]["steps"] == 3


def test_truncation_warning_logged(tmp_path, rng, caplog):
    """Features dropped by max_features must surface in the log (the
    reference's parser warned; silent truncation hides data bugs)."""
    import logging

    path = tmp_path / "train.libsvm"
    with open(path, "w") as f:
        for i in range(64):
            toks = " ".join(f"{(i + j) % 64}:1.0" for j in range(6))
            f.write(f"{i % 2} {toks}\n")
    cfg = _cfg(tmp_path, max_features=4)  # 2 of 6 features dropped per line
    with caplog.at_level(logging.WARNING):
        Trainer(cfg).train()
    msgs = [r.message for r in caplog.records if "dropped by" in r.message]
    assert msgs and "max_features=4" in msgs[0]
    assert "128" in msgs[0]  # 64 lines x 2 dropped


def test_weighted_metrics_report_unweighted_examples(tmp_path, rng):
    """examples = unweighted real-example count; weight_sum separate."""
    _write_data(tmp_path / "train.libsvm", rng)
    wpath = tmp_path / "w.txt"
    with open(wpath, "w") as f:
        f.write("2.5\n" * 256)
    cfg = _cfg(tmp_path, weight_files=[str(wpath)])
    r = Trainer(cfg).train()
    assert r["train"]["examples"] == 256.0  # not 256 * 2.5
    assert abs(r["train"]["weight_sum"] - 256 * 2.5) < 1e-3


def test_periodic_validation(tmp_path, rng):
    _write_data(tmp_path / "train.libsvm", rng)
    _write_data(tmp_path / "valid.libsvm", rng, lines=64)
    cfg = _cfg(
        tmp_path,
        validation_files=[str(tmp_path / "valid.libsvm")],
        validation_steps=3,
        metrics_file=str(tmp_path / "metrics.jsonl"),
    )
    result = Trainer(cfg).train()
    recs = [json.loads(line)
            for line in open(tmp_path / "metrics.jsonl")]
    vrecs = [r for r in recs if "validation_loss" in r]
    # 8 steps, validation every 3 -> steps 3 and 6.
    assert [r["step"] for r in vrecs] == [3, 6]
    for r in vrecs:
        assert np.isfinite(r["validation_loss"])
        assert 0.0 <= r["validation_auc"] <= 1.0
    assert "validation" in result  # final validation still runs


def test_ftrl_warm_start_normalizes_broken_invariant(tmp_path, rng, caplog):
    """The compact-K2 FTRL apply relies on w == ftrl_solve(z, n) for
    untouched rows (ops.sparse_apply.ftrl_apply's contract).  A warm
    start whose table was edited outside train.sparse must fail LOUDLY
    and be normalized at restore, not drift sweep-dependently (ADVICE
    r5)."""
    import logging

    import jax.numpy as jnp

    _write_data(tmp_path / "train.libsvm", rng)
    cfg = _cfg(tmp_path, optimizer="ftrl", learning_rate=0.05)
    t = Trainer(cfg)
    t.train()
    clean_table = np.asarray(t.state.params.table)

    # Violate the invariant the way an external edit would: perturb w,
    # leave (z, n) alone, re-save.
    t.state = t.state._replace(
        params=t.state.params._replace(table=t.state.params.table + 0.5)
    )
    t.save(8)

    with caplog.at_level(logging.WARNING):
        t2 = Trainer(cfg)
    assert any("ftrl_solve" in r.message for r in caplog.records)
    # Normalization recovers w = ftrl_solve(z, n) — the pre-edit table.
    np.testing.assert_allclose(
        np.asarray(t2.state.params.table), clean_table, rtol=0, atol=1e-6
    )

    # An invariant-respecting checkpoint restores bit-identically, no
    # warning: train one more run and warm-start from it untouched.
    caplog.clear()
    t2.train()
    good = np.asarray(t2.state.params.table)
    with caplog.at_level(logging.WARNING):
        t3 = Trainer(cfg)
    assert not any("ftrl_solve" in r.message for r in caplog.records)
    np.testing.assert_array_equal(np.asarray(t3.state.params.table), good)
