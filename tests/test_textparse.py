"""Vectorized request parsing (ISSUE 16 tentpole): the batch parser is
BITWISE-IDENTICAL to the legacy per-line loop — arrays, dtypes,
truncation counts, AND error text (the fast path falls back to the
legacy parser on any out-of-grammar input, so the legacy behavior is
the contract by construction) — and the scratch pool recycles the
per-request arrays without ever handing out a dirty buffer.

jax-free: textparse.py imports numpy and the hash oracle only.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.serve import textparse
from fast_tffm_tpu.serve.textparse import ParseScratchPool, parse_request


def _cfg(**kw):
    base = dict(vocabulary_size=1000, factor_num=4, max_features=39)
    base.update(kw)
    return FmConfig(**base)


# Every accepted-grammar corner plus every rejection the legacy loop
# attributes to a line: labels (signed/float/inf/nan), label-less
# lines, bare ids, comments/blanks, truncation, ffm tokens, mixed
# shapes, signed ids, >18-digit ids (valid input that must take the
# fallback), hash-only alpha/unicode ids, malformed tokens.
BODIES = [
    "0 1:0.5 2:1.5\n1 3:2.0\n",
    "1:0.5 2:1.5\n3:2.0\n",
    "0 5 7 9\n",
    "# comment\n\n0 1:1.0\n   \n",
    "0 " + " ".join(f"{i}:{i}.25" for i in range(50)) + "\n",
    "0 1:0.5\nbogus::3\n",
    "0 1:0.5 2:x\n",
    "",
    "\n# only\n\n",
    "0\n1\n",
    "-1 1:0.5\n+0.5 2:1\n",
    "inf 3:nan\nnan 4:inf\n",
    "0 1:1e-3 2:1E3 3:.5 4:5. 5:+.5e+2\n",
    "0 -5:0.5 +7:1.5\n",
    "0 " + str(10 ** 25) + ":1.0\n",
    "0 1:0.5 2\n",
    "0 1:2:0.5 3:4:1.5\n",
    "0 1:2:0.5 3:1.5 4\n",
    "0 :5\n",
    "0 a:0.5 b:1.5\n",
    "0 ü:1.0 café:2.0\n",
    "0 1:0.5 2:1.5\n\n1 3:2.0 4:4.0 5:1\n",
    "0 1:0.5 2:1.5\n1 3:2.0 4:4.0\n",
]


def _run(fn, body, cfg):
    try:
        return ("ok",) + tuple(fn(body, cfg, None))
    except ValueError as e:
        return ("err", str(e))


def _assert_same(a, b, ctx):
    assert a[0] == b[0], ctx
    if a[0] == "err":
        # Error TEXT parity, not just the raise: the 400 body names
        # the line either way.
        assert a[1] == b[1], ctx
        return
    _, i1, v1, f1, n1, t1 = a
    _, i2, v2, f2, n2, t2 = b
    assert (n1, t1) == (n2, t2), ctx
    for x, y in ((i1, i2), (v1, v2), (f1, f2)):
        assert x.dtype == y.dtype and x.shape == y.shape, ctx
        # tobytes(): bitwise, and nan-safe where array_equal is not.
        assert x.tobytes() == y.tobytes(), ctx


class TestVecLegacyParity:
    @pytest.mark.parametrize("field_num", [0, 3])
    @pytest.mark.parametrize("hash_mode", [False, True])
    def test_edge_matrix_bitwise(self, field_num, hash_mode):
        cfg = _cfg(field_num=field_num, hash_feature_id=hash_mode)
        for body in BODIES:
            a = _run(textparse._parse_legacy, body, cfg)
            b = _run(
                lambda t, c, p: parse_request(t, c, p), body, cfg
            )
            _assert_same(a, b, (body[:60], field_num, hash_mode))

    @pytest.mark.parametrize("size", [1, 2, 4, 8, 16, 64])
    def test_production_shapes_bitwise(self, size):
        rng = random.Random(7)
        body = "".join(
            "0 " + " ".join(
                f"{rng.randrange(1000)}:{rng.random():.3f}"
                for _ in range(12)
            ) + "\n"
            for _ in range(size)
        )
        for fn in (0, 3):
            cfg = _cfg(field_num=fn)
            _assert_same(
                _run(textparse._parse_legacy, body, cfg),
                _run(lambda t, c, p: parse_request(t, c, p), body,
                     cfg),
                (size, fn),
            )
        ffm = "".join(
            "1 " + " ".join(
                f"{rng.randrange(3)}:{rng.randrange(1000)}"
                f":{rng.random():.3f}"
                for _ in range(12)
            ) + "\n"
            for _ in range(size)
        )
        cfg = _cfg(field_num=3)
        _assert_same(
            _run(textparse._parse_legacy, ffm, cfg),
            _run(lambda t, c, p: parse_request(t, c, p), ffm, cfg),
            ("ffm", size),
        )

    def test_ragged_lines_bitwise(self):
        body = "0 1:0.5\n1 2:0.25 3:0.75 4:1.0\n0 5:0.5 6:0.5\n"
        cfg = _cfg(field_num=3)
        _assert_same(
            _run(textparse._parse_legacy, body, cfg),
            _run(lambda t, c, p: parse_request(t, c, p), body, cfg),
            "ragged",
        )

    def test_malformed_line_number_in_error(self):
        cfg = _cfg()
        with pytest.raises(ValueError, match="line 4"):
            parse_request("# c\n0 1:0.5\n\n0 2:oops\n", cfg)
        # Identical text from the forced-legacy engine.
        lcfg = _cfg(serve_parse_mode="legacy")
        try:
            parse_request("# c\n0 1:0.5\n\n0 2:oops\n", cfg)
        except ValueError as e_vec:
            with pytest.raises(ValueError) as e_leg:
                parse_request("# c\n0 1:0.5\n\n0 2:oops\n", lcfg)
            assert str(e_vec) == str(e_leg.value)

    def test_truncation_counts_match(self):
        cfg = _cfg(max_features=4)
        wide = (
            "0 " + " ".join(f"{i}:0.5" for i in range(9)) + "\n"
            "1 2:1.0\n"
        )
        *_, n_v, t_v = parse_request(wide, cfg)
        *_, n_l, t_l = textparse._parse_legacy(wide, cfg, None)
        assert (n_v, t_v) == (n_l, t_l) == (2, 5)

    def test_serve_parse_mode_legacy_forces_oracle(self, monkeypatch):
        cfg = _cfg(serve_parse_mode="legacy")

        def boom(*a, **k):  # the vec engine must not run at all
            raise AssertionError("vec path ran under legacy mode")

        monkeypatch.setattr(textparse, "_parse_vec", boom)
        ids, vals, fields, n, t = parse_request("0 1:0.5\n", cfg)
        assert n == 1 and ids[0, 0] == 1

    def test_fallback_reaches_legacy_on_out_of_grammar(
        self, monkeypatch
    ):
        """A >18-digit id is VALID legacy input outside the vec
        grammar: the vec engine must decline and the legacy result
        come back unchanged."""
        cfg = _cfg()
        called = []
        orig = textparse._parse_legacy

        def spy(text, c, pool):
            called.append(text)
            return orig(text, c, pool)

        monkeypatch.setattr(textparse, "_parse_legacy", spy)
        big = 10 ** 25
        ids, *_ = parse_request(f"0 {big}:1.0\n", cfg)
        assert called, "vec path did not fall back"
        assert ids[0, 0] == big % cfg.vocabulary_size


class TestScratchPool:
    def test_reuse_and_zero_fill(self):
        pool = ParseScratchPool(39)
        cfg = _cfg()
        ids1, vals1, _, n, _ = parse_request(
            "0 1:0.5 2:1.5\n", cfg, pool
        )
        base1 = ids1.base
        assert base1 is not None and pool.leased == 1
        pool.release(ids1)
        assert pool.leased == 0
        ids2, vals2, _, n, _ = parse_request("0 3:9.5\n", cfg, pool)
        # Same backing buffer, re-zeroed: slot 1 held 2:1.5 before.
        assert ids2.base is base1
        assert ids2[0, 1] == 0 and vals2[0, 1] == 0.0
        pool.release(ids2)

    def test_double_release_is_noop(self):
        pool = ParseScratchPool(8)
        ids, _, _ = pool.acquire(2)
        pool.release(ids)
        pool.release(ids)  # must not corrupt the free list
        assert pool.leased == 0
        a1, _, _ = pool.acquire(2)
        a2, _, _ = pool.acquire(2)
        assert a1.base is not a2.base
        pool.release(a1)
        pool.release(a2)

    def test_untracked_release_is_noop(self):
        pool = ParseScratchPool(8)
        pool.release(np.zeros((2, 8), np.int32))
        assert pool.leased == 0

    def test_oversized_requests_bypass_pool(self):
        pool = ParseScratchPool(8, max_pooled_rows=4)
        ids, vals, fields = pool.acquire(16)
        assert pool.leased == 0  # untracked fresh arrays
        pool.release(ids)

    def test_error_path_releases_lease(self):
        pool = ParseScratchPool(8)
        cfg = _cfg()
        with pytest.raises(ValueError):
            parse_request("0 1:0.5\n0 2:bad\n", cfg, pool)
        assert pool.leased == 0

    def test_telemetry_counters(self):
        from fast_tffm_tpu import obs

        tel = obs.Telemetry()
        pool = ParseScratchPool(8, telemetry=tel)
        a, _, _ = pool.acquire(2)
        pool.release(a)
        b, _, _ = pool.acquire(2)
        pool.release(b)
        snap = tel.snapshot()
        assert snap["counters"].get("serve.parse_scratch_reuse") == 1
        assert snap["gauges"].get("serve.parse_scratch_bytes", 0) > 0

    def test_concurrent_acquire_release(self):
        import threading

        pool = ParseScratchPool(8)
        cfg = _cfg()
        errs: list = []

        def worker(seed):
            try:
                for i in range(50):
                    ids, *_ = parse_request(
                        f"0 {seed + i}:0.5\n", cfg, pool
                    )
                    assert ids[0, 0] == (seed + i) % 1000
                    pool.release(ids)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        ts = [
            threading.Thread(target=worker, args=(100 * i,))
            for i in range(4)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs and pool.leased == 0
