"""Cross-platform TPU *lowering* tests for every Pallas kernel path.

Interpret-mode tests check kernel semantics but structurally cannot catch
Mosaic lowering errors — "Unimplemented primitive in Pallas TPU lowering"
aborted the round-3 hardware bench (scatter-add at the old
sparse_apply K1 carry add) while every interpret test passed.  Mosaic's
jaxpr->MLIR pass runs at jax LOWERING time, so ``jax.export`` with
``platforms=['tpu']`` under ``platform.force_compiled()`` surfaces that
entire failure class on this CPU-only machine.

Every Pallas entry point must have a case here; a new kernel without one
is unprotected against exactly the bug class that zeroed BENCH_r03.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import pytest

from fast_tffm_tpu import platform as pf
from fast_tffm_tpu.ops import fm_pallas, sparse_apply


def _export_skip_reason() -> str:
    """Version-aware probe of the jax.export / Mosaic toolchain.

    These tests need ``jax.export`` AND a Mosaic pass that can lower a
    trivial kernel for the tpu platform from a CPU-only host.  Both
    drift with the container's jax build (this jax 0.4.37 build ships
    no ``jax.export`` at all) — a DOCUMENTED pre-existing failure
    (ROADMAP.md "Pre-existing failures"), not a regression this suite
    should keep re-reporting as red.  Probe once at collection and
    skip LOUDLY: the skip reason names the exact drift so a toolchain
    bump that restores export support turns the suite back on by
    itself (and a skip that persists on a fixed toolchain is a bug in
    this probe).
    """
    if not hasattr(jax, "export"):
        return (
            f"jax {jax.__version__} in this container has no jax.export "
            "— the TPU-lowering gate cannot run (documented "
            "pre-existing failure; re-enable on a toolchain with "
            "jax.export + Mosaic)"
        )
    try:
        with pf.force_compiled():
            jax.export.export(
                jax.jit(lambda x: x + 1), platforms=["tpu"]
            )(jax.ShapeDtypeStruct((8,), jnp.float32))
    except Exception as e:  # pragma: no cover - toolchain-dependent
        return (
            f"jax.export for platform 'tpu' is broken in this container "
            f"(jax {jax.__version__}: {type(e).__name__}: {e}) — "
            "Mosaic container drift, documented pre-existing failure"
        )
    return ""


_SKIP_REASON = _export_skip_reason()
# Loud module-wide skip: every test here depends on the same probe, and
# a silent collection error would look identical to "suite green".
pytestmark = pytest.mark.skipif(
    bool(_SKIP_REASON), reason=_SKIP_REASON
)

V, D, N = 4096, 9, 2048
B, F, K = 1024, 39, 8


def lower_tpu(fn, *args):
    """Export ``fn`` for the tpu platform; raises on Mosaic lowering errors."""
    with pf.force_compiled():
        return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _s(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


class TestSparseApplyLowering:
    def test_adagrad_apply(self):
        lower_tpu(
            functools.partial(sparse_apply.adagrad_apply, lr=0.1, eps=1e-7),
            _s((V, D)), _s((V, D)), _s((N,), jnp.int32), _s((N, D)),
        )

    def test_sgd_apply(self):
        lower_tpu(
            functools.partial(sparse_apply.sgd_apply, lr=0.1),
            _s((V, D)), _s((N,), jnp.int32), _s((N, D)),
        )

    def test_ftrl_apply(self):
        lower_tpu(
            functools.partial(
                sparse_apply.ftrl_apply, lr=0.1, l1=0.01, l2=0.01, beta=1.0
            ),
            _s((V, D)), _s((V, D)), _s((V, D)), _s((N,), jnp.int32),
            _s((N, D)),
        )

    def test_adagrad_apply_compact(self):
        """Compact K2 (scalar-prefetch-driven index maps, touched-group
        grid) lowers for TPU.  Shapes chosen so the compact branch
        actually engages (entries << table groups)."""
        v_big, n_small = 1 << 21, 512
        lower_tpu(
            functools.partial(
                sparse_apply.adagrad_apply, lr=0.1, eps=1e-7, compact=True
            ),
            _s((v_big, D)), _s((v_big, D)), _s((n_small,), jnp.int32),
            _s((n_small, D)),
        )

    def test_unique_entries_merge_apply(self):
        """The full entries-exchange chain (unique_entries ->
        merge_entries -> k2_apply) lowers for TPU."""
        cap = sparse_apply.entries_cap(N, V)

        def chain(table, acc, ids, g):
            rows, pay, _ = sparse_apply.unique_entries(
                ids, g, vocab=V, cap=cap
            )
            # Simulate a 2-shard gather: the merged stream length is
            # what matters for lowering.
            u, ts = sparse_apply.merge_entries(
                jnp.concatenate([rows, rows]),
                jnp.concatenate([pay, pay], axis=0), vocab=V,
            )
            upd = functools.partial(
                sparse_apply.adagrad_update, lr=0.1, eps=1e-7
            )
            return sparse_apply.k2_apply(upd, ts, u, (table, acc))

        lower_tpu(
            chain, _s((V, D)), _s((V, D)), _s((N,), jnp.int32), _s((N, D)),
        )

    def test_dense_delta(self):
        lower_tpu(
            functools.partial(
                sparse_apply.dense_delta, vocab=V, vocab_local=V, row_lo=0
            ),
            _s((N,), jnp.int32), _s((N, D)),
        )

    @pytest.mark.parametrize("chunk,tile", [(256, 512), (1024, 512),
                                            (2048, 256)])
    def test_adagrad_apply_alternate_blocks(self, chunk, tile):
        """The tunable CHUNK/TILE values the hardware sweep tries must
        all pass Mosaic lowering, or the sweep would crash the chip run."""
        orig = sparse_apply.CHUNK, sparse_apply.TILE
        sparse_apply.CHUNK, sparse_apply.TILE = chunk, tile
        try:
            lower_tpu(
                functools.partial(
                    sparse_apply.adagrad_apply, lr=0.1, eps=1e-7
                ),
                _s((V, D)), _s((V, D)), _s((N,), jnp.int32), _s((N, D)),
            )
        finally:
            sparse_apply.CHUNK, sparse_apply.TILE = orig

    @pytest.mark.parametrize(
        "chunk,tile,k1_group,group",
        [
            (512, 256, 1, 1),
            (512, 256, 4, 16),
            # Small blocks so the big groups actually materialize:
            # N/CHUNK = 16 chunks and V/TILE = 32 tiles — _group_for
            # would silently clamp them at the default block sizes and
            # lower the same kernel as the case above.
            (128, 128, 16, 32),
        ],
    )
    def test_adagrad_apply_alternate_groups(self, chunk, tile, k1_group,
                                            group):
        """Every K1_GROUP/GROUP value the hardware sweep tries must pass
        Mosaic lowering — the unrolled window loops and their semaphore
        protocols change shape with the group counts."""
        orig = (sparse_apply.CHUNK, sparse_apply.TILE,
                sparse_apply.K1_GROUP, sparse_apply.GROUP)
        sparse_apply.CHUNK = chunk
        sparse_apply.TILE = tile
        sparse_apply.K1_GROUP = k1_group
        sparse_apply.GROUP = group
        try:
            assert sparse_apply._group_for(N // chunk, k1_group) == k1_group
            assert sparse_apply._group_for(V // tile) == group
            lower_tpu(
                functools.partial(
                    sparse_apply.adagrad_apply, lr=0.1, eps=1e-7
                ),
                _s((V, D)), _s((V, D)), _s((N,), jnp.int32), _s((N, D)),
            )
        finally:
            (sparse_apply.CHUNK, sparse_apply.TILE,
             sparse_apply.K1_GROUP, sparse_apply.GROUP) = orig

    def test_adagrad_apply_with_host_meta(self):
        """The host-sort fast path reshapes the kernel inputs (prefetched
        metadata instead of in-graph sort); it must lower for TPU too."""
        n_pad = -(-N // sparse_apply.CHUNK) * sparse_apply.CHUNK
        n_chunks = n_pad // sparse_apply.CHUNK
        n_tiles = V // sparse_apply.TILE
        from fast_tffm_tpu.data.libsvm import SortMeta

        meta = SortMeta(
            perm=_s((n_pad,), jnp.int32),
            upos=_s((n_pad,), jnp.int32),
            lrow_last=_s((n_pad,), jnp.float32),
            starts=_s((n_chunks,), jnp.int32),
            firsts=_s((n_chunks + 1,), jnp.int32),
            ends=_s((n_chunks,), jnp.int32),
            tile_start=_s((n_tiles + 1,), jnp.int32),
        )
        lower_tpu(
            lambda t, a, i, g, m: sparse_apply.adagrad_apply(
                t, a, i, g, lr=0.1, eps=1e-7, meta=m
            ),
            _s((V, D)), _s((V, D)), _s((N,), jnp.int32), _s((N, D)), meta,
        )


class TestFmKernelLowering:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_forward(self, dtype):
        lower_tpu(
            functools.partial(fm_pallas.fm_scores_pallas, interpret=False),
            _s((B, F, 1 + K), dtype), _s((B, F), dtype),
        )

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_backward(self, dtype):
        lower_tpu(
            functools.partial(fm_pallas.fm_grad_pallas, interpret=False),
            _s((B, F, 1 + K), dtype), _s((B, F), dtype), _s((B, K)),
            _s((B,)),
        )


class TestGraftEntryLowering:
    def test_entry_lowers_with_compiled_pallas(self):
        """The driver's single-chip compile gate runs entry() — which
        uses the Pallas forward — so entry must Mosaic-lower for TPU."""
        import __graft_entry__ as ge

        fn, args = ge.entry()
        lower_tpu(fn, *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args])


class TestFullStepLowering:
    """The exact step functions the trainer jits, lowered for TPU."""

    def test_single_device_tile_step_bf16(self):
        """The bf16-compute variant of the full tile step lowers too."""
        self.test_single_device_tile_step("adagrad", "bfloat16")

    @pytest.mark.parametrize("optimizer", ["adagrad", "ftrl", "sgd"])
    def test_single_device_tile_step(self, optimizer, compute_dtype="float32"):
        from fast_tffm_tpu.config import FmConfig
        from fast_tffm_tpu.data.libsvm import Batch
        from fast_tffm_tpu.models import fm
        from fast_tffm_tpu.train import sparse

        cfg = FmConfig(
            vocabulary_size=V, factor_num=K, max_features=F,
            batch_size=B, optimizer=optimizer, sparse_apply="tile",
            use_pallas=True, compute_dtype=compute_dtype,
        )
        params = fm.FmParams(w0=_s(()), table=_s((V, 1 + K)))
        opt = sparse.init_sparse_opt_state(
            cfg, fm.FmParams(w0=jnp.zeros(()), table=jnp.zeros((V, 1 + K)))
        )
        opt = jax.tree.map(lambda a: _s(a.shape, a.dtype), opt)
        batch = Batch(
            labels=_s((B,)), ids=_s((B, F), jnp.int32), vals=_s((B, F)),
            fields=_s((B, F), jnp.int32), weights=_s((B,)),
        )

        def step(params, opt, batch):
            p, o, scores = sparse.sparse_step(cfg, params, opt, batch)
            return p, o, scores

        lower_tpu(step, params, opt, batch)

    def test_shardmap_step_ffm(self):
        """FFM variant of the hand-sharded step lowers for TPU too."""
        self.test_shardmap_step("adagrad", field_num=4)

    def test_shardmap_step_entries_exchange(self):
        """The batch-proportional entries exchange (all-gather + merge +
        K2-from-stream) lowers for TPU."""
        self.test_shardmap_step("adagrad", sparse_exchange="entries")

    @pytest.mark.parametrize("optimizer", ["adagrad", "ftrl"])
    def test_shardmap_step(self, optimizer, field_num=0,
                           sparse_exchange="auto"):
        """The hand-sharded multi-device step over the virtual 8-dev mesh."""
        import numpy as np
        from jax.sharding import Mesh

        from fast_tffm_tpu.config import FmConfig
        from fast_tffm_tpu.data.libsvm import Batch
        from fast_tffm_tpu.models import fm
        from fast_tffm_tpu.parallel import mesh as mesh_lib
        from fast_tffm_tpu.train import shardmap_step, sparse

        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(4, 2),
            (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS),
        )
        cfg = FmConfig(
            vocabulary_size=V, factor_num=K, max_features=F,
            batch_size=B, optimizer=optimizer, sparse_apply="tile",
            use_pallas=True, field_num=field_num,
            sparse_exchange=sparse_exchange,
        )
        d = cfg.embedding_dim
        assert shardmap_step.supports_shardmap(cfg, mesh)
        params = fm.FmParams(w0=_s(()), table=_s((V, d)))
        opt = sparse.init_sparse_opt_state(
            cfg, fm.FmParams(w0=jnp.zeros(()), table=jnp.zeros((V, d)))
        )
        opt = jax.tree.map(lambda a: _s(a.shape, a.dtype), opt)
        batch = Batch(
            labels=_s((B,)), ids=_s((B, F), jnp.int32), vals=_s((B, F)),
            fields=_s((B, F), jnp.int32), weights=_s((B,)),
        )

        def step(params, opt, batch):
            return shardmap_step.sparse_step_shardmap(
                cfg, params, opt, batch, mesh
            )

        lower_tpu(step, params, opt, batch)


def test_transposed_k2_probe_lowers():
    """The micro_probe's transposed-K2 prototype must pass Mosaic
    lowering so it cannot waste hardware-window time (its column-block
    specs (9, block) differ structurally from production's (block, 9))."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import micro_probe

    lower_tpu(
        functools.partial(micro_probe.k2t_apply, lr=0.05, eps=1e-7),
        _s((D, V)), _s((D, V)), _s((N,), jnp.int32), _s((N, D)),
    )


def test_packed_k2_probe_lowers():
    """The packed [V/8, 128] super-row K2 prototype must pass Mosaic
    lowering (its lane-spread one-hot matmuls and packed block specs
    are structurally new)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import micro_probe

    lower_tpu(
        functools.partial(micro_probe.k2p_apply, lr=0.05, eps=1e-7),
        _s((V // 8, 128)), _s((V // 8, 128)), _s((N,), jnp.int32),
        _s((N, D)),
    )
