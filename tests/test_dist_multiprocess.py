"""Multi-process distributed smoke test on localhost (SURVEY.md §4).

The reference exercised multi-node by launching ps+worker processes on
loopback. The analogue here: two OS processes join a jax.distributed
cluster (CPU backend, 2 virtual devices each), build the global (data,
model) mesh, and run real training steps with the table row-sharded
ACROSS PROCESS BOUNDARIES. Asserts both processes agree on the result.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
# CPU cross-process collectives need the gloo transport; without it
# every multi-process computation fails with "Multiprocess
# computations aren't implemented on the CPU backend".
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert jax.device_count() == 4, jax.devices()
assert jax.process_count() == 2

import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.train.loop import Trainer

cfg = FmConfig(
    vocabulary_size=256, factor_num=4, max_features=8, batch_size=32,
    mesh_data=2, mesh_model=2, model_file="/tmp/fftpu_dist_" + sys.argv[2],
    log_steps=0,
)
trainer = Trainer(cfg)
rng = np.random.default_rng(0)  # same seed -> same global batch everywhere
for _ in range(3):
    batch = Batch(
        labels=rng.integers(0, 2, size=(32,)).astype(np.float32),
        ids=rng.integers(0, 256, size=(32, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, size=(32, 8)).astype(np.float32),
        fields=np.zeros((32, 8), np.int32),
        weights=np.ones((32,), np.float32),
    )
    trainer.state = trainer._train_step(trainer.state, trainer._put(batch))

# Print a fingerprint of the local table shards + global metrics.
table = trainer.state.params.table
local = np.concatenate(
    [np.asarray(s.data).ravel() for s in table.addressable_shards]
)
print("FINGERPRINT", float(np.abs(local).sum()), float(trainer.state.metrics.loss_sum))

# Second phase, same process pair (amortizes cluster startup): the
# shardmap step with the batch-proportional entries exchange — its
# all-gather of touched-entry streams crosses REAL process boundaries
# here, not just a virtual mesh.
cfg2 = FmConfig(
    vocabulary_size=2048, factor_num=8, max_features=8, batch_size=32,
    mesh_data=2, mesh_model=2, lookup="shardmap",
    sparse_exchange="entries",
    model_file="/tmp/fftpu_dist_e_" + sys.argv[2], log_steps=0,
)
trainer2 = Trainer(cfg2)
for _ in range(2):
    batch = Batch(
        labels=rng.integers(0, 2, size=(32,)).astype(np.float32),
        ids=rng.integers(0, 2048, size=(32, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, size=(32, 8)).astype(np.float32),
        fields=np.zeros((32, 8), np.int32),
        weights=np.ones((32,), np.float32),
    )
    trainer2.state = trainer2._train_step(trainer2.state, trainer2._put(batch))
table2 = trainer2.state.params.table
local2 = np.concatenate(
    [np.asarray(s.data).ravel() for s in table2.addressable_shards]
)
print("FINGERPRINT2", float(np.abs(local2).sum()),
      float(trainer2.state.metrics.loss_sum))
"""


@pytest.mark.slow
def test_two_process_distributed_training(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    env = dict(
        os.environ,
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(i)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(out)
    finally:
        for p in procs:  # reap stragglers if init hung or a worker failed
            if p.poll() is None:
                p.kill()
                p.communicate()
    fps = [l for o in outs for l in o.splitlines()
           if l.startswith("FINGERPRINT ")]
    assert len(fps) == 2
    # Same global metrics on both processes (replicated state agrees).
    m0 = float(fps[0].split()[2])
    m1 = float(fps[1].split()[2])
    np.testing.assert_allclose(m0, m1, rtol=1e-6)
    # Loss is finite and training actually ran.
    assert m0 > 0 and np.isfinite(m0)
    # Phase 2: shardmap + entries exchange across process boundaries.
    fps2 = [l for o in outs for l in o.splitlines()
            if l.startswith("FINGERPRINT2")]
    assert len(fps2) == 2
    e0 = float(fps2[0].split()[2])
    e1 = float(fps2[1].split()[2])
    np.testing.assert_allclose(e0, e1, rtol=1e-6)
    assert e0 > 0 and np.isfinite(e0)


_WORKER_FILES = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
# CPU cross-process collectives need the gloo transport; without it
# every multi-process computation fails with "Multiprocess
# computations aren't implemented on the CPU backend".
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
import jax.numpy as jnp
import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train.loop import Trainer

data_dir = sys.argv[3]
cfg = FmConfig(
    vocabulary_size=256, factor_num=4, max_features=8, batch_size=64,
    mesh_data=2, mesh_model=2,
    train_files=[data_dir + "/a.libsvm", data_dir + "/b.libsvm"],
    # ONE shared checkpoint path: Orbax multi-host save is collective
    # (process 0 writes metadata, each process writes its shards) —
    # per-process paths deadlock the save barrier.
    model_file=data_dir + "/model_mp",
    epoch_num=2, log_steps=0, thread_num=1, seed=5,
)
t = Trainer(cfg)
res = t.train()
fp = float(jax.jit(lambda x: jnp.sum(jnp.abs(x)))(t.state.params.table))
print("FINGERPRINT", fp, float(t.state.metrics.loss_sum),
      res["train"]["examples"], res["train"]["steps"])
"""


def _gen_dist_files(tmp_path, n_lines=256):
    rng = np.random.default_rng(11)
    for name in ("a", "b"):
        with open(tmp_path / f"{name}.libsvm", "w") as f:
            for _ in range(n_lines):
                toks = [str(rng.integers(0, 2))]
                toks += [f"{rng.integers(0, 256)}:{rng.uniform(0.1, 1):.4f}"
                         for _ in range(6)]
                f.write(" ".join(toks) + "\n")


@pytest.mark.slow
def test_host_sharded_input_matches_single_process(tmp_path):
    """Each process parses only its strided share of the input at LOCAL
    batch size; the global batch assembles via
    make_array_from_process_local_data.  The training result must equal a
    single-process run over the SAME global batches (the union of the
    hosts' shards)."""
    _gen_dist_files(tmp_path)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    env = dict(
        os.environ,
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    script = tmp_path / "worker_files.py"
    script.write_text(_WORKER_FILES)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(i), str(tmp_path)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    fps = [l for o in outs for l in o.splitlines()
           if l.startswith("FINGERPRINT")]
    assert len(fps) == 2
    fp0 = [float(x) for x in fps[0].split()[1:]]
    fp1 = [float(x) for x in fps[1].split()[1:]]
    np.testing.assert_allclose(fp0, fp1, rtol=1e-6)
    # Coverage: 512 lines x 2 epochs, every line trained exactly once per
    # epoch (16 local groups -> 8 complete rounds -> 8 global batches).
    assert fp0[2] == 1024.0
    assert fp0[3] == 16.0  # 8 steps x 2 epochs

    # Single-process equivalence: rebuild the SAME global batches by
    # concatenating the two shards' streams and train on a local 2x2 mesh
    # with identical seeds.
    import dataclasses

    import jax

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.libsvm import Batch
    from fast_tffm_tpu.data.pipeline import BatchPipeline
    from fast_tffm_tpu.train.loop import Trainer

    cfg = FmConfig(
        vocabulary_size=256, factor_num=4, max_features=8, batch_size=64,
        mesh_data=2, mesh_model=2,
        train_files=[str(tmp_path / "a.libsvm"), str(tmp_path / "b.libsvm")],
        model_file=str(tmp_path / "model_sp"),
        epoch_num=2, log_steps=0, thread_num=1, seed=5,
    )
    trainer = Trainer(cfg)
    pipe_cfg = dataclasses.replace(cfg, batch_size=32)
    for epoch in range(cfg.epoch_num):
        shards = [
            list(BatchPipeline(cfg.train_files, pipe_cfg, epochs=1,
                               shuffle=True, seed=cfg.seed + epoch,
                               shard=(i, 2)))
            for i in range(2)
        ]
        for b0, b1 in zip(shards[0], shards[1]):
            gb = Batch(*(np.concatenate([getattr(b0, k), getattr(b1, k)])
                         for k in ("labels", "ids", "vals", "fields",
                                   "weights")))
            trainer.state = trainer._train_step(
                trainer.state, trainer._put(gb)
            )
    import jax.numpy as jnp

    fp_sp = float(jax.jit(lambda x: jnp.sum(jnp.abs(x)))(
        trainer.state.params.table))
    np.testing.assert_allclose(fp0[0], fp_sp, rtol=1e-5)
    np.testing.assert_allclose(
        fp0[1], float(trainer.state.metrics.loss_sum), rtol=1e-5
    )
