"""Multi-process distributed smoke test on localhost (SURVEY.md §4).

The reference exercised multi-node by launching ps+worker processes on
loopback. The analogue here: two OS processes join a jax.distributed
cluster (CPU backend, 2 virtual devices each), build the global (data,
model) mesh, and run real training steps with the table row-sharded
ACROSS PROCESS BOUNDARIES. Asserts both processes agree on the result.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert jax.device_count() == 4, jax.devices()
assert jax.process_count() == 2

import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.train.loop import Trainer

cfg = FmConfig(
    vocabulary_size=256, factor_num=4, max_features=8, batch_size=32,
    mesh_data=2, mesh_model=2, model_file="/tmp/fftpu_dist_" + sys.argv[2],
    log_steps=0,
)
trainer = Trainer(cfg)
rng = np.random.default_rng(0)  # same seed -> same global batch everywhere
for _ in range(3):
    batch = Batch(
        labels=rng.integers(0, 2, size=(32,)).astype(np.float32),
        ids=rng.integers(0, 256, size=(32, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, size=(32, 8)).astype(np.float32),
        fields=np.zeros((32, 8), np.int32),
        weights=np.ones((32,), np.float32),
    )
    trainer.state = trainer._train_step(trainer.state, trainer._put(batch))

# Print a fingerprint of the local table shards + global metrics.
table = trainer.state.params.table
local = np.concatenate(
    [np.asarray(s.data).ravel() for s in table.addressable_shards]
)
print("FINGERPRINT", float(np.abs(local).sum()), float(trainer.state.metrics.loss_sum))
"""


@pytest.mark.slow
def test_two_process_distributed_training(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    env = dict(
        os.environ,
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(i)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(out)
    finally:
        for p in procs:  # reap stragglers if init hung or a worker failed
            if p.poll() is None:
                p.kill()
                p.communicate()
    fps = [l for o in outs for l in o.splitlines() if l.startswith("FINGERPRINT")]
    assert len(fps) == 2
    # Same global metrics on both processes (replicated state agrees).
    m0 = float(fps[0].split()[2])
    m1 = float(fps[1].split()[2])
    np.testing.assert_allclose(m0, m1, rtol=1e-6)
    # Loss is finite and training actually ran.
    assert m0 > 0 and np.isfinite(m0)
