#!/usr/bin/env python
"""Tier-1 marker audit: every test file must contribute to the tier-1
suite (``pytest -m 'not slow'``).

The tier-1 filter is the repo's correctness gate (ROADMAP.md).  Its
failure mode is silent: a test file whose every test carries (or
inherits) ``pytest.mark.slow`` simply stops being collected — nothing
fails, coverage just evaporates.  This tool audits the markers
STATICALLY (AST; no imports, no jax, runs in milliseconds) so bench.py
can run it as a preflight and CI can gate on it:

  python tools/check_tier1.py            # audit ./tests, exit 1 on drift
  python tools/check_tier1.py --list     # per-file tier-1/slow counts

(Also runs as rule T1001 of the tffm-lint suite — ``python -m
tools.lint``, the tools/verify.sh entry point; see LINTING.md.)

Checks:
  1. every ``tests/test_*.py`` defines at least one test;
  2. every test file keeps at least one tier-1 (non-slow) test — no
     file silently drops out of the gate;
  3. every marker used via ``pytest.mark.<name>`` is declared in
     pytest.ini (an undeclared marker is a typo that silently marks
     nothing — ``-m 'not slo'`` style drift).

Marker detection covers the repo's idioms: decorators
(``@pytest.mark.slow``, ``@pytest.mark.slow(...)``), module-level
``pytestmark = pytest.mark.slow`` / ``pytestmark = [...]``, and class
decorators inherited by test methods.  Dynamic marking
(``request.applymarker``) is invisible to AST — none is used here, and
the audit errs on the side of counting such tests as tier-1 (the gate
then sees a file it believes is covered, which collection itself would
catch as an error if the file went fully slow at runtime).
"""

from __future__ import annotations

import argparse
import ast
import configparser
import os
import sys


def _marks_in(node: ast.AST) -> set:
    """Names X used as ``pytest.mark.X`` anywhere inside ``node``."""
    out = set()
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Attribute)
            and sub.value.attr == "mark"
            and isinstance(sub.value.value, ast.Name)
            and sub.value.value.id == "pytest"
        ):
            out.add(sub.attr)
    return out


def _decorator_marks(node) -> set:
    marks = set()
    for dec in getattr(node, "decorator_list", []):
        marks |= _marks_in(dec)
    return marks


def audit_file(path: str) -> dict:
    """{tests, tier1, slow, marks_used} for one test file."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    module_marks = set()
    marks_used = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in stmt.targets
        ):
            module_marks |= _marks_in(stmt.value)
    marks_used |= module_marks

    tests = tier1 = slow_n = 0

    def visit_fn(fn, inherited: set):
        nonlocal tests, tier1, slow_n
        if not fn.name.startswith("test"):
            return
        marks = inherited | _decorator_marks(fn)
        marks_used.update(_decorator_marks(fn))
        tests += 1
        if "slow" in marks:
            slow_n += 1
        else:
            tier1 += 1

    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_fn(stmt, module_marks)
        elif isinstance(stmt, ast.ClassDef) and stmt.name.startswith(
            "Test"
        ):
            class_marks = module_marks | _decorator_marks(stmt)
            marks_used |= _decorator_marks(stmt)
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    visit_fn(sub, class_marks)
    return {
        "tests": tests, "tier1": tier1, "slow": slow_n,
        "marks_used": marks_used,
    }


def declared_markers(repo_root: str) -> set:
    """Marker names declared in pytest.ini (empty set if none found)."""
    ini = os.path.join(repo_root, "pytest.ini")
    if not os.path.exists(ini):
        return set()
    cp = configparser.ConfigParser()
    cp.read(ini)
    raw = cp.get("pytest", "markers", fallback="")
    out = set()
    for line in raw.splitlines():
        line = line.strip()
        if line:
            out.add(line.split(":", 1)[0].strip())
    return out


# Markers pytest defines itself — always legal without declaration.
_BUILTIN_MARKS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "timeout",
}

# Files ALLOWED to be fully slow — each entry is a deliberate decision,
# not drift, and needs a reason here.  New test files must contribute
# tier-1 tests or be added here with a justification.
_ALL_SLOW_ALLOWED = {
    # Spawns two jax.distributed OS processes over loopback; the tier-1
    # gate runs single-process CPU and cannot host a coordinator.
    "test_dist_multiprocess.py",
}


def audit(test_dir: str = "tests",
          repo_root: str = ".") -> dict:
    """Audit every tests/test_*.py; returns a summary dict:
    {ok, files, tests, tier1, slow, problems: [str, ...],
     per_file: {name: {...}}}."""
    problems = []
    per_file = {}
    declared = declared_markers(repo_root) | _BUILTIN_MARKS
    names = sorted(
        n for n in os.listdir(test_dir)
        if n.startswith("test_") and n.endswith(".py")
    )
    if not names:
        return {"ok": False, "files": 0, "tests": 0, "tier1": 0,
                "slow": 0, "problems": [f"no test files in {test_dir}"],
                "per_file": {}}
    totals = {"tests": 0, "tier1": 0, "slow": 0}
    for name in names:
        path = os.path.join(test_dir, name)
        try:
            info = audit_file(path)
        except SyntaxError as e:
            problems.append(f"{name}: does not parse ({e})")
            continue
        per_file[name] = info
        for key in totals:
            totals[key] += info[key]
        if info["tests"] == 0:
            problems.append(f"{name}: defines no tests")
        elif info["tier1"] == 0 and name not in _ALL_SLOW_ALLOWED:
            problems.append(
                f"{name}: every test is marked slow — the file has "
                "silently dropped out of the tier-1 gate (add tier-1 "
                "tests, or allowlist it in tools/check_tier1.py with a "
                "reason)"
            )
        undeclared = info["marks_used"] - declared
        if undeclared:
            problems.append(
                f"{name}: undeclared marker(s) {sorted(undeclared)} — "
                "add to pytest.ini or fix the typo"
            )
    return {
        "ok": not problems, "files": len(names), **totals,
        "problems": problems, "per_file": per_file,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="audit tier-1 (non-slow) test coverage per file"
    )
    ap.add_argument("--tests", default="tests",
                    help="test directory (default ./tests)")
    ap.add_argument("--root", default=".",
                    help="repo root holding pytest.ini (default .)")
    ap.add_argument("--list", action="store_true",
                    help="print per-file tier-1/slow counts")
    args = ap.parse_args(argv)
    result = audit(args.tests, args.root)
    if args.list:
        print(f"{'file':40} {'tests':>6} {'tier1':>6} {'slow':>5}")
        for name, info in sorted(result["per_file"].items()):
            print(f"{name:40} {info['tests']:>6} {info['tier1']:>6} "
                  f"{info['slow']:>5}")
    print(
        f"tier-1 audit: {result['files']} files, {result['tests']} "
        f"tests, {result['tier1']} tier-1, {result['slow']} slow"
    )
    for p in result["problems"]:
        print(f"  ! {p}")
    if not result["ok"]:
        return 1
    print("ok: every test file contributes to the tier-1 gate")
    return 0


if __name__ == "__main__":
    sys.exit(main())
