#!/usr/bin/env python
"""Metric-name drift lint: the code's instrument registry and the
OBSERVABILITY.md schema table must agree EXACTLY.

The failure mode this guards is silent on both sides: an instrument
registered in code but missing from the schema table is invisible to
anyone reading the docs (and to alert rules written from them); a
documented metric that no code registers is a rule or dashboard
watching a value that will never move.  Both get worse now that the
names are a LIVE surface — Prometheus series names on ``/metrics`` and
alert-rule signals resolve from exactly these strings.

Mechanics (static, stdlib-only, milliseconds — same discipline as
tools/check_tier1.py):

- AST-walk every ``fast_tffm_tpu/**/*.py`` for
  ``<anything>.counter("name") / .gauge(...) / .timer(...) /
  .depth_hist(...) / .sample(...)`` calls whose first argument is a
  non-empty string literal — the registry's create-or-return idiom
  makes every registration look like this;
- parse the ``## Metric schema`` table in OBSERVABILITY.md (first
  backticked cell of each row is the metric name);
- fail (exit 1) listing every name on one side only.

Run directly, or as rules OB001/OB002 of the tffm-lint suite
(``python -m tools.lint``, which tools/verify.sh runs — see
LINTING.md).
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys

_METHODS = {"counter", "gauge", "timer", "depth_hist", "sample"}
_SCHEMA_HEADER = "## Metric schema"
_ROW_NAME = re.compile(r"^\|\s*`([^`]+)`\s*\|")


def registered_names(pkg_dir: str) -> dict:
    """{name: [file:line, ...]} of every instrument registered in code."""
    out: dict = {}
    for root, _, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue  # other tooling flags unparsable sources
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value
                ):
                    continue
                name = node.args[0].value
                rel = os.path.relpath(path, os.path.dirname(pkg_dir))
                out.setdefault(name, []).append(f"{rel}:{node.lineno}")
    return out


def documented_names(md_path: str) -> set:
    """Metric names from the ``## Metric schema`` table (first
    backticked cell per row)."""
    out: set = set()
    in_section = False
    with open(md_path) as f:
        for line in f:
            stripped = line.strip()
            if stripped.startswith("## "):
                in_section = stripped.startswith(_SCHEMA_HEADER)
                continue
            if not in_section:
                continue
            m = _ROW_NAME.match(stripped)
            if m and m.group(1) not in ("metric",):  # skip header row
                out.add(m.group(1))
    return out


def audit(pkg_dir: str, md_path: str) -> dict:
    """{ok, registered, documented, undocumented: [...], stale: [...]}"""
    reg = registered_names(pkg_dir)
    doc = documented_names(md_path)
    undocumented = sorted(set(reg) - doc)
    stale = sorted(doc - set(reg))
    return {
        "ok": not undocumented and not stale and bool(doc),
        "registered": reg,
        "documented": doc,
        "undocumented": undocumented,
        "stale": stale,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="audit obs metric names against the "
                    "OBSERVABILITY.md schema table"
    )
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap.add_argument("--pkg", default=os.path.join(here, "fast_tffm_tpu"),
                    help="package directory to scan")
    ap.add_argument("--md", default=os.path.join(here, "OBSERVABILITY.md"),
                    help="markdown file holding the schema table")
    args = ap.parse_args(argv)
    result = audit(args.pkg, args.md)
    print(
        f"obs metric audit: {len(result['registered'])} registered, "
        f"{len(result['documented'])} documented"
    )
    if not result["documented"]:
        print(f"  ! no '{_SCHEMA_HEADER}' table found in {args.md}")
    for name in result["undocumented"]:
        sites = ", ".join(result["registered"][name][:3])
        print(f"  ! {name}: registered in code ({sites}) but missing "
              f"from the schema table — document it")
    for name in result["stale"]:
        print(f"  ! {name}: in the schema table but no code registers "
              f"it — remove the row or fix the name")
    if not result["ok"]:
        return 1
    print("ok: code registry and schema table agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
