#!/usr/bin/env python
"""Ingest-component microbench: scan vs parse vs pipeline, threads x rate.

Measures the three stages of the fast-ingest path separately so the
bottleneck is visible (SURVEY.md §7 hard-part 2: the parser must feed the
chips):

  scan      — _iter_raw_windows: chunked reads + ONE C++ memchr pass
  parse     — NativeParser.parse_raw over pre-scanned groups, 1 C++ thread
  pipeline  — BatchPipeline end-to-end drain (reader + N parse workers +
              shuffle), the rate training actually sees

Pipeline-stage records come from the pipeline's OWN telemetry snapshot
(obs.Telemetry) rather than bench-local stopwatches: delivered-example
counts exclude tail-batch padding, and each record carries the stage
attribution a training heartbeat would report (parse total/percentiles,
reader-block, worker delivery-block).

Prints a JSON line per measurement; run with no args on any machine.
Results are committed to INGEST.md with the host's core count — rates
scale with cores since parse workers are independent.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import tempfile
import time
import shutil

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH, NFEAT, VOCAB = 4096, 39, 1 << 20


def drain_with_telemetry(pipe, tel) -> dict:
    """Drain a BatchPipeline and report from ITS telemetry snapshot:
    the examples counter (real lines, padding excluded) gives the rate;
    the parse/reader_block/out_block timers attribute the drain's time
    the same way a training run's heartbeat would."""
    t0 = time.perf_counter()
    for _b in pipe:
        pass
    dt = max(time.perf_counter() - t0, 1e-9)
    snap = tel.snapshot()
    timers = snap.get("timers", {})

    def t(name, key):
        return timers.get(name, {}).get(key, 0.0)

    counters = snap.get("counters", {})
    out = {
        "lines_per_sec": round(counters["ingest.examples"] / dt),
        "batches": counters["ingest.batches"],
        "parse_total_s": t("ingest.parse", "total_s"),
        "parse_p50_ms": t("ingest.parse", "p50_ms"),
        "parse_p95_ms": t("ingest.parse", "p95_ms"),
        "reader_block_s": t("ingest.reader_block", "total_s"),
        "worker_out_block_s": t("ingest.out_block", "total_s"),
    }
    # SHM-ring split (parse_processes with ring_slots > 0): how many raw
    # windows went zero-copy vs pickled, and the descriptor bytes that
    # actually crossed the worker queue.
    ring = counters.get("ingest.ring_windows", 0)
    fallback = counters.get("ingest.ring_fallback_windows", 0)
    if ring or fallback:
        out["ring_zero_copy_frac"] = round(ring / (ring + fallback), 4)
        out["ring_window_mb"] = round(
            counters.get("ingest.ring_window_bytes", 0) / 1e6, 2
        )
        out["queue_msg_kb"] = round(
            counters.get("ingest.work_msg_bytes", 0) / 1e3, 2
        )
    # Prestacked-cache split: once-per-group stack cost at the source.
    ps = snap.get("timers", {}).get("ingest.prestack", {})
    if ps.get("count"):
        out["prestack_superbatches"] = ps["count"]
        out["stack_ms_per_superbatch"] = round(
            1e3 * ps["total_s"] / ps["count"], 3
        )
    return out


def _proc_worker(files, epochs, ready, go, out):
    """One ingest process: full BatchPipeline drain over its file shard.

    Same structure as multi-host input sharding (parallel.mesh strided
    file assignment): each process owns disjoint files, runs its own
    reader + parser threads, and shares nothing.  A warmup drain loads
    the native lib and the page cache; the barrier (ready/go events)
    keeps process startup out of the timed region.
    """
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import BatchPipeline

    try:
        cfg = FmConfig(
            vocabulary_size=VOCAB, factor_num=8, max_features=NFEAT,
            batch_size=BATCH, thread_num=1, queue_size=8,
        )
        n_warm = 0
        for _b in BatchPipeline(files, cfg, epochs=1, shuffle=False):
            n_warm += 1
            if n_warm >= 2:
                break
        ready.set()
        go.wait()
        t0 = time.perf_counter()
        n = 0
        for _b in BatchPipeline(files, cfg, epochs=epochs, shuffle=True):
            n += BATCH
        out.put((n, time.perf_counter() - t0))
    except BaseException as e:  # noqa: BLE001 - surface in the parent
        ready.set()  # never leave the parent stuck on the barrier
        out.put(("error", f"{type(e).__name__}: {e}"))


def bench_procs(files, n_procs: int, epochs: int = 2):
    """Aggregate lines/s of n_procs independent ingest processes.

    Returns (aggregate_rate, slowest_proc_seconds).  Aggregate is total
    lines over the slowest process's drain time — the rate a training
    fleet would actually see, since the step waits for every host.
    """
    ctx = mp.get_context("spawn")
    shards = [files[i::n_procs] for i in range(n_procs)]
    out = ctx.Queue()
    ready = [ctx.Event() for _ in range(n_procs)]
    go = ctx.Event()
    procs = [
        ctx.Process(target=_proc_worker, args=(s, epochs, r, go, out))
        for s, r in zip(shards, ready)
    ]
    for p in procs:
        p.start()
    for r, p in zip(ready, procs):
        # A worker that dies before the barrier must not hang the bench.
        while not r.wait(timeout=1.0):
            if not p.is_alive():
                go.set()
                raise RuntimeError(
                    f"ingest worker died before ready (exit {p.exitcode})"
                )
    go.set()
    results = []
    for p in procs:
        try:
            results.append(out.get(timeout=300))
        except Exception:
            raise RuntimeError("ingest worker produced no result") from None
    for p in procs:
        p.join()
    errors = [r for r in results if r[0] == "error"]
    if errors:
        raise RuntimeError(f"ingest workers failed: {errors}")
    total = sum(n for n, _ in results)
    slowest = max(dt for _, dt in results)
    return total / slowest, slowest


def main() -> int:
    from bench import _gen_libsvm_files
    from fast_tffm_tpu import obs
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data import native as native_lib
    from fast_tffm_tpu.data.pipeline import BatchPipeline, _iter_raw_groups

    tmpdir = tempfile.mkdtemp(prefix="ingest_bench_")
    try:
        rng = np.random.default_rng(7)
        files = _gen_libsvm_files(tmpdir, rng, 4, 8 * BATCH, NFEAT, VOCAB)
        total = 4 * 8 * BATCH
        size = sum(os.path.getsize(f) for f in files)
        print(json.dumps({
            "setup": {"lines": total, "mb": round(size / 1e6, 1),
                      "cpus": os.cpu_count(), "batch": BATCH,
                      "features": NFEAT},
        }))

        def emit(stage, rate, **kw):
            print(json.dumps({
                "stage": stage, "lines_per_sec": round(rate), **kw
            }))

        for _ in range(2):  # second pass = warm page cache
            t0 = time.perf_counter()
            n = 0
            for _, starts, _e in _iter_raw_groups(files, BATCH):
                n += len(starts)
            scan = n / (time.perf_counter() - t0)
        emit("scan", scan)

        groups = list(_iter_raw_groups(files, BATCH))
        for nt in (1, 2, 4):
            p = native_lib.NativeParser(VOCAB, NFEAT, False, 0, nt)
            p.parse_raw(*groups[0], BATCH)
            t0 = time.perf_counter()
            for g in groups:
                p.parse_raw(*g, BATCH)
            emit("parse", total / (time.perf_counter() - t0),
                 internal_threads=nt)

        # sort_meta: the host-side sparse-apply prep that rides the same
        # worker threads when host_sort engages (single-process tile).
        from fast_tffm_tpu.ops import sparse_apply

        ids = rng.integers(0, VOCAB, (BATCH * NFEAT,)).astype(np.int32)
        native_lib.sort_meta(
            ids, VOCAB, sparse_apply.CHUNK, sparse_apply.TILE
        )
        t0 = time.perf_counter()
        reps = 10
        for _ in range(reps):
            native_lib.sort_meta(
                ids, VOCAB, sparse_apply.CHUNK, sparse_apply.TILE
            )
        emit("sort_meta", reps * BATCH * NFEAT / (time.perf_counter() - t0),
             note="feature occurrences/sec, one core")

        for tn in (1, 2, 4, 8):
            for ordered in (False, True):
                cfg = FmConfig(
                    vocabulary_size=VOCAB, factor_num=8, max_features=NFEAT,
                    batch_size=BATCH, thread_num=tn, queue_size=8,
                )
                tel = obs.Telemetry()
                pipe = BatchPipeline(
                    files, cfg, epochs=2, shuffle=not ordered,
                    ordered=ordered, telemetry=tel,
                )
                stats = drain_with_telemetry(pipe, tel)
                emit("pipeline", stats.pop("lines_per_sec"),
                     thread_num=tn, ordered=ordered, **stats)

        # Process-parallel ingest: N fully independent reader+parser
        # processes over disjoint file shards (the multi-host input-
        # sharding structure).  On a multi-core host this demonstrates
        # the claimed aggregate scaling; on a 1-core host it documents
        # the hardware ceiling (processes time-slice one core).
        for np_ in (1, 2, 4):
            if np_ > len(files):
                continue
            rate, slowest = bench_procs(files, np_)
            emit("procs", rate, n_procs=np_,
                 per_proc=round(rate / np_),
                 slowest_s=round(slowest, 2),
                 cores=os.cpu_count())

        # In-pipeline process POOL (parse_processes, PR 2): unlike the
        # independent-shard "procs" stage above, this is ONE pipeline —
        # one reader, N spawned parse workers, parsed batches returning
        # over shared memory as a single trainable stream.  The rate the
        # trainer sees when the GIL (or the Python parse fallback) is
        # the bottleneck.  ring_slots toggles the INBOUND direction:
        # 0 pickles every raw window through the worker queue, >0 writes
        # windows into the SHM ring and ships descriptors only — the
        # threads-vs-procs drain comparison re-run on the ring.
        for np_ in (1, 2, 4):
            for slots in (0, 4):
                cfg = FmConfig(
                    vocabulary_size=VOCAB, factor_num=8,
                    max_features=NFEAT, batch_size=BATCH, queue_size=8,
                    parse_processes=np_, ring_slots=slots,
                )
                tel = obs.Telemetry()
                pipe = BatchPipeline(
                    files, cfg, epochs=1, shuffle=True, telemetry=tel
                )
                stats = drain_with_telemetry(pipe, tel)
                emit("pipeline-procpool", stats.pop("lines_per_sec"),
                     parse_processes=np_, ring_slots=slots,
                     cores=os.cpu_count(), **stats)

        # Pre-stacked epoch cache (cache_prestacked): epoch 0 parses and
        # stacks [K, ...] groups once; epoch 1 replays whole super-
        # batches.  The two epochs are timed SEPARATELY at the in-band
        # EpochEnd marker — the replay-epoch rate is what the trainer's
        # transfer stage sees with its stack skipped; averaging in the
        # epoch-0 parse would overstate it.
        from fast_tffm_tpu.data.pipeline import EpochEnd, SuperBatch

        cfg = FmConfig(
            vocabulary_size=VOCAB, factor_num=8, max_features=NFEAT,
            batch_size=BATCH, thread_num=2, queue_size=8,
            cache_epochs=True, cache_prestacked=True,
            steps_per_dispatch=8,
        )
        tel = obs.Telemetry()
        pipe = BatchPipeline(
            files, cfg, epochs=2, shuffle=True, ordered=True,
            cache_epochs=True, cache_max_bytes=4 << 30, prestack_k=8,
            epoch_marks=True, telemetry=tel,
        )
        t0 = time.perf_counter()
        t_mark = None
        n0 = n1 = 0
        for b in pipe:
            if isinstance(b, EpochEnd):
                if b.epoch == 0:
                    t_mark = time.perf_counter()
                continue
            n = int(np.count_nonzero(b.batch.weights > 0)) if isinstance(
                b, SuperBatch) else int(np.count_nonzero(b.weights > 0))
            if t_mark is None:
                n0 += n
            else:
                n1 += n
        t_end = time.perf_counter()
        ps = tel.snapshot().get("timers", {}).get("ingest.prestack", {})
        emit("pipeline-prestack",
             n1 / max(t_end - t_mark, 1e-9),
             note="cached REPLAY epoch only (epoch-0 parse excluded)",
             epoch0_lines_per_sec=round(n0 / max(t_mark - t0, 1e-9)),
             steps_per_dispatch=8,
             prestack_superbatches=ps.get("count", 0),
             stack_ms_per_superbatch=round(
                 1e3 * ps.get("total_s", 0.0) / max(ps.get("count", 1), 1),
                 3,
             ))

        # Pipeline with per-batch sort_meta on the workers: what the
        # training path actually runs when host_sort engages.
        for tn in (4, 8):
            cfg = FmConfig(
                vocabulary_size=VOCAB, factor_num=8, max_features=NFEAT,
                batch_size=BATCH, thread_num=tn, queue_size=8,
            )
            tel = obs.Telemetry()
            pipe = BatchPipeline(
                files, cfg, epochs=2, shuffle=True,
                sort_meta_spec=(
                    VOCAB, sparse_apply.CHUNK, sparse_apply.TILE
                ),
                telemetry=tel,
            )
            stats = drain_with_telemetry(pipe, tel)
            emit("pipeline+meta", stats.pop("lines_per_sec"),
                 thread_num=tn, **stats)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
