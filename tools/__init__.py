# Makes tools/ an importable package so `python -m tools.lint` and
# `from tools import check_tier1` work from the repo root.  bench.py's
# historical `sys.path.insert(0, tools); import check_tier1` spelling
# keeps working too — the modules have no intra-package imports.
