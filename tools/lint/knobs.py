"""KD00x — knob drift analyzer: config fields, the INI parse surface,
the CLI parser, the docs, and the run-header fingerprint must agree.

The shipped incident behind this rule: ``alert_rules`` (and later the
resource-aliased rules) could be configured while the plane that
evaluates them was off — a knob that LOOKS set but is silently inert.
The same drift class appears every time a field is added to
``config.py`` without its INI key, or a ``--flag`` is added to cli.py
without its entry in the overrides tuple (the flag parses and then
falls on the floor).

Checks (all static; config.py and cli.py are parsed, never imported):

- KD001  FmConfig field has no INI key in ``_KEYMAP`` (the knob cannot
         be set from a cfg file);
- KD002  ``_KEYMAP`` entry names a nonexistent field (typo — the key
         parses into a constructor TypeError at load time);
- KD003  an argparse ``--flag`` whose dest IS a config field never
         appears in the CLI override plumbing (the flag parses, then
         its value is dropped — a silently-inert CLI surface);
- KD004  an override key that is not a config field (getattr/
         constructor blowup waiting for the first use);
- KD005  a config field mentioned in none of the repo docs (README /
         OBSERVABILITY / SERVING / INGEST / EMBEDDING / ...);
- KD006  a knob row in OBSERVABILITY.md's "## Knobs" table that names
         a nonexistent field or CLI flag (docs drifted ahead of code);
- KD007  the run-header fingerprint does not cover the full config
         (``_config_fingerprint`` must hash ``dataclasses.asdict`` of
         the WHOLE dataclass, or explicitly name every field) — a
         fingerprint that skips a knob lets two incomparable runs
         claim comparability.
"""

from __future__ import annotations

import ast
import re

from tools.lint.core import Context, Finding

_NON_CONFIG_DESTS = {
    # distributed-launch / legacy flags — not config knobs by design
    "coordinator", "num_processes", "process_id",
    "ps_hosts", "worker_hosts", "job_name", "task_index",
}


def _config_fields(tree) -> dict:
    """{field: lineno} of FmConfig dataclass AnnAssign fields."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "FmConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    out[stmt.target.id] = stmt.lineno
    return out


def _keymap(tree) -> dict:
    """{ini-key: (field, lineno)} from the ``_KEYMAP`` dict literal."""
    out = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "_KEYMAP"
                    for t in node.targets)
            and isinstance(node.value, ast.Dict)
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                field = None
                if isinstance(v, ast.Tuple) and v.elts and isinstance(
                    v.elts[0], ast.Constant
                ):
                    field = v.elts[0].value
                out[k.value] = (field, k.lineno)
    return out


def _cli_surface(tree):
    """(flags {--flag: (dest, lineno)}, override_mentions set).

    ``override_mentions`` is every string constant that appears inside
    a tuple/list literal or as a subscript-store key in cli.py — the
    two idioms the override plumbing uses (the big overrides tuple and
    ``overrides["telemetry"] = False``-style special cases)."""
    flags = {}
    mentions = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            dest = None
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    dest = kw.value.value
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ) and arg.value.startswith("--"):
                    d = dest or arg.value.lstrip("-").replace("-", "_")
                    flags[arg.value] = (d, arg.lineno)
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, str
                ):
                    mentions.add(e.value)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)
                ):
                    mentions.add(tgt.slice.value)
    return flags, mentions


_KNOB_ROW = re.compile(r"^\|([^|]*)\|")
_BACKTICK = re.compile(r"`([^`]+)`")


def _knob_table(md_text: str):
    """Rows of the ``## Knobs`` table: (knob, [cli spellings], lineno)."""
    rows = []
    in_section = False
    for lineno, line in enumerate(md_text.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("## "):
            in_section = stripped.startswith("## Knobs")
            continue
        if not in_section:
            continue
        m = _KNOB_ROW.match(stripped)
        if not m:
            continue
        names = _BACKTICK.findall(m.group(1))
        if not names or names[0] in ("knob",):
            continue
        knob = names[0]
        clis = [n.split()[0] for n in names[1:] if n.startswith("--")]
        rows.append((knob, clis, lineno))
    return rows


class KnobsRule:
    name = "knobs"
    rule_ids = ("KD001", "KD002", "KD003", "KD004", "KD005", "KD006",
                "KD007")

    def run(self, ctx: Context):
        findings = []
        cfg_rel = f"{ctx.pkg}/config.py"
        cli_rel = f"{ctx.pkg}/cli.py"
        if not ctx.exists(cfg_rel):
            return findings
        cfg_tree = ctx.tree(cfg_rel)
        if cfg_tree is None:
            return findings
        fields = _config_fields(cfg_tree)
        keymap = _keymap(cfg_tree)
        keymap_fields = {f for f, _ in keymap.values() if f}

        # KD001 / KD002
        for field, line in sorted(fields.items()):
            if field not in keymap_fields:
                findings.append(Finding(
                    rule="KD001", path=cfg_rel, line=line,
                    message=f"config field `{field}` has no INI key in "
                            "_KEYMAP — it cannot be set from a cfg file",
                    hint=f'add `"{field}": ("{field}", <parser>)` to '
                         "_KEYMAP",
                    symbol=field,
                ))
        for key, (field, line) in sorted(keymap.items()):
            if field and field not in fields:
                findings.append(Finding(
                    rule="KD002", path=cfg_rel, line=line,
                    message=f"_KEYMAP entry `{key}` maps to nonexistent "
                            f"field `{field}`",
                    hint="fix the field name (this key raises TypeError "
                         "at load time)",
                    symbol=key,
                ))

        # KD003 / KD004 against cli.py
        flags = {}
        if ctx.exists(cli_rel) and ctx.tree(cli_rel) is not None:
            flags, mentions = _cli_surface(ctx.tree(cli_rel))
            for flag, (dest, line) in sorted(flags.items()):
                if dest in fields and dest not in mentions:
                    findings.append(Finding(
                        rule="KD003", path=cli_rel, line=line,
                        message=(
                            f"CLI flag `{flag}` parses into dest "
                            f"`{dest}` but `{dest}` never appears in "
                            "the override plumbing — the flag is "
                            "silently inert"
                        ),
                        hint="add the dest to the overrides tuple in "
                             "cli.main()",
                        symbol=flag,
                    ))
            dests = {d for d, _ in flags.values()}
            for mention in sorted(mentions):
                if (
                    mention in dests
                    and mention not in fields
                    and mention not in _NON_CONFIG_DESTS
                ):
                    findings.append(Finding(
                        rule="KD004", path=cli_rel, line=1,
                        message=(
                            f"override key `{mention}` is plumbed from "
                            "the CLI but is not an FmConfig field"
                        ),
                        hint="rename the key to a real field or add "
                             "the field",
                        symbol=mention,
                    ))

        # KD005: every field documented somewhere
        doc_text = ""
        for doc in ctx.doc_files:
            if ctx.exists(doc):
                doc_text += ctx.source(doc) + "\n"
        for field, line in sorted(fields.items()):
            if not re.search(rf"\b{re.escape(field)}\b", doc_text):
                findings.append(Finding(
                    rule="KD005", path=cfg_rel, line=line,
                    message=f"config field `{field}` is mentioned in "
                            "none of the repo docs",
                    hint="document the knob (README or the subsystem "
                         "doc that owns it)",
                    symbol=field,
                ))

        # KD006: knobs table rows point at real code
        if ctx.exists(ctx.obs_md):
            for knob, clis, line in _knob_table(ctx.source(ctx.obs_md)):
                if knob not in fields and knob not in keymap:
                    findings.append(Finding(
                        rule="KD006", path=ctx.obs_md, line=line,
                        message=f"Knobs table row `{knob}` is not a "
                                "config field or INI key",
                        hint="fix the row or add the knob",
                        symbol=knob,
                    ))
                for cli in clis:
                    if flags and cli not in flags:
                        findings.append(Finding(
                            rule="KD006", path=ctx.obs_md, line=line,
                            message=f"Knobs table names CLI spelling "
                                    f"`{cli}` but cli.py defines no "
                                    "such flag",
                            hint="fix the spelling or add the flag",
                            symbol=cli,
                        ))

        # KD007: fingerprint covers the full config
        findings.extend(self._check_fingerprint(ctx, fields))
        return findings

    def _check_fingerprint(self, ctx, fields):
        findings = []
        for rel in ctx.package_files():
            tree = ctx.tree(rel)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.FunctionDef)
                    and node.name == "_config_fingerprint"
                ):
                    continue
                uses_asdict = any(
                    isinstance(sub, ast.Attribute)
                    and sub.attr == "asdict"
                    for sub in ast.walk(node)
                )
                if uses_asdict:
                    return []
                named = {
                    sub.value for sub in ast.walk(node)
                    if isinstance(sub, ast.Constant)
                    and isinstance(sub.value, str)
                } | {
                    sub.attr for sub in ast.walk(node)
                    if isinstance(sub, ast.Attribute)
                }
                for field in sorted(set(fields) - named):
                    findings.append(Finding(
                        rule="KD007", path=rel, line=node.lineno,
                        message=(
                            "_config_fingerprint enumerates fields but "
                            f"omits `{field}` — two runs differing in "
                            "it would fingerprint as comparable"
                        ),
                        hint="hash dataclasses.asdict(cfg) (covers "
                             "every field forever) or add the field",
                        symbol=field,
                    ))
                return findings
        return findings
