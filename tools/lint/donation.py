"""DA00x — donation / aliasing discipline analyzer.

Two hazard classes, both drawn from shipped incidents:

DA001 **use-after-donate**: an argument at a ``donate_argnums``
position of a jitted callable is dead the moment the call dispatches —
XLA may reuse its buffer for the output.  Reading the donated name
afterwards (without rebinding it to the call's result) is the
classic silent-corruption bug.  The analyzer records every
``X = jax.jit(fn, donate_argnums=...)`` binding (constant argnums
only), then flags call sites where a donated positional arg's name is
read again later in the same function without an intervening rebind.

DA002 **device_put alias-write**: on single-device CPU,
``jax.device_put`` ALIASES host memory instead of copying — writing to
the host array afterwards corrupts the in-flight device value.  That
is the PR 6 staging-pool hazard: recycled staging buffers were
rewritten while a previous super-batch still read them, making
1-device-CPU training nondeterministic until ``_StagingPool`` grew a
probe-on-first-retire gate.  The analyzer flags any name handed to
``device_put`` and LATER written in the same scope (subscript store,
augmented assign, ``.fill()``, ``np.copyto``).  Writes that go through
the probe-gated staging pool are the sanctioned exception — suppress
with ``# lint: disable=DA002`` next to the probe gate, where a reader
will find the justification.
"""

from __future__ import annotations

import ast

from tools.lint.core import (
    Context, Finding, call_name, function_scopes, recv_repr, walk_scope,
)


def _const_argnums(kw_value) -> tuple:
    """donate_argnums constant indices, or None when not static."""
    if isinstance(kw_value, ast.Constant) and isinstance(
        kw_value.value, int
    ):
        return (kw_value.value,)
    if isinstance(kw_value, (ast.Tuple, ast.List)):
        out = []
        for e in kw_value.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _donating_bindings(tree) -> dict:
    """{terminal-name: argnums} for every ``X = jax.jit(...,
    donate_argnums=CONST)`` binding in the module (X a Name or a
    ``self.X`` attribute; matching at call sites is by terminal
    name)."""
    out = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        val = node.value
        if not (
            isinstance(val, ast.Call) and call_name(val.func) in
            ("jit", "pjit")
        ):
            continue
        argnums = None
        for kw in val.keywords:
            if kw.arg == "donate_argnums":
                argnums = _const_argnums(kw.value)
        if not argnums:
            continue
        tgt = node.targets[0]
        name = (
            tgt.id if isinstance(tgt, ast.Name)
            else tgt.attr if isinstance(tgt, ast.Attribute)
            else None
        )
        if name:
            out[name] = argnums
    return out


def _name_events(fn, target: str):
    """(line, kind) events for ``target`` in one scope: kind is
    'load' or 'store'.  ``target`` is a canonical receiver string
    (``x`` or ``self.x``)."""
    events = []
    for node in walk_scope(fn):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if recv_repr(node) != target:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                events.append((node.lineno, "store"))
            elif isinstance(ctx, ast.Load):
                events.append((node.lineno, "load"))
    return sorted(events)


class DonationRule:
    name = "donation"
    rule_ids = ("DA001", "DA002")

    def run(self, ctx: Context):
        findings = []
        for rel in ctx.package_files():
            tree = ctx.tree(rel)
            if tree is None:
                continue
            donating = _donating_bindings(tree)
            for qual, fn in function_scopes(tree):
                if donating:
                    findings.extend(self._check_donate_calls(
                        rel, qual, fn, donating
                    ))
                findings.extend(self._check_device_put(rel, qual, fn))
        return findings

    # -- DA001 ---------------------------------------------------------

    def _check_donate_calls(self, rel, qual, fn, donating):
        findings = []
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            argnums = donating.get(call_name(node.func))
            if not argnums:
                continue
            for i in argnums:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                target = recv_repr(arg)
                if not target:
                    continue
                events = _name_events(fn, target)
                # The donated value is dead after the CALL (its
                # end_lineno — a multiline call's own argument lines
                # are not "later" reads); the FIRST later event must
                # be a rebind (store), not a read.  (Stores on the
                # call's own lines cover the idiomatic
                # ``state = step(state, ...)``.)
                end = getattr(node, "end_lineno", node.lineno)
                later = [e for e in events if e[0] > end]
                same_line_store = any(
                    node.lineno <= ln <= end and k == "store"
                    for ln, k in events
                )
                if same_line_store:
                    continue
                if later and later[0][1] == "load":
                    findings.append(Finding(
                        rule="DA001", path=rel, line=later[0][0],
                        message=(
                            f"`{target}` is read after being donated "
                            f"to `{call_name(node.func)}` (donate_"
                            f"argnums position {i}, call at line "
                            f"{node.lineno}) — XLA may have reused "
                            "its buffer"
                        ),
                        hint="rebind the name to the call's result, "
                             "or stop donating that argument",
                        symbol=f"{qual}.{target}",
                    ))
        return findings

    # -- DA002 ---------------------------------------------------------

    def _check_device_put(self, rel, qual, fn):
        findings = []
        put_names = {}  # target -> device_put call line
        for node in walk_scope(fn):
            if (
                isinstance(node, ast.Call)
                and call_name(node.func) == "device_put"
                and node.args
            ):
                target = recv_repr(node.args[0])
                if target:
                    put_names.setdefault(
                        target, getattr(node, "end_lineno", node.lineno)
                    )
        if not put_names:
            return findings
        for node in walk_scope(fn):
            write_line = None
            target = None
            # arr[...] = v
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        target = recv_repr(tgt.value)
                        write_line = tgt.lineno
            # arr += v / arr[...] += v
            elif isinstance(node, ast.AugAssign):
                tgt = node.target
                if isinstance(tgt, ast.Subscript):
                    target = recv_repr(tgt.value)
                else:
                    target = recv_repr(tgt)
                write_line = tgt.lineno
            # arr.fill(v) / np.copyto(arr, v)
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fill"
                ):
                    target = recv_repr(node.func.value)
                    write_line = node.lineno
                elif call_name(node.func) == "copyto" and node.args:
                    target = recv_repr(node.args[0])
                    write_line = node.lineno
            if (
                target in put_names
                and write_line is not None
                and write_line > put_names[target]
            ):
                findings.append(Finding(
                    rule="DA002", path=rel, line=write_line,
                    message=(
                        f"host array `{target}` was handed to "
                        f"device_put (line {put_names[target]}) and is "
                        "written here — on single-device backends "
                        "device_put ALIASES host memory, so this "
                        "corrupts the in-flight device value"
                    ),
                    hint="route the reuse through a probe-gated pool "
                         "(see _StagingPool) or copy before the write",
                    symbol=f"{qual}.{target}",
                ))
        return findings
