"""RS00x — JSONL record-schema drift analyzer.

check_obs pinned metric NAMES; this rule generalizes the discipline to
the full ``record:`` taxonomy of the metrics stream (run_header /
train / validation / heartbeat / final / compile / alert / status, plus
the ``health`` / ``tiered`` / ``resource`` / ``serve`` / ``stages``
blocks that ride the heartbeat-shaped records), pinned against the
"## Record schema" table in OBSERVABILITY.md.  The failure mode is the
same on both sides: a record type code emits but the docs never name
is invisible to everyone parsing the stream from the docs
(tools/report.py included); a documented type nothing emits is a
dashboard watching a stream that will never carry it.

Code-side collection is static and covers the repo's two idioms:

- literal sites: any dict literal with a ``"record": "<type>"`` entry;
- builder sites: a function whose record dict reads the type from a
  parameter (``def build(kind="status"): {... "record": kind ...}``) —
  the analyzer resolves every string literal passed to that function
  (plus the parameter default) into emitted types.

Checks:

- RS001  a record type emitted in code but absent from the table;
- RS002  a documented record type nothing emits (stale row);
- RS003  a LITERAL record dict missing keys the table pins as required
         for its type (dynamic builders can't be key-checked
         statically and are exempt);
- RS004  a documented block name never attached to any record in code.
"""

from __future__ import annotations

import ast
import re

from tools.lint.core import Context, Finding

_BACKTICK = re.compile(r"`([^`]+)`")


def parse_schema_table(md_text: str):
    """Rows of the ``## Record schema`` table.

    Expected columns: ``| record | required keys | blocks | notes |``.
    Returns ({record: (required_keys, lineno)}, {block: lineno})."""
    records: dict = {}
    blocks: dict = {}
    in_section = False
    for lineno, line in enumerate(md_text.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("## "):
            in_section = stripped.startswith("## Record schema")
            continue
        if not in_section or not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if len(cells) < 3 or set(cells[0]) <= {"-", " ", ":"}:
            continue
        names = _BACKTICK.findall(cells[0])
        if not names or names[0] == "record":
            continue
        required = tuple(_BACKTICK.findall(cells[1]))
        records[names[0]] = (required, lineno)
        for b in _BACKTICK.findall(cells[2]):
            if b != "—":
                blocks.setdefault(b, lineno)
    return records, blocks


def _collect_emissions(ctx: Context):
    """Scan the package for emitted record types.

    Returns (literal_sites, dynamic_types, attached_keys) where
    ``literal_sites`` is [(type, rel, line, literal_keys)],
    ``dynamic_types`` is {type: (rel, line)} resolved through builder
    parameters, and ``attached_keys`` is every string constant used as
    a dict-literal key or subscript-store key anywhere in the package
    (the block-attachment surface)."""
    literal_sites = []
    attached = {}

    for rel in ctx.package_files():
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Dict):
                keys = [
                    k.value for k in node.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                ]
                for k in keys:
                    attached.setdefault(k, (rel, node.lineno))
                for k, v in zip(node.keys, node.values):
                    if not (
                        isinstance(k, ast.Constant)
                        and k.value == "record"
                    ):
                        continue
                    if isinstance(v, ast.Constant) and isinstance(
                        v.value, str
                    ):
                        literal_sites.append(
                            (v.value, rel, node.lineno, set(keys))
                        )
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)
                    ):
                        attached.setdefault(
                            tgt.slice.value, (rel, tgt.lineno)
                        )

    # Resolve dynamic builders: find the function whose parameter feeds
    # the "record" value, then every literal argument at its call
    # sites (any file) plus the parameter default.
    dynamic: dict = {}
    builder_fns = []  # (rel, func name, param name, param index, default)
    for rel in ctx.package_files():
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            # Does this function build a {"record": <param>} dict?
            params = [a.arg for a in fn.args.args]
            dict_names = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (
                            isinstance(k, ast.Constant)
                            and k.value == "record"
                            and isinstance(v, ast.Name)
                        ):
                            dict_names.add(v.id)
            for pname in dict_names:
                if pname not in params:
                    continue
                idx = params.index(pname)
                default = None
                n_defaults = len(fn.args.defaults)
                if n_defaults and idx >= len(params) - n_defaults:
                    d = fn.args.defaults[idx - (len(params) - n_defaults)]
                    if isinstance(d, ast.Constant) and isinstance(
                        d.value, str
                    ):
                        default = d.value
                builder_fns.append((rel, fn.name, pname, idx, default))

    for rel, fname, pname, idx, default in builder_fns:
        if default:
            dynamic.setdefault(default, (rel, 1))
        for rel2 in ctx.package_files():
            tree = ctx.tree(rel2)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                tname = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else ""
                )
                if tname != fname:
                    continue
                # positional (account for a leading self at methods
                # called as attributes) or keyword
                cands = []
                for off in (0, -1):  # plain call / bound-method call
                    pos = idx + off
                    if 0 <= pos < len(node.args):
                        cands.append(node.args[pos])
                for kw in node.keywords:
                    if kw.arg == pname:
                        cands.append(kw.value)
                for c in cands:
                    if isinstance(c, ast.Constant) and isinstance(
                        c.value, str
                    ):
                        dynamic.setdefault(
                            c.value, (rel2, node.lineno)
                        )
    return literal_sites, dynamic, attached


class RecordsRule:
    name = "records"
    rule_ids = ("RS001", "RS002", "RS003", "RS004")

    def run(self, ctx: Context):
        findings = []
        if not ctx.exists(ctx.obs_md):
            return findings
        documented, doc_blocks = parse_schema_table(ctx.source(ctx.obs_md))
        literal_sites, dynamic, attached = _collect_emissions(ctx)

        emitted: dict = {}
        for rtype, rel, line, _keys in literal_sites:
            emitted.setdefault(rtype, (rel, line))
        for rtype, site in dynamic.items():
            emitted.setdefault(rtype, site)

        if not documented:
            findings.append(Finding(
                rule="RS002", path=ctx.obs_md, line=1,
                message="no '## Record schema' table found — the "
                        "record taxonomy is unpinned",
                hint="add the table (see LINTING.md)",
                symbol="<missing-table>",
            ))
            return findings

        for rtype, (rel, line) in sorted(emitted.items()):
            if rtype not in documented:
                findings.append(Finding(
                    rule="RS001", path=rel, line=line,
                    message=f"record type `{rtype}` is emitted here "
                            "but absent from OBSERVABILITY.md's "
                            "Record schema table",
                    hint="add a row documenting the record",
                    symbol=rtype,
                ))
        for rtype, (_req, line) in sorted(documented.items()):
            if rtype not in emitted:
                findings.append(Finding(
                    rule="RS002", path=ctx.obs_md, line=line,
                    message=f"documented record type `{rtype}` is "
                            "emitted nowhere in the package",
                    hint="remove the row or fix the emitting code",
                    symbol=rtype,
                ))
        for rtype, rel, line, keys in literal_sites:
            req, _ = documented.get(rtype, ((), 0))
            missing = [k for k in req if k not in keys]
            if missing:
                findings.append(Finding(
                    rule="RS003", path=rel, line=line,
                    message=(
                        f"literal `{rtype}` record is missing pinned "
                        f"key(s) {missing}"
                    ),
                    hint="emit the keys or update the Record schema "
                         "table",
                    symbol=f"{rtype}@{rel}",
                ))
        for block, line in sorted(doc_blocks.items()):
            if block not in attached:
                findings.append(Finding(
                    rule="RS004", path=ctx.obs_md, line=line,
                    message=f"documented block `{block}` is never "
                            "attached to any record in code",
                    hint="remove it from the table or fix the "
                         "attaching code",
                    symbol=block,
                ))
        return findings
