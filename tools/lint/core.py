"""tffm-lint core: the shared AST-walk framework every analyzer rides.

The repo's hardest bugs have been *invariant violations no test caught
until a reviewer did* (the PR 6 single-device ``device_put`` aliasing
hazard, the PR 7 tracer drop-cap truncation, silently-inert
``alert_rules``).  Each analyzer in this package makes one of those
review checklists mechanical.  The framework's jobs:

- parse every package source ONCE (:class:`Context` caches trees);
- represent results uniformly (:class:`Finding`: file:line + rule id +
  message + fix hint + a line-number-free ``key`` for baselining);
- suppress grandfathered findings via a ``--baseline`` file so NEW
  violations fail while old ones burn down;
- honor inline ``# lint: disable=RULE`` comments on the flagged line
  (for the rare sanctioned exception that deserves to live next to the
  code it excuses, e.g. the probe-gated staging pool).

Everything is stdlib-only, static (no imports of the package under
analysis), and runs in milliseconds — the same discipline as the two
ancestors it grew from (tools/check_tier1.py, tools/check_obs.py),
which are folded in as rules T1001/OB001-OB002.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Optional


@dataclasses.dataclass
class Finding:
    """One rule violation at one site.

    ``symbol`` is the stable identity used for baselining (a qualified
    name like ``ClassName.attr`` — never a line number, so baselines
    survive unrelated edits to the file above the finding).
    """

    rule: str      # e.g. "TL001"
    path: str      # repo-relative
    line: int
    message: str
    hint: str = ""
    symbol: str = ""

    @property
    def key(self) -> str:
        """Baseline identity: rule + path + symbol (no line numbers)."""
        sym = self.symbol or re.sub(r"\s+", "-", self.message)[:80]
        return f"{self.rule}:{self.path}:{sym}"

    def render(self, baselined: bool = False) -> str:
        tag = " [baselined]" if baselined else ""
        hint = f"  (fix: {self.hint})" if self.hint else ""
        return f"{self.path}:{self.line}: {self.rule}{tag} " \
               f"{self.message}{hint}"


class Context:
    """One lint run's view of the repo: file discovery + parse cache.

    Paths are configurable so tests can point the same rules at a
    fixture tree (a miniature repo with its own config.py / cli.py /
    OBSERVABILITY.md) instead of the live one.
    """

    def __init__(
        self,
        root: str,
        pkg: str = "fast_tffm_tpu",
        tests_dir: str = "tests",
        obs_md: str = "OBSERVABILITY.md",
        doc_files: tuple = ("README.md", "OBSERVABILITY.md",
                            "SERVING.md", "INGEST.md", "EMBEDDING.md",
                            "QUALITY.md", "LINTING.md"),
        extra_files: tuple = (),
    ):
        self.root = os.path.abspath(root)
        self.pkg = pkg
        self.tests_dir = tests_dir
        self.obs_md = obs_md
        self.doc_files = doc_files
        self.extra_files = tuple(extra_files)
        self._trees: dict = {}
        self._sources: dict = {}

    # -- file discovery ------------------------------------------------

    def package_files(self) -> list:
        """Repo-relative paths of every package ``.py`` source, plus any
        ``extra_files`` (fixture snippets in tests)."""
        out = []
        pkg_dir = os.path.join(self.root, self.pkg)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fname), self.root
                    ))
        out.extend(self.extra_files)
        return out

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def exists(self, rel: str) -> bool:
        return os.path.exists(self.abspath(rel))

    # -- parse cache ---------------------------------------------------

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            with open(self.abspath(rel)) as f:
                self._sources[rel] = f.read()
        return self._sources[rel]

    def tree(self, rel: str) -> Optional[ast.AST]:
        """Parsed AST for one file (None on syntax error — an
        unparsable source is its own, louder problem)."""
        if rel not in self._trees:
            try:
                self._trees[rel] = ast.parse(
                    self.source(rel), filename=rel
                )
            except SyntaxError:
                self._trees[rel] = None
        return self._trees[rel]

    def line_disables(self, rel: str, line: int) -> set:
        """Rule ids named by a ``# lint: disable=R1,R2`` comment on
        ``line`` (1-indexed) of ``rel``."""
        try:
            text = self.source(rel).splitlines()[line - 1]
        except IndexError:
            return set()
        m = re.search(r"#\s*lint:\s*disable=([\w,]+)", text)
        return set(m.group(1).split(",")) if m else set()


# ---------------------------------------------------------------------
# shared AST helpers (used by several analyzers)
# ---------------------------------------------------------------------

def call_name(node: ast.AST) -> str:
    """Terminal name of a call target: ``jax.jit`` -> ``jit``,
    ``Thread`` -> ``Thread``, anything else -> ''."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def recv_repr(node: ast.AST) -> str:
    """Canonical text of a simple receiver chain (``self._lock``,
    ``work``); '' for anything more complex."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = recv_repr(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def function_scopes(tree: ast.AST) -> list:
    """Every function in the module as ``(qualname, node)``, methods
    qualified by their class.  Each scope's body is analyzed
    independently; nested defs become their own scopes."""
    out = []

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                out.append((qual, child))
                visit(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def walk_scope(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk limited to one function scope: descends everything
    EXCEPT nested function/class bodies (they are separate scopes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(child))


# ---------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------

def load_baseline(path: str) -> dict:
    """{finding-key: comment} from a baseline file.  Line format::

        RULE:path:symbol  # why this finding is grandfathered

    Full-line ``#`` comments and blanks are ignored.  Every entry MUST
    carry a trailing comment — a baseline without a reason is just a
    muted alarm (enforced by the CLI, warned here)."""
    out: dict = {}
    if not path or not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            key, _, comment = line.partition("#")
            out[key.strip()] = comment.strip()
    return out


def run_rules(rules, ctx: Context, baseline: Optional[dict] = None) -> dict:
    """Run every rule; classify findings against the baseline.

    Returns ``{findings, new, baselined, stale, uncommented}`` where
    ``stale`` lists baseline keys no current finding matches (burn the
    entry down) and ``uncommented`` baseline keys with no reason."""
    baseline = baseline or {}
    findings: list = []
    for rule in rules:
        for f in rule.run(ctx):
            if f.rule in ctx.line_disables(f.path, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    seen_keys = set()
    new, baselined = [], []
    for f in findings:
        seen_keys.add(f.key)
        (baselined if f.key in baseline else new).append(f)
    stale = sorted(set(baseline) - seen_keys)
    uncommented = sorted(
        k for k, comment in baseline.items() if not comment
    )
    return {
        "findings": findings, "new": new, "baselined": baselined,
        "stale": stale, "uncommented": uncommented,
    }
