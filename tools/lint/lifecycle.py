"""TL00x — thread/queue/SHM/HTTP-server lifecycle analyzer.

Every concurrency resource the package creates must have a REACHABLE
teardown on its owner's shutdown path:

  ==============================  =========================
  resource (constructor)          teardown (any of)
  ==============================  =========================
  ``threading.Thread``            ``join``
  ``multiprocessing`` ``Process`` ``join`` / ``terminate``
  ``_ClosableQueue``              ``cancel`` / ``close``
  ``shared_memory.SharedMemory``  ``close`` / ``unlink``
  ``ThreadingHTTPServer``         ``shutdown``
  ``subprocess.Popen``            ``wait`` / ``terminate`` / ``kill``
  ==============================  =========================

``Popen`` (TL006) joined the table with the serve router's replica
manager: a spawned replica subprocess with no reachable
terminate/wait on the manager's teardown path would OUTLIVE its
router — an orphaned jax process holding a port and a device.

The class of leak this catches only shows at runtime today — the
``test_ingest_matrix`` /dev/shm sweep finds orphaned segments, and a
daemon thread that is never joined dies mid-write at interpreter exit
(the PR 2 poll-free-shutdown work exists because of exactly that).
``daemon=True`` does NOT excuse a missing join: daemon threads are the
ones that get killed holding locks or half-written files.

Ownership heuristics (deliberately conservative — transfer of
ownership suppresses the finding, the baseline catches what slips
through):

- ``self.x = Thread(...)``: some method of the SAME class must call
  ``self.x.join()`` (rule TL001; analogous ids per resource kind).
- local ``t = Thread(...)``: the same function must call ``t.join()``,
  unless the local is returned, stored on ``self``, appended into a
  container, or passed to another callable (ownership moved).
- ``threads = [Thread(...) ...]`` / ``threads += [...]`` /
  ``lst.append(Thread(...))``: some loop/comprehension over that
  container must call ``.join()`` on the loop variable.
- ``self.workers = [Thread(...) for ...]`` (rule TL007, the
  worker-pool shape PooledHTTPServer introduced): some
  loop/comprehension over ``self.workers`` ANYWHERE in the class must
  call the teardown on the loop variable — pooled handler threads need
  a reachable join on the server's shutdown path, same class of leak
  as TL005/TL006.
- ``threading.Thread(...).start()`` with the object never bound:
  nothing can EVER join it — always a finding.
"""

from __future__ import annotations

import ast

from tools.lint.core import (
    Context, Finding, call_name, function_scopes, recv_repr, walk_scope,
)

# constructor terminal name -> (rule id, kind, teardown attr names)
_RESOURCES = {
    "Thread": ("TL001", "thread", ("join",)),
    "Process": ("TL001", "process", ("join", "terminate")),
    "_ClosableQueue": ("TL002", "queue", ("cancel", "close")),
    "SharedMemory": ("TL003", "SHM segment", ("close", "unlink")),
    "ThreadingHTTPServer": ("TL004", "HTTP server", ("shutdown",)),
    "HTTPServer": ("TL004", "HTTP server", ("shutdown",)),
    "ObsHTTPServer": ("TL004", "HTTP server", ("shutdown",)),
    "PooledHTTPServer": ("TL004", "HTTP server", ("shutdown",)),
    "Popen": ("TL006", "subprocess", ("wait", "terminate", "kill")),
}


def _ctor(node):
    """(rule, kind, teardowns) when ``node`` constructs a tracked
    resource, else None."""
    if isinstance(node, ast.Call):
        info = _RESOURCES.get(call_name(node.func))
        if info:
            return info
    return None


def _teardown_calls(node, teardowns):
    """Receivers (canonical text) of ``X.join()``-style calls under
    ``node`` — includes nested functions: a teardown is reachable from
    a closure (``finally: ... join``) as much as from a method."""
    out = set()
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in teardowns
        ):
            r = recv_repr(sub.func.value)
            if r:
                out.add(r)
    return out


def _container_teardown(node, container, teardowns) -> bool:
    """True when ``node`` contains ``for t in <container>: t.join()``
    (or a comprehension doing the same)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.For, ast.comprehension)):
            it = sub.iter
            tgt = sub.target
            if recv_repr(it) != container:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            body = sub.body if isinstance(sub, ast.For) else []
            haystack = body or [sub]
            for b in haystack:
                for c in ast.walk(b if isinstance(b, ast.AST) else sub):
                    if (
                        isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr in teardowns
                        and isinstance(c.func.value, ast.Name)
                        and c.func.value.id == tgt.id
                    ):
                        return True
        # [t.join() for t in threads]
        if isinstance(sub, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            gens = sub.generators
            if (
                gens
                and recv_repr(gens[0].iter) == container
                and isinstance(gens[0].target, ast.Name)
                and isinstance(sub.elt, ast.Call)
                and isinstance(sub.elt.func, ast.Attribute)
                and sub.elt.func.attr in teardowns
                and isinstance(sub.elt.func.value, ast.Name)
                and sub.elt.func.value.id == gens[0].target.id
            ):
                return True
    return False


class LifecycleRule:
    name = "lifecycle"
    rule_ids = ("TL001", "TL002", "TL003", "TL004", "TL005", "TL006",
                "TL007")

    def run(self, ctx: Context):
        findings = []
        for rel in ctx.package_files():
            tree = ctx.tree(rel)
            if tree is None:
                continue
            findings.extend(self._check_module(ctx, rel, tree))
        return findings

    # -----------------------------------------------------------------

    def _check_module(self, ctx, rel, tree):
        findings = []
        # Class-attribute bindings: teardown must exist on the class.
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            torn = {}  # teardown attr receivers, computed lazily per kind
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                if len(node.targets) != 1:
                    continue
                tgt = node.targets[0]
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                info = _ctor(node.value)
                if info is not None:
                    rule, kind, teardowns = info
                    if teardowns not in torn:
                        torn[teardowns] = _teardown_calls(cls, teardowns)
                    if f"self.{tgt.attr}" not in torn[teardowns]:
                        findings.append(Finding(
                            rule=rule, path=rel, line=node.value.lineno,
                            message=(
                                f"{kind} `self.{tgt.attr}` created in "
                                f"{cls.name} has no reachable "
                                f"{'/'.join(teardowns)} anywhere in "
                                "the class"
                            ),
                            hint=(
                                f"call `self.{tgt.attr}."
                                f"{teardowns[0]}()` on the owner's "
                                "close()/teardown path"
                            ),
                            symbol=f"{cls.name}.{tgt.attr}",
                        ))
                    continue
                # TL007 — the worker-pool shape: a CONTAINER of
                # tracked resources bound to a self attribute
                # (``self._workers = [Thread(...) for ...]``).  The
                # function-scope container pass cannot see these (the
                # teardown loop lives in ANOTHER method, usually
                # close()/server_close()), so the class is the scope:
                # some loop/comprehension over ``self.attr`` must tear
                # each element down.
                if isinstance(node.value, (ast.List, ast.ListComp)):
                    elts = (
                        node.value.elts
                        if isinstance(node.value, ast.List)
                        else [node.value.elt]
                    )
                    for e in elts:
                        info = _ctor(e)
                        if info is None:
                            continue
                        _, kind, teardowns = info
                        if _container_teardown(
                            cls, f"self.{tgt.attr}", teardowns
                        ):
                            continue
                        findings.append(Finding(
                            rule="TL007", path=rel, line=e.lineno,
                            message=(
                                f"{kind}s collected into "
                                f"`self.{tgt.attr}` in {cls.name} are "
                                f"never {'/'.join(teardowns)}ed (no "
                                f"loop over `self.{tgt.attr}` "
                                "anywhere in the class tears them "
                                "down)"
                            ),
                            hint=(
                                f"`for t in self.{tgt.attr}: "
                                f"t.{teardowns[0]}()` on the owner's "
                                "close()/teardown path"
                            ),
                            symbol=f"{cls.name}.{tgt.attr}[]",
                        ))
            # Local bindings inside methods are handled by the
            # function-scope pass below (function_scopes covers them).
        # Function-scope locals + containers + unbound starts.
        for qual, fn in function_scopes(tree):
            findings.extend(self._check_scope(ctx, rel, qual, fn))
        return findings

    def _check_scope(self, ctx, rel, qual, fn):
        findings = []
        locals_: dict = {}      # name -> (line, rule, kind, teardowns)
        containers: dict = {}   # container name -> (line, rule, kind, tds)
        transferred: set = set()

        # Pass 1: register bindings (walk order is arbitrary, so
        # transfers are collected in a second pass once every local is
        # known — `return cls(shm, ...)` transfers `shm` regardless of
        # visit order).
        for node in walk_scope(fn):
            # t = Thread(...)
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                info = _ctor(node.value)
                tgt = node.targets[0]
                if info and isinstance(tgt, ast.Name):
                    locals_[tgt.id] = (node.value.lineno,) + info
                    continue
                # threads = [Thread(...), ...] / [... for _ in range(n)]
                if isinstance(tgt, ast.Name) and isinstance(
                    node.value, (ast.List, ast.ListComp)
                ):
                    elts = (
                        node.value.elts
                        if isinstance(node.value, ast.List)
                        else [node.value.elt]
                    )
                    for e in elts:
                        info = _ctor(e)
                        if info:
                            containers[tgt.id] = (e.lineno,) + info
            # threads += [Thread(...) for ...]
            if isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name
            ) and isinstance(node.value, (ast.List, ast.ListComp)):
                elts = (
                    node.value.elts
                    if isinstance(node.value, ast.List)
                    else [node.value.elt]
                )
                for e in elts:
                    info = _ctor(e)
                    if info:
                        containers[node.target.id] = (e.lineno,) + info
            if isinstance(node, ast.Call):
                # Thread(...).start() with the object never bound.
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start"
                    and _ctor(node.func.value)
                ):
                    rule, kind, teardowns = _ctor(node.func.value)
                    findings.append(Finding(
                        rule="TL005", path=rel,
                        line=node.func.value.lineno,
                        message=(
                            f"{kind} started in {qual} without binding "
                            "the object — nothing can ever "
                            f"{'/'.join(teardowns)} it"
                        ),
                        hint="bind it to an attribute and tear it down "
                             "with the owner",
                        symbol=f"{qual}.<unbound>",
                    ))
                # lst.append(Thread(...))
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and isinstance(node.func.value, ast.Name)
                    and node.args
                ):
                    info = _ctor(node.args[0])
                    if info:
                        containers[node.func.value.id] = (
                            (node.args[0].lineno,) + info
                        )

        # Pass 2: ownership transfers out of the scope.
        for node in walk_scope(fn):
            if isinstance(node, ast.Call):
                # x passed to another callable -> ownership moved
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Name) and arg.id in locals_:
                        transferred.add(arg.id)
            # return x / self.y = x -> ownership moved
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                transferred.add(node.value.id)
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Name
            ):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        transferred.add(node.value.id)

        for name, (line, rule, kind, teardowns) in locals_.items():
            if name in transferred:
                continue
            # Teardown reachable anywhere in the function, incl. nested
            # closures (shutdown paths often live in a finally).
            if name in _teardown_calls(fn, teardowns):
                continue
            findings.append(Finding(
                rule=rule, path=rel, line=line,
                message=(
                    f"{kind} `{name}` created in {qual} is never "
                    f"{'/'.join(teardowns)}ed in this scope and its "
                    "ownership never leaves it"
                ),
                hint=f"`{name}.{teardowns[0]}()` before the scope "
                     "exits (a finally: block survives errors)",
                symbol=f"{qual}.{name}",
            ))
        for cname, (line, rule, kind, teardowns) in containers.items():
            if _container_teardown(fn, cname, teardowns):
                continue
            findings.append(Finding(
                rule=rule, path=rel, line=line,
                message=(
                    f"{kind}s collected into `{cname}` in {qual} are "
                    f"never {'/'.join(teardowns)}ed (no loop over "
                    f"`{cname}` tears them down)"
                ),
                hint=f"`for t in {cname}: t.{teardowns[0]}()` on the "
                     "teardown path",
                symbol=f"{qual}.{cname}[]",
            ))
        return findings
