"""T1001 / OB00x — the two ancestor lints folded in as rules.

tools/check_tier1.py (tier-1 marker audit) and tools/check_obs.py
(metric-name drift) predate the framework and stay importable on their
own (bench.py's preflight imports check_tier1 directly), but
``python -m tools.lint`` is now the one entry point: their findings
flow through the same baseline / exit-code machinery as every other
rule.

- T1001  one finding per check_tier1 problem (a test file with no
         tier-1 tests, an undeclared marker, a file defining no tests);
- OB001  an instrument registered in code but missing from
         OBSERVABILITY.md's Metric schema table;
- OB002  a documented metric no code registers.
"""

from __future__ import annotations

import os
import re

from tools.lint.core import Context, Finding


class Tier1Rule:
    name = "tier1"
    rule_ids = ("T1001",)

    def run(self, ctx: Context):
        from tools import check_tier1

        tests_dir = os.path.join(ctx.root, ctx.tests_dir)
        if not os.path.isdir(tests_dir):
            return []
        result = check_tier1.audit(tests_dir, ctx.root)
        findings = []
        for problem in result["problems"]:
            fname, _, detail = problem.partition(":")
            path = (
                f"{ctx.tests_dir}/{fname}" if fname.endswith(".py")
                else ctx.tests_dir
            )
            # Stable symbol: the file plus the problem's first clause
            # (line numbers never appear in check_tier1 output).
            sym = re.sub(r"\s+", "-", detail.strip())[:60] or fname
            findings.append(Finding(
                rule="T1001", path=path, line=1,
                message=problem,
                hint="see tools/check_tier1.py --list",
                symbol=f"{fname}:{sym.split('—')[0].strip('-')}",
            ))
        return findings


class ObsMetricsRule:
    name = "obs-metrics"
    rule_ids = ("OB001", "OB002")

    def run(self, ctx: Context):
        from tools import check_obs

        md = ctx.abspath(ctx.obs_md)
        pkg = os.path.join(ctx.root, ctx.pkg)
        if not (os.path.exists(md) and os.path.isdir(pkg)):
            return []
        result = check_obs.audit(pkg, md)
        findings = []
        for name in result["undocumented"]:
            site = result["registered"][name][0]
            path, _, line = site.partition(":")
            findings.append(Finding(
                rule="OB001", path=path, line=int(line or 1),
                message=f"instrument `{name}` is registered here but "
                        "missing from the Metric schema table",
                hint="add the row to OBSERVABILITY.md",
                symbol=name,
            ))
        for name in result["stale"]:
            findings.append(Finding(
                rule="OB002", path=ctx.obs_md, line=1,
                message=f"documented metric `{name}` is registered "
                        "nowhere in code",
                hint="remove the row or fix the name",
                symbol=name,
            ))
        if not result["documented"]:
            findings.append(Finding(
                rule="OB002", path=ctx.obs_md, line=1,
                message="no '## Metric schema' table found",
                hint="add the table",
                symbol="<missing-table>",
            ))
        return findings
