"""LK001 — blocking-call-under-lock analyzer.

The heartbeat, status-endpoint, serve-batcher, and checkpoint-watcher
threads all share locks with the hot path.  A blocking call made while
HOLDING one of those locks turns a slow peer into a stalled trainer
(and two such sites into a deadlock).  Flagged while inside a
``with <lock>:`` body:

- ``q.get()`` / ``q.put(item)`` with no ``timeout=`` (indefinite queue
  block; the PR 2 shutdown hangs were exactly this);
- ``x.join()`` with no timeout (thread join);
- ``fut.result()`` with no timeout;
- ``sock.recv(...)`` / ``sock.accept()`` (socket reads);
- ``time.sleep(...)``, ``ev.wait()`` with no timeout;
- ``arr.block_until_ready()`` (device sync — the one call that also
  perturbs the measurement the obs plane exists to take).

A ``with`` target counts as a lock when its terminal name contains
``lock`` or is a condition variable (``_cv`` / ``cond``).  For a
condition variable, ``wait``/``wait_for`` on the SAME object is the
sanctioned idiom (it releases the lock) and is not flagged.

Heuristics keep noise down: ``d.get(key)`` (positional args = dict
access) and ``", ".join(parts)`` (string receiver / single iterable
arg) are not flagged.  Nested function bodies defined under the lock
do not execute under it and are skipped.
"""

from __future__ import annotations

import ast

from tools.lint.core import (
    Context, Finding, call_name, function_scopes, recv_repr,
)

_CV_HINTS = ("_cv", "cond")


def _is_lock_expr(expr) -> tuple:
    """(is_lock, receiver, is_cv) for a with-item context expr."""
    r = recv_repr(expr)
    if not r:
        return False, "", False
    terminal = r.rsplit(".", 1)[-1].lower()
    if "lock" in terminal:
        return True, r, False
    if any(h in terminal for h in _CV_HINTS):
        return True, r, True
    return False, r, False


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _blocking_reason(call: ast.Call, cv_receivers: set):
    """Why this call blocks indefinitely, or None."""
    func = call.func
    name = call_name(func)
    recv = (
        recv_repr(func.value) if isinstance(func, ast.Attribute) else ""
    )
    if name == "get" and not call.args and not _has_timeout(call):
        # zero positional args = queue.get(); d.get(key) has one.
        if isinstance(func, ast.Attribute):
            return "Queue.get() with no timeout"
    if name == "put" and len(call.args) == 1 and not _has_timeout(call):
        if isinstance(func, ast.Attribute):
            return "Queue.put() with no timeout (blocks when full)"
    if name == "join" and isinstance(func, ast.Attribute):
        # exclude str.join ("sep".join(parts), receiver-with-arg) and
        # os.path.join
        if (
            not call.args
            and not isinstance(func.value, (ast.Constant, ast.JoinedStr))
            and recv.rsplit(".", 1)[-1] != "path"
        ):
            return "join() with no timeout"
    if name == "result" and not call.args and not _has_timeout(call):
        if isinstance(func, ast.Attribute):
            return "Future.result() with no timeout"
    if name in ("recv", "accept") and isinstance(func, ast.Attribute):
        return f"socket {name}()"
    if name == "sleep":
        return "time.sleep()"
    if name in ("wait", "wait_for") and isinstance(func, ast.Attribute):
        if recv in cv_receivers:
            return None  # cv.wait() releases the cv's own lock
        if not call.args and not _has_timeout(call):
            return "wait() with no timeout"
    if name == "block_until_ready":
        return "device sync (block_until_ready)"
    return None


class LocksRule:
    name = "locks"
    rule_ids = ("LK001",)

    def run(self, ctx: Context):
        findings = []
        for rel in ctx.package_files():
            tree = ctx.tree(rel)
            if tree is None:
                continue
            for qual, fn in function_scopes(tree):
                findings.extend(self._check_scope(rel, qual, fn))
        return findings

    def _check_scope(self, rel, qual, fn):
        findings = []

        def visit(node, held, cvs):
            """Walk statements tracking the set of held locks; nested
            defs start fresh (their bodies run later, lock not held)."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, ast.With):
                new_held, new_cvs = set(held), set(cvs)
                for item in node.items:
                    is_lock, recv, is_cv = _is_lock_expr(
                        item.context_expr
                    )
                    if is_lock:
                        new_held.add(recv)
                        if is_cv:
                            new_cvs.add(recv)
                for item in node.items:
                    visit(item.context_expr, held, cvs)
                for stmt in node.body:
                    visit(stmt, new_held, new_cvs)
                return
            if isinstance(node, ast.Call) and held:
                reason = _blocking_reason(node, cvs)
                if reason:
                    locks = ", ".join(sorted(held))
                    findings.append(Finding(
                        rule="LK001", path=rel, line=node.lineno,
                        message=(
                            f"blocking call ({reason}) while holding "
                            f"`{locks}` in {qual}"
                        ),
                        hint="add a timeout, or move the blocking "
                             "call outside the lock",
                        symbol=f"{qual}.{call_name(node.func)}"
                               f"@{locks}",
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child, held, cvs)

        for stmt in fn.body:
            visit(stmt, set(), set())
        return findings
