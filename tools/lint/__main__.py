"""CLI for tffm-lint.

::

    python -m tools.lint                  # all rules, default baseline
    python -m tools.lint --list-rules     # rule catalog
    python -m tools.lint --show-baselined # include grandfathered finds
    python -m tools.lint --write-baseline # bootstrap/refresh baseline
    python -m tools.lint --no-baseline    # raw findings (exit 1 on any)

Exit codes: 0 = clean (or every finding baselined), 1 = new findings
(or a malformed baseline: stale entries and entries without a reason
comment fail too — a baseline is a burn-down list, not a mute button).
"""

from __future__ import annotations

import argparse
import os
import sys

# `python tools/lint/__main__.py` (path form) lacks the repo root on
# sys.path; `python -m tools.lint` has it.  Support both.
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools import lint  # noqa: E402
from tools.lint.core import Context, load_baseline, run_rules  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="fast_tffm_tpu static-analysis suite "
                    "(rule catalog: LINTING.md)",
    )
    ap.add_argument("--root", default=_REPO,
                    help="repo root (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default "
                         f"<root>/{lint.DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report and fail on "
                         "every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding to the baseline "
                         "file (entries still need a reason comment "
                         "added by hand)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--rules", default=None, metavar="NAMES",
                    help="comma-separated rule names to run "
                         "(default: all; see --list-rules)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    rules = lint.default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:12} {', '.join(r.rule_ids)}")
        return 0
    if args.rules:
        wanted = {w.strip() for w in args.rules.split(",")}
        rules = [r for r in rules if r.name in wanted]
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    ctx = Context(args.root)
    baseline_path = args.baseline or os.path.join(
        ctx.root, lint.DEFAULT_BASELINE
    )
    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    if args.rules:
        # A subset run can only see its own rules' findings — entries
        # for unselected rules are invisible, not stale.
        selected_ids = {i for r in rules for i in r.rule_ids}
        baseline = {
            k: v for k, v in baseline.items()
            if k.split(":", 1)[0] in selected_ids
        }
    result = run_rules(rules, ctx, baseline)

    if args.write_baseline:
        with open(baseline_path, "w") as f:
            f.write(
                "# tffm-lint baseline: grandfathered findings "
                "(LINTING.md).\n"
                "# One key per line; EVERY entry needs a trailing "
                "'# reason'.\n"
                "# Burn entries down — a fixed finding shows up as "
                "'stale' and fails the run.\n"
            )
            for fnd in result["findings"]:
                comment = baseline.get(fnd.key, "")
                f.write(f"{fnd.key}  # {comment}\n")
        print(f"wrote {len(result['findings'])} finding key(s) to "
              f"{baseline_path} — add a reason after each '#'")
        return 0

    for fnd in result["new"]:
        print(fnd.render())
    if args.show_baselined:
        for fnd in result["baselined"]:
            print(fnd.render(baselined=True))
    problems = len(result["new"])
    for key in result["stale"]:
        print(f"stale baseline entry (fixed? remove the line): {key}")
    for key in result["uncommented"]:
        print(f"baseline entry without a reason comment: {key}")
    n_rules = sum(len(r.rule_ids) for r in rules)
    print(
        f"tffm-lint: {len(rules)} analyzers ({n_rules} rule ids), "
        f"{len(result['findings'])} finding(s) "
        f"({len(result['baselined'])} baselined, "
        f"{len(result['new'])} new), "
        f"{len(result['stale'])} stale baseline entr(ies)"
    )
    if problems or result["stale"] or result["uncommented"]:
        return 1
    print("ok: no new findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
