"""tffm-lint: the repo's own static-analysis suite.

``python -m tools.lint`` runs every analyzer over the package and
exits nonzero on any NEW finding (one not grandfathered by
``tools/lint/baseline.txt``).  See LINTING.md for the rule catalog and
how to add a rule.

Programmatic use (bench preflight, tests)::

    from tools import lint
    result = lint.run(root=".")          # default rules + baseline
    result["new"]                        # findings that would fail CI
"""

from __future__ import annotations

import os

from tools.lint.core import (   # noqa: F401  (public API re-exports)
    Context, Finding, load_baseline, run_rules,
)
from tools.lint.donation import DonationRule
from tools.lint.knobs import KnobsRule
from tools.lint.legacy import ObsMetricsRule, Tier1Rule
from tools.lint.lifecycle import LifecycleRule
from tools.lint.locks import LocksRule
from tools.lint.records import RecordsRule

DEFAULT_BASELINE = "tools/lint/baseline.txt"

ALL_RULES = (
    LifecycleRule, DonationRule, LocksRule, KnobsRule, RecordsRule,
    Tier1Rule, ObsMetricsRule,
)


def default_rules():
    return [cls() for cls in ALL_RULES]


def run(root: str = ".", baseline_path: str = None, rules=None,
        ctx: Context = None) -> dict:
    """One lint pass; returns the run_rules() dict plus ``baseline``."""
    if ctx is None:
        ctx = Context(root)
    if baseline_path is None:
        baseline_path = os.path.join(ctx.root, DEFAULT_BASELINE)
    baseline = load_baseline(baseline_path)
    out = run_rules(rules if rules is not None else default_rules(),
                    ctx, baseline)
    out["baseline"] = baseline
    return out
