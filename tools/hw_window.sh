#!/usr/bin/env bash
# Hardware-window watcher: poll the TPU tunnel; on the first healthy
# probe run the round-5 measurement sequence IN ORDER (VERDICT r4 #1:
# official bench FIRST, sweeps after) and commit the artifacts.
#
# Usage: nohup bash tools/hw_window.sh >/tmp/hw_window.log 2>&1 &
# Probe is a subprocess with a hard timeout: a wedged tunnel must not
# hang the watcher (observed in r4: probe OK, pool gone minutes later).

set -u
cd /root/repo
MARK=/tmp/hw_window_done
PROBE_TIMEOUT=${PROBE_TIMEOUT:-150}
POLL_S=${POLL_S:-60}

probe() {
  timeout "$PROBE_TIMEOUT" python -c "
import jax
d = jax.devices()
assert d and d[0].platform not in ('cpu',), d
print('TUNNEL_OK', d[0].platform, len(d))
" 2>/dev/null | grep -q TUNNEL_OK
}

echo "[hw_window] watcher started $(date -u +%FT%TZ)"
while true; do
  if [ -e "$MARK" ]; then
    echo "[hw_window] already completed; exiting"; exit 0
  fi
  if probe; then
    echo "[hw_window] TUNNEL UP $(date -u +%FT%TZ) — running sequence"
    # 1. Official bench first (watchdog-protected internally).
    python bench.py | tee /tmp/bench_r05_builder.out
    # A tunnel that died between the probe and the bench leaves a CPU
    # fallback line — that window is LOST, not done: resume polling
    # instead of consuming our one shot on a CPU artifact.
    if tail -n 1 /tmp/bench_r05_builder.out | \
        grep -q '"platform": "cpu"'; then
      echo "[hw_window] bench fell back to CPU; window lost — resuming"
      continue
    fi
    # Only commit the artifact if the last line is actual JSON (a hung/
    # failed bench leaves an error string there instead).
    if tail -n 1 /tmp/bench_r05_builder.out | python -c \
        "import json,sys; json.loads(sys.stdin.read())" 2>/dev/null; then
      tail -n 1 /tmp/bench_r05_builder.out > BENCH_r05_builder.json
    else
      echo "[hw_window] bench output not JSON; artifact not written"
    fi
    # 2. Validation sweep → TPU_RESULTS.md (grouping/host_sort/flat/FFM).
    timeout 2400 python tools/tpu_validate.py --sweep-blocks \
      --out TPU_RESULTS.md || echo "[hw_window] tpu_validate failed/timeout"
    # 3. Micro probe → layout decision data.
    timeout 1200 python tools/micro_probe.py \
      > MICRO_PROBE_r05.txt 2>&1 || echo "[hw_window] micro_probe failed"
    touch "$MARK"
    git add -A BENCH_r05_builder.json TPU_RESULTS.md MICRO_PROBE_r05.txt \
      2>/dev/null
    git -c user.name="$(git config user.name)" commit -m \
      "Record round-5 hardware-window measurements" || true
    echo "[hw_window] sequence complete $(date -u +%FT%TZ)"
    exit 0
  fi
  sleep "$POLL_S"
done
