#!/usr/bin/env python
"""Pretty-print / summarize fast_tffm_tpu observability artifacts.

Three modes (see OBSERVABILITY.md):

1. Metrics stream summary (default).  The trainer's ``metrics_file`` is
   self-describing (every record carries a ``record`` type: run_header |
   train | validation | heartbeat | alert | compile | final):

     python tools/report.py /path/to/metrics.jsonl
     python tools/report.py rank0.jsonl rank1.jsonl ...  # fleet merge

   Sections: the run header (config fingerprint, dispatch/ingest mode,
   platform), the train/validation progression, and the end-of-run
   wall-clock attribution — starvation (``ingest_wait_frac``) vs
   dispatch vs other, per-stage timing histograms, per-put/get
   queue-depth histograms, the data-integrity counters, and the
   training-health monitors (grad norm, non-finite steps, embedding
   occupancy).  Multi-host runs write one metrics_file per process,
   tagged with ``rank``; passing several files prints a per-rank
   attribution table plus the full breakdown of the SLOWEST rank.

2. ``--trace``: merge one or more Chrome-trace span files (written by
   ``trace_file`` / ``--trace``; one per rank) into a single
   Perfetto-loadable file (``-o``, default ``<first>.merged.json``) and
   print a critical-path summary: per-stage span totals, and for every
   dispatched super-batch the connected chain read → ring slot → parse
   → deliver → stack → H2D → dispatch with the slowest chains broken
   down segment by segment.

   Rotated trace windows (``trace_rotate_events``; ``trace.0.json,
   trace.1.json, ...``) are re-joined automatically: windows sharing
   one run's clock anchors are concatenated back into a single stream
   before chain reconstruction, so chains that SPAN a rotation
   boundary still connect.  With more than one rank stream, a
   straggler section attributes each chain segment (parse / stack /
   h2d / dispatch) to the slowest rank.

3. ``--compare A B``: ratio-diff two runs — metrics JSONLs or bench
   JSONs (BENCH_rN.json) — and flag regressions beyond ``--threshold``
   (default 5%).  Rates/ratios regress when they FALL; times/fractions
   /losses regress when they RISE.  ``--threshold`` repeats for
   per-key overrides (``--threshold ingest_wait_frac=0.10 --threshold
   default=0.05``), so noisy keys get slack without loosening the whole
   gate.  Alert records (``record: alert``, the watchdog's output)
   contribute ``alerts_total`` / per-rule counts — a run that starts
   alerting is itself a regression.  Exit code 2 when any regression is
   flagged, so the BENCH trajectory check stops being eyeball-only.

4. ``--incident DIR``: human summary of one blackbox forensic bundle
   (``incidents/<ts>_<reason>/``, see OBSERVABILITY.md "Incidents &
   capture"): which rule fired (or what crashed), the breached
   signals' trajectory across the ringed records, the critical path
   from the trace tail, and slowest-rank / slowest-replica
   attribution from the last ringed record.

Dependency-free on purpose: it must run on any box the artifacts land
on, jax or not.
"""

from __future__ import annotations

import argparse
import bisect
import json
import os
import re
import sys
import time


def _classify(rec: dict) -> str:
    """Record type, inferring for legacy streams without `record`."""
    kind = rec.get("record")
    if kind:
        return kind
    if "validation_loss" in rec:
        return "validation"
    if "loss" in rec:
        return "train"
    return "unknown"


def load(path: str) -> dict:
    """Group a JSONL file's records by type (order preserved)."""
    groups: dict = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                print(f"  ! line {lineno}: not JSON, skipped",
                      file=sys.stderr)
                continue
            groups.setdefault(_classify(rec), []).append(rec)
    return groups


def _fmt_rate(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}M"
    if v >= 1e3:
        return f"{v / 1e3:.1f}k"
    return f"{v:.0f}"


def _print_header(header: dict) -> None:
    print("run:")
    for key in (
        "mode", "model_file", "serve_batch_sizes", "max_batch_wait_ms",
        "serve_poll_secs",
        "rank", "config_fingerprint", "steps_per_dispatch", "ingest_mode",
        "fast_ingest", "cache_epochs", "cache_prestacked", "ring_slots",
        "batch_size", "epoch_num",
        "optimizer", "backend", "jax_version", "mesh", "telemetry",
        "resource_metrics",
        "heartbeat_secs", "resume_step", "resume_epoch", "resume_skip",
    ):
        if key in header:
            print(f"  {key:20s} {header[key]}")


def _print_progress(trains: list, valids: list, limit: int) -> None:
    if trains:
        print(f"\ntrain records ({len(trains)}; showing last {limit}):")
        print(f"  {'step':>8} {'examples':>12} {'loss':>9} {'auc':>7} "
              f"{'ex/s':>9}")
        for r in trains[-limit:]:
            print(
                f"  {r.get('step', 0):>8} {r.get('examples', 0):>12.0f} "
                f"{r.get('loss', float('nan')):>9.5f} "
                f"{r.get('auc', float('nan')):>7.4f} "
                f"{_fmt_rate(r.get('examples_per_sec', 0.0)):>9}"
            )
    if valids:
        print(f"\nvalidation records ({len(valids)}; showing last {limit}):")
        for r in valids[-limit:]:
            loss = r.get("validation_loss", r.get("loss", float("nan")))
            auc = r.get("validation_auc", r.get("auc", float("nan")))
            print(f"  step {r.get('step', '?'):>8}  loss {loss:.5f}  "
                  f"auc {auc:.4f}")


def _print_breakdown(rec: dict) -> None:
    kind = rec.get("record", "final")
    wall = max(rec.get("elapsed", 0.0), 1e-9)
    wait = rec.get("wait_input_s", 0.0)
    disp = rec.get("dispatch_s", 0.0)
    other = rec.get("other_s", max(0.0, wall - wait - disp))
    frac = rec.get("ingest_wait_frac", wait / wall)
    if rec.get("exception"):
        print(f"\n  !! run DIED with {rec['exception']}: "
              f"{rec.get('exception_msg', '')}")
    # Serve streams carry no training attribution (no ingest, no
    # dispatch loop) — the serve section below is their breakdown.
    training_rec = "wait_input_s" in rec or "serve" not in rec
    if training_rec:
        print(f"\nwall-clock attribution ({kind} record, step "
              f"{rec.get('step', '?')}, {wall:.1f}s):")
        print(f"  waiting for input   {wait:>9.2f}s  "
              f"({100 * wait / wall:5.1f}%)"
              f"   <- starvation: ingest too slow")
        print(f"  dispatch            {disp:>9.2f}s  "
              f"({100 * disp / wall:5.1f}%)"
              f"   <- enqueue + device backpressure")
        print(f"  other               {other:>9.2f}s  "
              f"({100 * other / wall:5.1f}%)   <- logging/validation/save")
        verdict = (
            "INGEST-BOUND (grow thread_num/parse_processes, or "
            "cache_epochs)"
            if frac > 0.25 else "compute-bound (ingest keeps up)"
        )
        print(f"  ingest_wait_frac    {frac:>9.3f}    -> {verdict}")
    else:
        print(f"\nserving run ({kind} record, checkpoint step "
              f"{rec.get('step', '?')}, {wall:.1f}s up)")
    for key in ("truncated_features", "out_of_range_batches",
                "ingest_cache", "examples_in"):
        if key in rec:
            print(f"  {key:22s} {rec[key]}")
    health = rec.get("health") or {}
    if health:
        print("\ntraining health (scan-carry monitors):")
        for key in ("grad_norm", "grad_norm_rms", "nonfinite_steps",
                    "first_nonfinite_step", "emb_rows_touched",
                    "emb_row_occupancy", "emb_touch_events"):
            if key in health:
                print(f"  {key:22s} {health[key]}")
        if health.get("nonfinite_steps", 0):
            print("  !! non-finite gradients occurred — the model is "
                  "numerically unhealthy (see nan_policy)")
    if rec.get("trace_dropped_events"):
        print(f"\n  !! trace TRUNCATED: {rec['trace_dropped_events']} "
              "event(s) dropped at the buffer cap — chains stop mid-run")
    resource = rec.get("resource")
    if resource:
        print("\nmemory & compile (resource block):")
        for key in ("rss_mb", "peak_rss_mb", "device_bytes_in_use",
                    "device_peak_bytes", "device_bytes_est"):
            if key in resource:
                print(f"  {key:22s} {resource[key]}")
        comps = [
            (k, resource[k]) for k in (
                "ring_bytes", "staging_bytes", "cache_bytes",
                "cold_store_bytes", "trace_buffer_bytes",
            ) if resource.get(k)
        ]
        if comps:
            print("  component host-memory ledger:")
            for name, v in comps:
                print(f"    {name:20s} {v / (1 << 20):10.1f} MiB")
        for key in ("compiles", "compile_s", "recompiles_unexpected",
                    "flops_per_dispatch", "bytes_per_dispatch",
                    "arithmetic_intensity", "model_flops_per_s"):
            if key in resource:
                print(f"  {key:22s} {resource[key]}")
        if resource.get("recompiles_unexpected"):
            print("  !! UNEXPECTED recompile(s) mid-run — the input "
                  "stream changed shape under the trainer (only the "
                  "epoch-tail K' compile is whitelisted)")
    else:
        print("\nmemory & compile: n/a (stream has no resource block — "
              "pre-resource run or resource_metrics=off)")
    serve = rec.get("serve")
    if serve:
        print("\nserving (latency under load):")
        for key in ("requests", "examples", "batches", "qps",
                    "p50_ms", "p95_ms", "p99_ms", "max_ms",
                    "parse_p50_ms", "batch_fill", "swaps", "compiles",
                    "steady_compiles", "recompiles_unexpected",
                    "table_mb", "quant_error_max",
                    "shed", "shed_frac", "replicas",
                    "replicas_healthy", "evictions", "respawns",
                    "replicas_scraped", "fleet_qps", "fleet_p50_ms",
                    "fleet_p99_ms", "fleet_scrape_age_max_s",
                    "slo_bad_frac", "burn_rate"):
            if key in serve:
                print(f"  {key:22s} {serve[key]}")
        if serve.get("steady_compiles"):
            print("  !! compiles happened AFTER warmup — a request "
                  "shape escaped the serve_batch_sizes ladder (a "
                  "multi-second latency cliff on the hot path)")
        if serve.get("burn_rate", 0) > 1:
            print("  !! SLO error budget is burning faster than it "
                  "accrues (burn_rate > 1) — the fleet is out of SLO")
    else:
        print("\nserving: n/a (stream has no serve block — training "
              "run or pre-serve stream)")
    quality = rec.get("quality")
    if quality:
        print("\nquality & drift (model-quality block):")
        for key in ("examples", "window_examples", "logloss", "auc",
                    "score_mean", "label_rate", "calib_ratio",
                    "logloss_drift", "psi_values", "psi_lengths",
                    "psi_ids", "psi_scores", "psi_max",
                    "sketch_examples"):
            if key in quality:
                print(f"  {key:22s} {quality[key]}")
        if quality.get("psi_max", 0.0) > 0.25:
            print("  !! adjacent-window PSI above 0.25 — the input "
                  "distribution SHIFTED mid-run (0.1-0.25 reads as "
                  "drifting, > 0.25 as shifted)")
        calib = quality.get("calib_ratio")
        if calib is not None and not 0.8 <= calib <= 1.25:
            print("  !! calibration ratio far from 1.0 — mean "
                  "predicted rate disagrees with the observed label "
                  "rate")
    else:
        print("\nquality & drift: n/a (stream has no quality block — "
              "pre-quality run or quality=off)")
    tiered = rec.get("tiered") or {}
    if tiered:
        print("\ntiered embedding table (hot/cold migration):")
        for key in ("hot_rows", "vocab", "resident_rows", "rows_seen",
                    "hot_hit_frac", "hit_occurrences", "miss_occurrences",
                    "rows_loaded", "rows_evicted", "writeback_rows",
                    "oor_occurrences", "cold_store_bytes"):
            if key in tiered:
                print(f"  {key:22s} {tiered[key]}")
        if tiered.get("hot_hit_frac", 1.0) < 0.9:
            print("  !! hot-set hit fraction is low — the hot table is "
                  "churning; consider raising hot_rows")
    stages = rec.get("stages") or {}
    timers = stages.get("timers") or {}
    if timers:
        print("\nstage timers:")
        print(f"  {'stage':24} {'count':>8} {'total_s':>9} {'p50_ms':>8} "
              f"{'p95_ms':>8} {'max_ms':>8}")
        for name in sorted(timers):
            t = timers[name]
            print(
                f"  {name:24} {t.get('count', 0):>8} "
                f"{t.get('total_s', 0.0):>9.2f} {t.get('p50_ms', 0.0):>8.2f} "
                f"{t.get('p95_ms', 0.0):>8.2f} {t.get('max_ms', 0.0):>8.2f}"
            )
    gauges = stages.get("gauges") or {}
    if gauges:
        print("\ngauges (at snapshot time):")
        for name in sorted(gauges):
            print(f"  {name:24} {gauges[name]}")
    counters = stages.get("counters") or {}
    if counters:
        print("\ncounters:")
        for name in sorted(counters):
            print(f"  {name:24} {counters[name]}")
    depths = stages.get("depths") or {}
    depths = {k: d for k, d in depths.items() if d.get("count")}
    if depths:
        print("\nqueue depths (per put/get histogram):")
        print(f"  {'queue':24} {'events':>8} {'mean':>6} {'max':>5}  "
              f"occupancy")
        for name in sorted(depths):
            d = depths[name]
            buckets = " ".join(
                f"{k}:{v}" for k, v in (d.get("buckets") or {}).items()
            )
            print(
                f"  {name:24} {d['count']:>8} {d.get('mean', 0):>6} "
                f"{d.get('max', 0):>5}  {buckets}"
            )


def _print_compiles(compiles: list) -> None:
    """Compile-sentinel stream summary: every `record: compile` entry is
    one actual train-step compilation (wall time + XLA cost captured at
    compile time); an unexpected one is the headline."""
    if not compiles:
        return
    total_s = sum(c.get("compile_s", 0.0) for c in compiles)
    bad = [c for c in compiles if not c.get("expected", True)]
    print(f"\ncompiles ({len(compiles)}, {total_s:.2f}s total"
          + (f", {len(bad)} UNEXPECTED" if bad else "") + "):")
    for c in compiles:
        flag = "" if c.get("expected", True) else "  << UNEXPECTED"
        flops = c.get("flops")
        extra = f"  {flops:.3g} flops" if flops else ""
        if c.get("where") == "serve":
            # Serving-ladder compile: identified by rung shape, not a
            # training step.
            print(f"  serve shape {str(c.get('shape', '?')):>10} "
                  f"{c.get('compile_s', 0.0):7.2f}s{flag}")
            continue
        print(f"  step {c.get('step', '?'):>6}  k={c.get('k', '?'):<4} "
              f"{c.get('compile_s', 0.0):7.2f}s{extra}{flag}")


def _print_autotune(entries: list) -> None:
    """Kernel-autotune summary: every `record: autotune` entry is one
    interaction-impl decision (per context) — which impl the run
    actually executed, where the decision came from (pin / cache /
    measurement), and the per-candidate medians when a measurement
    ran.  Streams written before the autotuner existed (or runs with
    a pinned impl, which skip the record) print n/a, not nothing —
    the reader should know the section was consulted."""
    if not entries:
        print("\nautotune: n/a (stream has no autotune records — "
              "pre-autotune run, or interaction_impl was pinned)")
        return
    print(f"\nautotune (interaction-impl decisions, {len(entries)}):")
    for e in entries:
        times = " ".join(
            f"{k}={v}ms"
            for k, v in sorted((e.get("times_ms") or {}).items())
        )
        gated = [
            k for k, v in (e.get("parity_err") or {}).items()
            if k not in (e.get("times_ms") or {})
        ]
        print(f"  {e.get('context', '?'):6} {e.get('impl', '?'):10} "
              f"({e.get('source', '?')}"
              + (f"; {times}" if times else "") + ")")
        if gated:
            print(f"    parity-gated out: {', '.join(sorted(gated))}")


def _print_alerts(alerts: list, limit: int = 8) -> None:
    """Watchdog summary: per-rule fire counts + the most recent
    alerts.  A halt rule is the headline — it is why the run stopped."""
    if not alerts:
        return
    per_rule: dict = {}
    for a in alerts:
        per_rule.setdefault(a.get("rule", "?"), []).append(a)
    n_halt = sum(1 for a in alerts if a.get("action") == "halt")
    print(f"\nalerts ({len(alerts)} fired"
          + (f", {n_halt} HALT" if n_halt else "") + "):")
    print(f"  {'rule':36} {'fires':>6} {'action':>6}  last value")
    for rule in sorted(per_rule):
        rows = per_rule[rule]
        last = rows[-1]
        print(
            f"  {rule:36} {len(rows):>6} {last.get('action', '?'):>6}  "
            f"{last.get('signal')}={last.get('value')} at step "
            f"{last.get('step')}"
        )
    for a in alerts[-limit:]:
        print(
            f"    step {a.get('step', '?'):>6}  {a.get('rule')}: "
            f"{a.get('signal')}={a.get('value')} {a.get('op')} "
            f"{a.get('threshold')} -> {a.get('action')}"
        )


def _stream_rank(groups: dict, fallback: int) -> int:
    headers = groups.get("run_header", [])
    if headers and "rank" in headers[-1]:
        return int(headers[-1]["rank"])
    return fallback


def _merge_ranks(streams: list) -> int:
    """Fleet view over per-rank metrics files: a rank attribution table
    + the slowest rank's full breakdown."""
    rows = []
    for path, groups in streams:
        rank = _stream_rank(groups, len(rows))
        final = (groups.get("final") or groups.get("heartbeat") or [None])
        rows.append((rank, path, groups, final[-1]))
    rows.sort(key=lambda r: r[0])
    print(f"merged {len(rows)} rank streams: "
          f"{', '.join(str(r[0]) for r in rows)}")
    headers = rows[0][2].get("run_header", [])
    if headers:
        _print_header(headers[-1])
        fps = {
            (r[2].get("run_header") or [{}])[-1].get("config_fingerprint")
            for r in rows
        }
        if len(fps) > 1:
            print("  ! config fingerprints DIFFER across ranks:", fps)
    print("\nper-rank attribution:")
    print(f"  {'rank':>4} {'step':>8} {'elapsed':>9} {'wait_frac':>9} "
          f"{'examples_in':>12} {'alerts':>6}  verdict")
    slowest = None
    for rank, path, groups, final in rows:
        if final is None:
            print(f"  {rank:>4} {'?':>8} {'?':>9} {'?':>9} {'?':>12} "
                  f"{'?':>6}  no final/heartbeat record ({path})")
            continue
        frac = final.get("ingest_wait_frac", 0.0)
        verdict = "ingest-bound" if frac > 0.25 else "compute-bound"
        print(
            f"  {rank:>4} {final.get('step', 0):>8} "
            f"{final.get('elapsed', 0.0):>9.1f} {frac:>9.3f} "
            f"{final.get('examples_in', 0):>12} "
            f"{len(groups.get('alert', [])):>6}  {verdict}"
        )
        if slowest is None or frac > slowest[1].get("ingest_wait_frac", 0):
            slowest = (rank, final)
    if slowest is not None:
        print(f"\nslowest rank: {slowest[0]} (the step waits for every "
              f"host — this rank sets the fleet's pace)")
        _print_breakdown(slowest[1])
    return 0


# ---------------------------------------------------------------------------
# --trace: merge Chrome-trace span files + critical-path summary
# ---------------------------------------------------------------------------


def load_trace(path: str) -> tuple[list, dict]:
    """(events, otherData) from one trace file (object or bare-array
    Chrome trace format)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare traceEvents array
        return doc, {}
    return doc.get("traceEvents", []), doc.get("otherData", {})


def merge_traces(paths: list) -> tuple[list, list, list]:
    """Merge per-rank/per-process trace files onto ONE timeline.

    Timestamps are perf_counter µs — already shared across processes of
    one host.  Across hosts each file's ``otherData`` anchors give the
    wall-clock offset; events are shifted onto the wall timeline and
    re-zeroed at the earliest event.  Returns (events, notes,
    per_file) — ``per_file`` entries are ``(path, events, otherData)``
    with the UNSHIFTED original events, for chain reconstruction (which
    is per-rank and only needs intra-file deltas), so a near-cap 250 MB
    trace is parsed once.
    """
    notes = []
    all_events = []
    per_file = []
    for path in paths:
        events, other = load_trace(path)
        per_file.append((path, events, other))
        shift = 0
        if "wall_anchor" in other and "perf_anchor" in other:
            shift = int(
                (other["wall_anchor"] - other["perf_anchor"]) * 1e6
            )
        dropped = other.get("dropped_events", 0)
        if dropped:
            notes.append(f"{path}: {dropped} events were dropped at "
                         "record time (buffer cap)")
        for ev in events:
            if "ts" in ev:
                ev = dict(ev)
                ev["ts"] += shift
            all_events.append(ev)
    tss = [ev["ts"] for ev in all_events if "ts" in ev]
    if tss:
        t0 = min(tss)
        for ev in all_events:
            if "ts" in ev:
                ev["ts"] -= t0
    return all_events, notes, per_file


def group_streams(per_file: list) -> list:
    """Re-join rotated trace windows into per-run streams.

    A rotated tracer (``trace_rotate_events``) dumps one run as
    ``trace.0.json .. trace.N.json``; every window carries the SAME
    clock anchors + pid and its ``window`` index in ``otherData``.
    Windows sharing (pid, wall_anchor, perf_anchor) are one stream —
    concatenated in window order so chains that span a rotation
    boundary reconnect.  Files without a ``window`` key (unrotated
    traces, one per rank) each stay their own stream, preserving the
    per-rank chain contract (sb/seq ids restart per rank).

    Returns ``[(label, events), ...]``.
    """
    singles = []
    windowed: dict = {}
    for path, events, other in per_file:
        if "window" in other:
            key = (
                other.get("pid"),
                other.get("wall_anchor"),
                other.get("perf_anchor"),
            )
            windowed.setdefault(key, []).append(
                (other["window"], path, events)
            )
        else:
            singles.append((path, events))
    streams = list(singles)
    for key in sorted(windowed, key=str):
        wins = sorted(windowed[key], key=lambda w: w[0])
        events: list = []
        for _, _, evs in wins:
            events.extend(evs)
        label = f"{wins[0][1]} (+{len(wins) - 1} window(s))" \
            if len(wins) > 1 else wins[0][1]
        streams.append((label, events))
    return streams


def _straggler_section(stream_chains: list, limit: int = 8) -> None:
    """Slowest-rank attribution per chain segment.

    ``stream_chains`` is ``[(label, chains), ...]`` — one entry per
    rank stream.  For each stream the mean duration of every chain
    segment (parse / stack / h2d / dispatch) and the mean end-to-end
    chain latency are tabulated; the slowest rank per segment is named.
    In a synchronous-update fleet the step waits for every host, so
    the slowest rank per segment is where fleet time actually goes —
    the groundwork for straggler detection (ROADMAP direction 4).
    """
    segs = ("parse", "stack", "h2d", "dispatch")
    rows = []
    for label, chains in stream_chains:
        if not chains:
            continue
        sums = {s: 0.0 for s in segs}
        counts = {s: 0 for s in segs}
        lat = 0.0
        for c in chains:
            lat += c["latency_us"]
            for name, (_, dur) in _chain_segments(c).items():
                sums[name] += dur
                counts[name] += 1
        rows.append({
            "label": label,
            "chains": len(chains),
            "lat_ms": lat / len(chains) / 1e3,
            **{
                s: (sums[s] / counts[s] / 1e3 if counts[s] else 0.0)
                for s in segs
            },
        })
    if len(rows) < 2:
        return
    print("\nstraggler attribution (mean ms per chain segment, "
          "per rank stream):")
    print(f"  {'stream':40} {'chains':>6} "
          + "".join(f"{s:>9}" for s in segs) + f" {'latency':>9}")
    for r in rows[:limit]:
        label = r["label"]
        if len(label) > 40:
            label = "..." + label[-37:]
        print(
            f"  {label:40} {r['chains']:>6} "
            + "".join(f"{r[s]:>9.2f}" for s in segs)
            + f" {r['lat_ms']:>9.2f}"
        )
    for s in segs + ("lat_ms",):
        worst = max(rows, key=lambda r: r[s])
        if worst[s] <= 0:
            continue
        name = "latency" if s == "lat_ms" else s
        print(f"  slowest {name:9}: {worst['label']} "
              f"({worst[s]:.2f} ms mean)")


def trace_chains(events: list) -> list:
    """Reconstruct each dispatched super-batch's span chain.

    Join keys (see obs/trace.py): ``train.dispatch`` and the
    prefetcher's ``prefetch.stack``/``prefetch.h2d`` spans share ``sb``;
    the stack span names its batch range (``batch0``, ``n``);
    ``ingest.deliver`` points bridge ``batch`` -> ``seq`` (one point may
    cover ``n`` batches — a prestacked SuperBatch delivers whole);
    ``seq`` joins ``parse.batch``, ``ring.slot_acquire``, and
    ``read.item``.  Returns one dict per dispatch: {sb, dispatch, stack,
    h2d, batches: [{batch, seq, deliver, parse, read}...], complete,
    latency_us}.

    Contract: ``events`` must come from ONE rank's trace (sb/seq/batch
    ids restart per rank); ``trace_mode`` therefore builds chains per
    input file before merging the timeline.
    """
    by_name: dict = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_name.setdefault(ev.get("name"), []).append(ev)

    def args_index(name, key):
        out = {}
        for ev in by_name.get(name, []):
            a = ev.get("args") or {}
            if key in a:
                out[a[key]] = ev
        return out

    dispatches = args_index("train.dispatch", "sb")
    stacks = args_index("prefetch.stack", "sb")
    h2ds = args_index("prefetch.h2d", "sb")
    parses = args_index("parse.batch", "seq")
    reads = args_index("read.item", "seq")
    delivers = {}
    for ev in by_name.get("ingest.deliver", []):
        a = ev.get("args") or {}
        if "batch" in a:
            # One deliver point covers its whole batch range (n > 1 for
            # prestacked SuperBatches delivered whole).
            for i in range(a["batch"], a["batch"] + a.get("n", 1)):
                delivers[i] = ev
    # ring windows: sorted seq0 list; a batch seq belongs to the last
    # window at or before it (bisect — a near-cap trace can hold 1e5
    # windows x 1e6 batches, so per-batch linear scans would hang the
    # tool on exactly the traces it exists for).  Sort on the seq key
    # only (tuple comparison would fall through to the event dicts on
    # ties).
    rings = sorted(
        (
            (ev.get("args", {}).get("seq"), ev)
            for ev in by_name.get("ring.slot_acquire", [])
            if ev.get("args", {}).get("seq") is not None
        ),
        key=lambda pair: pair[0],
    )
    ring_seqs = [s0 for s0, _ in rings]

    def ring_for(seq):
        i = bisect.bisect_right(ring_seqs, seq)
        return rings[i - 1][1] if i else None

    chains = []
    for sb, disp in sorted(dispatches.items()):
        stack = stacks.get(sb)
        h2d = h2ds.get(sb)
        # Prestacked super-batches have no transfer-stage stack; their
        # h2d span carries the batch range instead.
        rng_ev = stack if stack is not None else h2d
        batches = []
        if rng_ev is not None:
            a = rng_ev.get("args") or {}
            b0, n = a.get("batch0"), a.get("n")
            if b0 is not None and n is not None:
                for b in range(b0, b0 + n):
                    dv = delivers.get(b)
                    seq = (dv.get("args") or {}).get("seq") if dv else None
                    batches.append({
                        "batch": b, "seq": seq, "deliver": dv,
                        "parse": parses.get(seq) if seq is not None
                        else None,
                        "read": reads.get(seq) if seq is not None
                        else None,
                        "ring": ring_for(seq) if seq is not None
                        else None,
                    })
        # A chain is complete when the dispatch connects through h2d to
        # its batch range and every batch connects to a deliver point;
        # parse/read links are required only for batches that name a seq
        # (cached replays legitimately deliver with seq=None — their
        # parse happened in a previous epoch's chain).
        complete = (
            h2d is not None and batches
            and all(b["deliver"] is not None for b in batches)
            and all(
                b["parse"] is not None and b["read"] is not None
                for b in batches if b["seq"] is not None
            )
        )
        starts = [disp["ts"]]
        for b in batches:
            for k in ("read", "parse", "deliver"):
                if b[k] is not None:
                    starts.append(b[k]["ts"])
        if h2d is not None:
            starts.append(h2d["ts"])
        if stack is not None:
            starts.append(stack["ts"])
        chains.append({
            "sb": sb, "dispatch": disp, "stack": stack, "h2d": h2d,
            "batches": batches, "complete": bool(complete),
            "latency_us": disp["ts"] + disp.get("dur", 0) - min(starts),
        })
    return chains


def _chain_segments(chain: dict) -> dict:
    """Stage timing along one chain, for the critical-path breakdown:
    the LAST-finishing batch's read/parse spans, the stack/h2d spans,
    and the dispatch — plus the gaps between them."""
    segs = {}
    last_parse = None
    for b in chain["batches"]:
        if b["parse"] is not None:
            end = b["parse"]["ts"] + b["parse"].get("dur", 0)
            if last_parse is None or end > last_parse["ts"] + \
                    last_parse.get("dur", 0):
                last_parse = b["parse"]
    for name, ev in (
        ("parse", last_parse), ("stack", chain["stack"]),
        ("h2d", chain["h2d"]), ("dispatch", chain["dispatch"]),
    ):
        if ev is not None:
            segs[name] = (ev["ts"], ev.get("dur", 0))
    return segs


# Serve-path request chain: sequential segments (the critical path a
# request walks) in order, plus the router spans that wrap them.
_SERVE_SEGMENTS = ("admit", "queue_wait", "coalesce", "dispatch",
                   "respond")


def serve_request_chains(events: list) -> list:
    """Reconstruct per-request span chains from serving traces.

    Join key: the ``rid`` arg every serve-path span carries
    (``serve.admit`` / ``serve.proxy`` on the router,
    ``serve.queue_wait`` / ``serve.coalesce`` / ``serve.dispatch`` /
    ``serve.respond`` on the replica).  Unlike super-batch chains, rid
    uniqueness is fleet-global (pid + boot time + counter), so chains
    join across ALL files at once.  Returns one dict per rid:
    {rid, replica, spans: {name: ev}, latency_us, complete}.
    """
    by_rid: dict = {}
    routed = False
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if not name.startswith("serve."):
            continue
        rid = (ev.get("args") or {}).get("rid")
        if rid is None:
            continue
        seg = name[len("serve."):]
        if seg in ("admit", "proxy"):
            routed = True
        by_rid.setdefault(rid, {})[seg] = ev
    chains = []
    for rid, spans in by_rid.items():
        starts = [ev["ts"] for ev in spans.values()]
        ends = [ev["ts"] + ev.get("dur", 0) for ev in spans.values()]
        # A shed request legitimately ends at the admit decision; a
        # scored one must carry the full replica chain (and, behind a
        # router, the proxy span).
        decision = (spans.get("admit", {}).get("args") or {}).get(
            "decision", "admit"
        )
        if decision != "admit":
            complete = "admit" in spans
        else:
            need = {"queue_wait", "coalesce", "dispatch", "respond"}
            if routed:
                need |= {"admit", "proxy"}
            complete = need <= set(spans)
        replica = None
        for seg in ("proxy", "dispatch", "admit"):
            a = spans.get(seg, {}).get("args") or {}
            if isinstance(a.get("replica"), int) and a["replica"] >= 0:
                replica = a["replica"]
                break
        chains.append({
            "rid": rid, "replica": replica, "spans": spans,
            "decision": decision,
            "latency_us": max(ends) - min(starts),
            "complete": complete,
        })
    return chains


def serve_trace_mode(paths: list, out: str, limit: int) -> int:
    """``--serve-trace``: per-request critical-path breakdown across
    the router + replica trace family, with slowest-replica
    attribution."""
    events, notes, _per_file = merge_traces(paths)
    if not events:
        print("no trace events")
        return 1
    if out:
        with open(out, "w") as f:
            json.dump(
                {"traceEvents": events, "displayTimeUnit": "ms"}, f
            )
        print(f"merged {len(paths)} file(s), {len(events)} events -> "
              f"{out}")
    for note in notes:
        print(f"  ! {note}")
    chains = serve_request_chains(events)
    if not chains:
        print("no sampled serve requests in this trace "
              "(serve_trace_sample = 0, or a training trace?)")
        return 1
    n_ok = sum(1 for c in chains if c["complete"])
    n_shed = sum(1 for c in chains if c["decision"] != "admit")
    print(f"\nsampled requests: {len(chains)} traced, {n_ok} with a "
          f"complete chain"
          + (f", {n_shed} shed/unrouted" if n_shed else ""))
    if n_ok < len(chains):
        bad = [c["rid"] for c in chains if not c["complete"]][:5]
        print(f"  ! incomplete chains (first 5 rids): {bad}")
        print("    (a SIGKILLed replica's spans die with it — its "
              "requests retried elsewhere keep only the router half)")

    slowest = sorted(chains, key=lambda c: -c["latency_us"])[:limit]
    print(f"\ncritical path — slowest {len(slowest)} request(s) "
          f"(admit -> queue -> coalesce -> dispatch -> respond):")
    for c in slowest:
        parts = []
        prev_end = None
        for seg in _SERVE_SEGMENTS:
            ev = c["spans"].get(seg)
            if ev is None:
                continue
            ts, dur = ev["ts"], ev.get("dur", 0)
            if prev_end is not None and ts > prev_end:
                parts.append(f"(+{(ts - prev_end) / 1e3:.2f} gap)")
            parts.append(f"{seg} {dur / 1e3:.2f}")
            prev_end = ts + dur
        proxy = c["spans"].get("proxy")
        if proxy is not None:
            parts.append(f"| proxy {proxy.get('dur', 0) / 1e3:.2f}")
        rep = f" r{c['replica']}" if c["replica"] is not None else ""
        print(f"  {c['rid'][-14:]:>14}{rep}: "
              f"{c['latency_us'] / 1e3:9.2f} ms  "
              f"[ms: {' -> '.join(parts)}]")

    # Slowest-replica attribution: in a P2C fleet every replica sees
    # comparable traffic, so a replica whose mean dispatch/queue time
    # stands out is where fleet latency actually goes.
    per_rep: dict = {}
    for c in chains:
        if c["replica"] is None or not c["complete"]:
            continue
        row = per_rep.setdefault(
            c["replica"],
            {s: [0.0, 0] for s in _SERVE_SEGMENTS + ("latency",)},
        )
        row["latency"][0] += c["latency_us"]
        row["latency"][1] += 1
        for seg in _SERVE_SEGMENTS:
            ev = c["spans"].get(seg)
            if ev is not None:
                row[seg][0] += ev.get("dur", 0)
                row[seg][1] += 1
    if len(per_rep) >= 2:
        segs = _SERVE_SEGMENTS + ("latency",)
        print("\nslowest-replica attribution (mean ms per segment):")
        print(f"  {'replica':>8} {'chains':>7} "
              + "".join(f"{s:>11}" for s in segs))
        means: dict = {}
        for rep in sorted(per_rep):
            row = per_rep[rep]
            means[rep] = {
                s: (row[s][0] / row[s][1] / 1e3 if row[s][1] else 0.0)
                for s in segs
            }
            print(f"  {rep:>8} {row['latency'][1]:>7} "
                  + "".join(f"{means[rep][s]:>11.2f}" for s in segs))
        for s in segs:
            worst = max(means, key=lambda r: means[r][s])
            if means[worst][s] > 0:
                print(f"  slowest {s:10}: replica {worst} "
                      f"({means[worst][s]:.2f} ms mean)")
    return 0


def trace_mode(paths: list, out: str, limit: int) -> int:
    events, notes, per_file = merge_traces(paths)
    if not events:
        print("no trace events")
        return 1
    out = out or (paths[0] + ".merged.json")
    with open(out, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    print(f"merged {len(paths)} file(s), {len(events)} events -> {out}")
    print("open in https://ui.perfetto.dev (or chrome://tracing)")
    for note in notes:
        print(f"  ! {note}")
    # Chains are reconstructed PER RANK STREAM: sb/seq/batch ids
    # restart per rank, so joining across the merged pool would
    # cross-wire the ranks' super-batches.  Rotated windows of one run
    # (shared clock anchors + a window index) are first re-joined into
    # their stream so chains spanning a rotation boundary reconnect.
    streams = group_streams(per_file)
    if len(streams) < len(per_file):
        print(f"  re-joined {len(per_file)} file(s) into "
              f"{len(streams)} stream(s) (rotated trace windows)")
    stream_chains = [
        (label, trace_chains(evs)) for label, evs in streams
    ]
    chains = []
    for _, cs in stream_chains:
        chains.extend(cs)

    spans: dict = {}
    for ev in events:
        if ev.get("ph") == "X":
            tot, cnt, mx = spans.get(ev["name"], (0, 0, 0))
            d = ev.get("dur", 0)
            spans[ev["name"]] = (tot + d, cnt + 1, max(mx, d))
    print(f"\nstage spans ({sum(c for _, c, _ in spans.values())} total):")
    print(f"  {'span':24} {'count':>7} {'total_ms':>10} {'mean_ms':>9} "
          f"{'max_ms':>9}")
    for name in sorted(spans, key=lambda n: -spans[n][0]):
        tot, cnt, mx = spans[name]
        print(f"  {name:24} {cnt:>7} {tot / 1e3:>10.2f} "
              f"{tot / cnt / 1e3:>9.3f} {mx / 1e3:>9.3f}")

    if not chains:
        print("\nno dispatched super-batches in this trace")
        return 0
    n_ok = sum(1 for c in chains if c["complete"])
    print(f"\nsuper-batch chains: {len(chains)} dispatched, {n_ok} with "
          f"a complete read->parse->deliver->h2d->dispatch chain")
    if n_ok < len(chains):
        bad = [c["sb"] for c in chains if not c["complete"]][:10]
        print(f"  ! incomplete chains (first 10 sb ids): {bad}")
    slowest = sorted(chains, key=lambda c: -c["latency_us"])[:limit]
    print(f"\ncritical path — slowest {len(slowest)} chain(s) "
          f"(end-to-end latency, first event -> dispatch done):")
    for c in slowest:
        segs = _chain_segments(c)
        parts = []
        prev_end = None
        for name in ("parse", "stack", "h2d", "dispatch"):
            if name not in segs:
                continue
            ts, dur = segs[name]
            if prev_end is not None and ts > prev_end:
                parts.append(f"(+{(ts - prev_end) / 1e3:.2f} gap)")
            parts.append(f"{name} {dur / 1e3:.2f}")
            prev_end = ts + dur
        print(f"  sb {c['sb']:>5}: {c['latency_us'] / 1e3:9.2f} ms  "
              f"[ms: {' -> '.join(parts)}]")
    _straggler_section(stream_chains, limit)
    return 0


# ---------------------------------------------------------------------------
# --compare: ratio-diff two runs (metrics JSONLs or bench JSONs)
# ---------------------------------------------------------------------------

# Direction heuristics: which way is a regression?  Rates and hit
# fractions regress when they FALL; times, losses, waits, drops regress
# when they RISE.  Anything unclassified is shown without a flag.
_HIGHER_BETTER = (
    "_per_sec", "_frac", "vs_baseline", "_vs_step_only", "value",
    "examples", "auc", "steps",
)
_LOWER_BETTER = (
    "_ms", "_s", "loss", "logloss", "mse", "ingest_wait_frac",
    "truncated_features", "out_of_range_batches", "nonfinite_steps",
    "elapsed", "dispatch_overhead",
)
# Keys where the heuristic suffixes collide or mislead.
_DIRECTION_OVERRIDES = {
    "ingest_wait_frac": "low", "wait_input_s": "low",
    "telemetry_on_vs_off": None, "trace_overhead": "low",
    "ring_zero_copy_frac": "high", "prestack_hit_frac": "high",
    "h2d_overlap_frac": "high",
    # Tiered table: a FALLING hot-set hit fraction is the regression
    # (the *_frac rise-is-bad heuristic points the wrong way here).
    "tiered.hot_hit_frac": "high",
    "tiered.rows_evicted": None, "tiered.rows_loaded": None,
    "trace_dropped_events": "low",
    # Live observability plane: endpoint overhead is a cost ratio
    # (off/on, like trace_overhead — rising means the endpoint slows
    # training); rotated windows are informational; a run that starts
    # ALERTING regressed even when its rates held.
    "status_endpoint_overhead": "low",
    "trace_windows": None,
    "alerts_total": "low", "alerts_halt": "low",
    # Resource plane (PR 8): memory footprints and compile costs
    # regress when they RISE; sustained device FLOP/s regresses when
    # it FALLS; the resource_overhead probe is a cost ratio like the
    # telemetry/trace/status ones.  Bare spellings gate bench JSONs,
    # `resource.`-prefixed ones the flattened metrics-stream block.
    "peak_rss_mb": "low", "resource.peak_rss_mb": "low",
    "rss_mb": None, "resource.rss_mb": None,
    "compile_s": "low", "resource.compile_s": "low",
    "recompiles_unexpected": "low",
    "resource.recompiles_unexpected": "low",
    "model_flops_per_s": "high", "resource.model_flops_per_s": "high",
    "resource.compiles": None,
    "resource_overhead": "low",
    # Serving path (PR 9): tail latency regresses when it RISES (the
    # _ms suffix already says so; bench keys listed for clarity),
    # throughput and batch fill when they FALL; any compile after
    # warmup is a latency cliff.  Bare spellings gate bench JSONs,
    # `serve.`-prefixed ones the flattened metrics-stream block.
    "serve_p50_ms": "low", "serve_p99_ms": "low",
    "serve_qps": "high", "serve.qps": "high",
    "serve_batch_fill": "high", "serve.batch_fill": "high",
    "serve_steady_compiles": "low", "serve.steady_compiles": "low",
    "serve.recompiles_unexpected": "low",
    "serve.requests": None, "serve.swaps": None, "serve.compiles": None,
    # Quantized tables (PR 11): table bytes regress when they RISE
    # (compactness is the feature), quant error when it RISES (served
    # scores drifting from fp32), and the quantized step-rate fraction
    # (dtype rate / fp32 rate at the bench tiered config) when it
    # FALLS — quantization must buy bytes, not cost throughput.  The
    # per-section _frac/_mb spellings need overrides because the
    # suffix heuristics miss or misread them.
    "serve_table_mb": "low", "serve.table_mb": "low",
    "serve_quant_error_max_int8": "low", "serve.quant_error_max": "low",
    "quant_table_bytes_frac_bf16": "low",
    "quant_table_bytes_frac_int8": "low",
    "quant_step_rate_frac_bf16": "high",
    "quant_step_rate_frac_int8": "high",
    # Scale-out serving (PR 12): router throughput regresses when it
    # FALLS, router tail latency / shed fraction / binary-decode cost
    # when they RISE (shed_frac is measured under the bench's fixed
    # 4x-offered-load burst, so more shedding at the same offered load
    # means less capacity).  The burst p99 is the ADMITTED-request
    # tail under overload — the graceful-degradation number.
    "serve_router_qps": "high", "serve_router_p99_ms": "low",
    "serve_router_p50_ms": "low",
    "serve_shed_frac": "low", "serve.shed_frac": "low",
    "serve_burst_p99_ms": "low", "serve_burst_p99_x": "low",
    "serve_bin_p50_ms": "low", "serve.parse_bin_p50_ms": "low",
    "serve.shed": None, "serve.retries": None,
    "serve.evictions": None, "serve.readmissions": None,
    "serve.inflight": None,
    "serve.canary_promotions": None, "serve.canary_rollbacks": None,
    "serve.replicas": None, "serve.replicas_healthy": None,
    # Fleet observability (ISSUE 14): the SLO burn rate regresses when
    # it RISES (the error budget is burning faster), as do respawns
    # (managed replicas are dying), dropped trace events (the trace
    # lies by omission) and the sampled-tracing overhead ratio (off/on
    # qps, same shape as trace_overhead); fleet_scrape_ms is the
    # router's scrape-sweep cost.  Staleness fluctuates with the
    # scrape cadence — informational, not gated.
    "serve_burn_rate": "low", "serve.burn_rate": "low",
    "serve_respawns": "low", "serve.respawns": "low",
    "serve_trace_dropped": "low",
    "serve_trace_overhead": "low",
    "fleet_scrape_ms": "low",
    "serve_slo_bad_frac": "low", "serve.slo_bad_frac": "low",
    "serve.fleet_scrape_age_max_s": None,
    "serve.slo_good": None, "serve.slo_bad": None,
    # Canary shadow-score distribution keys (serve/router.py writes
    # them as bench-style JSONs): the canary gate flags a DRIFT in
    # EITHER direction — "both" is the two-sided direction compare_mode
    # implements for exactly this.
    "score_mean": "both", "score_std": "both",
    "score_p10": "both", "score_p50": "both", "score_p90": "both",
    "score_n": None,
    # Model quality & drift (ISSUE 15): windowed logloss and every PSI
    # axis regress when they RISE, windowed AUC when it FALLS; the
    # calibration ratio is two-sided like the canary score stats (a
    # systematic over- OR under-prediction is the regression) — so is
    # logloss_drift in principle, but a RISING window loss is the
    # page-worthy direction.  Counts are informational.  Bench keys:
    # quality_overhead is a cost ratio like the other obs probes;
    # quality_psi_identity is the self-skew floor (identity traffic
    # must read ~0, so any rise is a sketch/PSI correctness drift).
    "quality.logloss": "low", "quality.auc": "high",
    "quality.calib_ratio": "both",
    "quality.logloss_drift": "low",
    "quality.psi_values": "low", "quality.psi_lengths": "low",
    "quality.psi_ids": "low", "quality.psi_scores": "low",
    "quality.psi_max": "low",
    "quality.examples": None, "quality.window_examples": None,
    "serve.skew_psi_values": "low", "serve.skew_psi_lengths": "low",
    "serve.skew_psi_ids": "low", "serve.skew_psi_scores": "low",
    "serve.skew_psi_max": "low", "serve.skew_examples": None,
    "quality_overhead": "low", "quality_psi_identity": "low",
    # Static-analysis cleanliness (PR 10): bench preflight runs
    # `python -m tools.lint` and records the NEW-finding count — a PR
    # that introduces one regresses the bench compare like any perf
    # key (0 -> N flags via the inf ratio).  The baselined count is
    # informational: it should only ever burn DOWN, but shrinking it
    # must never flag, so no direction.
    "lint_findings_new": "low", "lint_findings_baselined": None,
    # Serve hot path (ISSUE 16): the text-parse p50 and the vectorized
    # parser's speedup over the legacy per-line loop gate the request
    # hot path (parse time regresses when it RISES, the speedup when
    # it FALLS below ~1).  The pooled-accept toggle keys are
    # informational: which accept model ran, its worker count, and the
    # paired legacy-accept window (pooled_x is box-sensitive on small
    # hosts — the gated axis is serve_qps itself).
    "serve_parse_p50_ms": "low", "serve.parse_p50_ms": "low",
    "serve_parse_vec_speedup": "high",
    "serve_accept_pooled": None, "serve_accept_pooled_x": None,
    "serve_qps_legacy_accept": None, "serve_http_threads": None,
    "serve.parse_scratch_reuse": None,
    "serve.parse_scratch_bytes": None,
    # Kernel autotuner (ISSUE 17): the paired reference/auto step-rate
    # ratio regresses when it RISES (the <= 1.05 overhead budget), and
    # the persistent-compile-cache warm compile regresses when it
    # RISES (a warm replica spawn re-lowering from scratch reads as
    # warm ~= cold).  Cold compile time is box- and XLA-version-bound
    # noise, the hit count and which impl won are informational
    # (kernel_impl is a string, so it never reaches the compare
    # anyway — it shows in the autotune summary section instead).
    "autotune_overhead": "low",
    "compile_s_warm": "low",
    "compile_s_cold": None,
    "compile_cache_hits": None,
    # Concurrent ladder warmup: the serve wall time to ready regresses
    # when it RISES back toward the serial sum; the compile-second sum
    # itself is the same work either way (informational).
    "serve.warmup_wall_s": "low",
    "serve.warmup_compile_s": None,
    # Training-fleet observability (ISSUE 18): straggler ratio / skews
    # / the exchange barrier fraction regress when they RISE (one rank
    # slowing the fleet), as does the paired fleet-scrape overhead
    # ratio (off/on rate, same shape as the other obs cost probes).
    # Which rank is slowest, how many answered, and the scrape
    # staleness (cadence-bound) are informational.
    "fleet.straggler_ratio": "low", "fleet.rank_step_skew": "low",
    "fleet.exchange_frac": "low",
    "fleet.dispatch_skew_ms": "low", "fleet.wait_skew_ms": "low",
    "fleet.dispatch_p99_ms": "low", "fleet.wait_p99_ms": "low",
    "fleet.exchange_p99_ms": "low",
    "fleet.slowest_rank": None, "fleet.slowest_rank_share": None,
    "fleet.ranks_scraped": None, "fleet.scrape_age_max_s": None,
    "fleet.examples_in": None, "fleet.ingest_wait_frac": "low",
    "fleet_scrape_overhead": "low",
    # Rank-sharded tiering + overlapped exchange (ISSUE 19): the
    # synchronous exchange window fraction and its overlapped
    # counterpart regress when they RISE (overlap stops hiding the
    # merge); the per-rank device-bytes fraction vs the host-global
    # baseline regresses when it RISES back toward 1.0 (sharding
    # stopped shedding table+optimizer memory); the sharded step rate
    # is a plain throughput axis.  The geometry echoes (shards, the
    # off-run rate) are informational.
    "fleet_exchange_frac": "low",
    "fleet_exchange_overlap_frac": "low",
    "fleet_shard_bytes_frac": "low",
    "fleet_cold_bytes_frac": "low",
    "fleet_sharded_examples_per_sec": "high",
    "fleet_global_examples_per_sec": None,
    "fleet_tier_shards": None,
    # Bench preflight (--timeline over the BENCH_r*.json stack): any
    # key whose trend already crossed its threshold counts here — a
    # new one appearing is itself a regression signal.
    "timeline_regressions": "low",
    # Incident flight recorder (ISSUE 20): the traffic-capture cost
    # ratio (off/on qps, same paired shape as the trace/quality/fleet
    # probes) regresses when it RISES past the 1.05 budget; how many
    # requests the capture window recorded and how many bundles a run
    # dumped are informational (a run that ALERTS more already flags
    # via alerts_total).
    "capture_overhead": "low",
    "capture_requests": None,
    "serve.capture_requests": None,
    "obs.incidents": None,
}


def _direction(key: str):
    if key in _DIRECTION_OVERRIDES:
        return _DIRECTION_OVERRIDES[key]
    # Watchdog per-rule fire counts (alert.<rule-name>): more fires of
    # any rule is the regression, whatever signal the rule watches.
    if key.startswith("alert."):
        return "low"
    for suffix in _LOWER_BETTER:
        if key.endswith(suffix) or key == suffix:
            return "low"
    for suffix in _HIGHER_BETTER:
        if key.endswith(suffix) or key == suffix:
            return "high"
    return None


def _comparable_metrics(path: str) -> dict:
    """Flatten one artifact into {key: number}.

    Bench JSONs (one object with a ``metric`` key, e.g. BENCH_rN.json)
    contribute their numeric top-level keys.  Metrics JSONLs contribute
    the final record's attribution + health and the last train record's
    rate/loss/auc.
    """
    with open(path) as f:
        first = f.readline()
        rest = f.read()
    try:
        doc = json.loads(first + rest)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "metric" in doc:  # bench JSON
        return {
            k: float(v) for k, v in doc.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
    groups = load(path)
    out: dict = {}
    final = (groups.get("final") or groups.get("heartbeat") or [{}])[-1]
    for key in ("elapsed", "wait_input_s", "dispatch_s", "other_s",
                "ingest_wait_frac", "truncated_features",
                "out_of_range_batches", "examples_in", "step"):
        if key in final:
            out[key] = float(final[key])
    for key, val in (final.get("health") or {}).items():
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"health.{key}"] = float(val)
    for key in ("hot_hit_frac", "rows_evicted", "rows_loaded"):
        val = (final.get("tiered") or {}).get(key)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"tiered.{key}"] = float(val)
    # Resource block (PR 8): gate the memory/compile axes.  Streams
    # WITHOUT the block (pre-resource runs, resource_metrics=off)
    # simply contribute no resource.* keys — --compare works on the
    # shared set, so old baselines never KeyError.
    for key in ("peak_rss_mb", "rss_mb", "compile_s", "compiles",
                "recompiles_unexpected", "model_flops_per_s"):
        val = (final.get("resource") or {}).get(key)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"resource.{key}"] = float(val)
    # Serving block (PR 9): latency/throughput axes of a serve stream.
    # Training streams carry no serve block and contribute no serve.*
    # keys — same shared-set back-compat as the resource block.
    for key in ("qps", "p50_ms", "p95_ms", "p99_ms", "batch_fill",
                "requests", "swaps", "compiles", "steady_compiles",
                "recompiles_unexpected", "shed", "shed_frac",
                "burn_rate", "slo_bad_frac", "respawns", "evictions",
                "retries", "warmup_wall_s", "warmup_compile_s"):
        val = (final.get("serve") or {}).get(key)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"serve.{key}"] = float(val)
    # Quality block (ISSUE 15): the model-quality/drift axes.  Streams
    # without the block (pre-quality runs, quality=off) contribute no
    # quality.* keys — same shared-set back-compat as resource/serve.
    for key in ("logloss", "auc", "calib_ratio", "logloss_drift",
                "psi_values", "psi_lengths", "psi_ids", "psi_scores",
                "psi_max", "examples", "window_examples"):
        val = (final.get("quality") or {}).get(key)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"quality.{key}"] = float(val)
    # Training-fleet block (ISSUE 18): rank 0's merged cross-rank view
    # plus the straggler attribution.  Single-process streams carry no
    # fleet block and contribute no fleet.* keys — the shared-set
    # back-compat every block follows.
    for key, val in (final.get("fleet") or {}).items():
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"fleet.{key}"] = float(val)
    # Serving skew keys live inside the serve block (skew_*).
    for key in ("skew_psi_values", "skew_psi_lengths", "skew_psi_ids",
                "skew_psi_scores", "skew_psi_max", "skew_examples"):
        val = (final.get("serve") or {}).get(key)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[f"serve.{key}"] = float(val)
    if "trace_dropped_events" in final:
        out["trace_dropped_events"] = float(final["trace_dropped_events"])
    # Watchdog output: total fires, halts, and per-rule counts — all
    # present (0) whenever the stream has records at all, so a run that
    # STARTS alerting flags against a clean baseline (a key missing
    # from one side would silently drop out of the comparison).
    alerts = groups.get("alert", [])
    out["alerts_total"] = float(len(alerts))
    out["alerts_halt"] = float(
        sum(1 for a in alerts if a.get("action") == "halt")
    )
    for a in alerts:
        key = f"alert.{a.get('rule', '?')}"
        out[key] = out.get(key, 0.0) + 1.0
    if final.get("elapsed") and final.get("examples_in"):
        out["examples_in_per_sec"] = (
            final["examples_in"] / final["elapsed"]
        )
    trains = groups.get("train") or []
    if trains:
        last = trains[-1]
        for key in ("examples_per_sec", "loss", "auc"):
            if key in last:
                out[f"train.{key}"] = float(last[key])
    valids = groups.get("validation") or []
    if valids:
        last = valids[-1]
        for key in ("loss", "auc"):
            if key in last:
                out[f"validation.{key}"] = float(last[key])
    return out


def parse_thresholds(values) -> dict:
    """``--threshold`` values -> {key_or_"default": fraction}.

    Accepted forms (repeatable, later wins): a bare float (``0.07`` —
    sets the default, the historical spelling), ``default=0.05``, and
    per-key overrides (``ingest_wait_frac=0.10``).  The watchdog and
    the bench gates share one regression vocabulary this way: the same
    key names that appear in ``--compare`` output key the overrides.
    """
    out = {"default": 0.05}
    for raw in values or []:
        raw = raw.strip()
        if "=" in raw:
            key, _, val = raw.partition("=")
            key = key.strip()
        else:
            key, val = "default", raw
        try:
            out[key] = float(val)
        except ValueError:
            raise SystemExit(
                f"--threshold {raw!r}: expected FLOAT or KEY=FLOAT"
            ) from None
    return out


def compare_mode(path_a: str, path_b: str, thresholds: dict) -> int:
    a, b = _comparable_metrics(path_a), _comparable_metrics(path_b)
    shared = sorted(set(a) & set(b))
    if not shared:
        print("no comparable numeric keys shared by the two files")
        return 1
    default = thresholds.get("default", 0.05)
    overrides = {k: v for k, v in thresholds.items() if k != "default"}
    print(f"comparing A={path_a}  ->  B={path_b} "
          f"(flag threshold {default:.0%}"
          + (f", {len(overrides)} per-key override(s)" if overrides
             else "") + ")")
    print(f"  {'key':40} {'A':>12} {'B':>12} {'B/A':>8}  flag")
    regressions = []
    for key in shared:
        va, vb = a[key], b[key]
        if va == 0 and vb == 0:
            continue
        ratio = vb / va if va else float("inf")
        direction = _direction(key)
        threshold = thresholds.get(key, default)
        flag = ""
        if direction == "high" and ratio < 1 - threshold:
            flag = "REGRESSION"
        elif direction == "low" and ratio > 1 + threshold:
            flag = "REGRESSION"
        elif direction == "both" and not (
            1 - threshold <= ratio <= 1 + threshold
        ):
            # Two-sided keys (canary score distributions): movement in
            # EITHER direction is the regression — there is no
            # "improved" side to a score drift.
            flag = "REGRESSION"
        elif direction == "high" and ratio > 1 + threshold:
            flag = "improved"
        elif direction == "low" and ratio < 1 - threshold:
            flag = "improved"
        if flag and key in thresholds:
            flag += f" (thr {threshold:g})"
        if flag.startswith("REGRESSION"):
            regressions.append(key)
        rs = f"{ratio:8.3f}" if ratio != float("inf") else "     inf"
        print(f"  {key:40} {va:>12.4g} {vb:>12.4g} {rs}  {flag}")
    if regressions:
        print(f"\n{len(regressions)} REGRESSION(s): "
              f"{', '.join(regressions)}")
        return 2
    print("\nno regressions beyond threshold")
    return 0


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(vals: list) -> str:
    lo, hi = min(vals), max(vals)
    if hi == lo:
        return _SPARK_BLOCKS[3] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK_BLOCKS[
            min(len(_SPARK_BLOCKS) - 1,
                int((v - lo) / span * len(_SPARK_BLOCKS)))
        ]
        for v in vals
    )


def _bench_order(path: str):
    """Sort key putting BENCH_r2 before BENCH_r10 (numeric round when
    the name carries one, lexical otherwise)."""
    m = re.search(r"_r(\d+)\D*\.json$", os.path.basename(path))
    return (0, int(m.group(1)), path) if m else (1, 0, path)


def _timeline_series(paths: list, log=None) -> tuple:
    """Load a bench-JSON stack into ``(labels, {key: [(label, val),
    ...]})`` — numeric top-level keys only, unreadable/stub rounds
    skipped (``log`` gets one line per skip when provided)."""
    series: dict = {}
    labels = []
    for path in sorted(paths, key=_bench_order):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            if log:
                log(f"{path}: unreadable ({e}); skipped")
            continue
        if not isinstance(doc, dict) or "metric" not in doc:
            # Harness stubs from rounds where the bench never ran
            # (rc!=0 wrappers) carry no metric keys — skip, don't
            # fake a flat round.
            if log:
                log(f"{os.path.basename(path)}: no bench metrics; "
                    f"skipped")
            continue
        label = os.path.basename(path)
        labels.append(label)
        for key, val in doc.items():
            if isinstance(val, (int, float)) and not isinstance(
                val, bool
            ):
                series.setdefault(key, []).append((label, float(val)))
    return labels, series


def timeline_regressions(paths: list, thresholds: dict = None) -> dict:
    """Machine-readable first-regression attribution over a bench-JSON
    stack — the same adjacent-step rule ``--timeline`` prints, for
    callers that gate on it (bench.py preflight records the count).
    Returns ``{"rounds": N, "regressions": {key: "rA -> rB (1.23x)"}}``
    (empty regressions when fewer than two readable rounds)."""
    thresholds = thresholds or {}
    default = thresholds.get("default", 0.05)
    labels, series = _timeline_series(paths)
    out: dict = {"rounds": len(labels), "regressions": {}}
    if len(labels) < 2:
        return out
    for key in sorted(series):
        points = series[key]
        if len(points) < 2:
            continue
        direction = _direction(key)
        threshold = thresholds.get(key, default)
        for (lab_a, va), (lab_b, vb) in zip(points, points[1:]):
            if va == 0 and vb == 0:
                continue
            ratio = vb / va if va else float("inf")
            if (
                (direction == "low" and ratio > 1 + threshold)
                or (direction == "high" and ratio < 1 - threshold)
                or (direction == "both" and not (
                    1 - threshold <= ratio <= 1 + threshold))
            ):
                rs = (f"{ratio:.2f}x" if ratio != float("inf")
                      else "inf")
                out["regressions"][key] = f"{lab_a} -> {lab_b} ({rs})"
                break
    return out


def timeline_mode(paths: list, thresholds: dict) -> int:
    """Trend view over a stack of bench JSONs (BENCH_rN.json): one
    sparkline row per shared key plus first-regression attribution —
    the earliest round whose step beyond ``--threshold`` moved in the
    regressing direction for that key (same direction vocabulary as
    ``--compare``).  Informational: always exits 0."""
    default = thresholds.get("default", 0.05)
    labels, series = _timeline_series(paths, log=print)
    if len(labels) < 2:
        print("--timeline needs at least two readable bench JSONs")
        return 1
    culprits = timeline_regressions(paths, thresholds)["regressions"]
    print(f"timeline over {len(labels)} rounds: "
          f"{labels[0]} .. {labels[-1]} "
          f"(step threshold {default:.0%})")
    print(f"  {'key':34} {'trend':>{max(5, len(labels))}} "
          f"{'first':>10} {'last':>10} {'l/f':>7}  first regression")
    for key in sorted(series):
        points = series[key]
        if len(points) < 2:
            continue
        vals = [v for _lab, v in points]
        # First-regression attribution: the earliest adjacent step
        # whose ratio moved beyond the threshold the WRONG way
        # (timeline_regressions is the single rule source).
        culprit = culprits.get(key, "")
        lf = vals[-1] / vals[0] if vals[0] else float("inf")
        lfs = f"{lf:7.3f}" if lf != float("inf") else "    inf"
        print(f"  {key:34} {_sparkline(vals):>{max(5, len(labels))}} "
              f"{vals[0]:>10.4g} {vals[-1]:>10.4g} {lfs}  {culprit}")
    return 0


def _dig_numeric(rec: dict, dotted: str):
    """Resolve a dotted signal path (``serve.qps``) against one
    record; bare spellings fall back to the standard blocks the alert
    aliases resolve into.  Returns a float or None."""

    def walk(cur, parts):
        for part in parts:
            if not isinstance(cur, dict) or part not in cur:
                return None
            cur = cur[part]
        return cur

    val = walk(rec, dotted.split("."))
    if val is None and "." not in dotted:
        for block in ("resource", "serve", "health", "fleet",
                      "tiered", "quality"):
            val = walk(rec, [block, dotted])
            if val is not None:
                break
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        return None
    return float(val)


def incident_mode(path: str, limit: int = 8) -> int:
    """Render one blackbox bundle (``incidents/<ts>_<reason>/``) as a
    human incident summary.  Informational: exits 1 only when the
    manifest itself is unreadable."""
    man_path = os.path.join(path, "manifest.json")
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        print(f"{man_path}: unreadable incident manifest ({e})")
        return 1

    def _jsonl(name: str) -> list:
        rows = []
        try:
            with open(os.path.join(path, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            rows.append(json.loads(line))
                        except ValueError:
                            pass
        except OSError:
            pass
        return rows

    records = _jsonl("records.jsonl")
    alerts = _jsonl("alerts.jsonl")
    when = manifest.get("time")
    stamp = (
        time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime(when))
        if isinstance(when, (int, float)) else "?"
    )
    landed = sorted(
        name for name, ok in (manifest.get("files") or {}).items() if ok
    )
    print(f"incident: {manifest.get('reason', '?')}  ({stamp})")
    print(f"  bundle:  {path}")
    print(f"  process: {manifest.get('suffix') or '-'}")
    print(f"  rings:   {len(records)} record(s), {len(alerts)} "
          f"alert(s); artifacts: {', '.join(landed) or 'none'}")

    if alerts:
        print(f"\nalerts (last {min(len(alerts), limit)} of "
              f"{len(alerts)}):")
        for a in alerts[-limit:]:
            print(
                f"  {a.get('rule', '?'):30} action={a.get('action', '?')}"
                f"  value={a.get('value', '?')} (threshold "
                f"{a.get('op', '?')} {a.get('threshold', '?')}, "
                f"step {a.get('step', '?')})"
            )

    # Signal trajectory: the breached signals first, then the standard
    # page-one vitals, each sparklined across the ringed records.
    signals = []
    for a in alerts:
        sig = a.get("signal")
        if sig and sig not in signals:
            signals.append(sig)
    for sig in ("serve.qps", "serve.p99_ms", "ingest_wait_frac",
                "resource.rss_mb", "resource.open_fds", "step"):
        if sig not in signals:
            signals.append(sig)
    rows = []
    for sig in signals:
        vals = [v for v in (_dig_numeric(r, sig) for r in records)
                if v is not None]
        if len(vals) >= 2:
            rows.append((sig, vals))
    if rows:
        print("\nsignal trajectory (oldest -> newest):")
        for sig, vals in rows:
            print(f"  {sig:28} {_sparkline(vals)}  "
                  f"{vals[0]:.4g} -> {vals[-1]:.4g}")

    # Critical path from the trace-buffer tail: the longest complete
    # spans right before the dump.
    trace_path = os.path.join(path, "trace_tail.json")
    if os.path.exists(trace_path):
        try:
            with open(trace_path) as f:
                events = (json.load(f) or {}).get("traceEvents") or []
        except (OSError, ValueError):
            events = []
        spans = [
            e for e in events
            if isinstance(e, dict) and e.get("ph") == "X"
            and isinstance(e.get("dur"), (int, float))
        ]
        spans.sort(key=lambda e: e["dur"], reverse=True)
        if spans:
            print(f"\ntrace tail critical path (top "
                  f"{min(len(spans), limit)} of {len(spans)} spans):")
            for e in spans[:limit]:
                print(f"  {e.get('name', '?'):32} "
                      f"{e['dur'] / 1e3:10.3f} ms")

    # Who was slowest when the incident fired: the trainer's fleet
    # block or the router's per-replica scrape detail, whichever the
    # last ringed record carries.
    last = records[-1] if records else {}
    fleet = last.get("fleet")
    if isinstance(fleet, dict) and fleet:
        keys = [k for k in ("slowest_rank", "slowest_rank_share",
                            "straggler_ratio", "rank_step_skew",
                            "dispatch_skew_ms", "wait_skew_ms",
                            "ranks_scraped") if k in fleet]
        if keys:
            print("\nfleet attribution (last record):")
            for k in keys:
                print(f"  {k:24} {fleet[k]}")
    per = (last.get("serve") or {}).get("per_replica")
    if isinstance(per, list) and per:
        slowest = max(
            (p for p in per if isinstance(p.get("p99_ms"), (int, float))),
            key=lambda p: p["p99_ms"], default=None,
        )
        print("\nreplica attribution (last record):")
        for p in per:
            mark = (" <- slowest" if slowest is not None
                    and p is slowest else "")
            print(
                f"  replica {p.get('index', '?')}: "
                f"healthy={p.get('healthy', '?')} "
                f"inflight={p.get('inflight', '?')} "
                f"p99_ms={p.get('p99_ms', 'n/a')}{mark}"
            )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize fast_tffm_tpu metrics JSONLs, merge "
                    "trace files, or ratio-diff two runs"
    )
    ap.add_argument("paths", nargs="+",
                    help="metrics_file JSONL(s) (one per rank to merge "
                         "a fleet); trace JSON files with --trace; "
                         "exactly two artifacts with --compare")
    ap.add_argument("--limit", type=int, default=8,
                    help="train/validation rows (or slowest chains) to "
                         "show (default 8)")
    ap.add_argument("--trace", action="store_true",
                    help="treat paths as Chrome-trace span files: merge "
                         "onto one timeline and print the critical-path "
                         "summary")
    ap.add_argument("--serve-trace", action="store_true",
                    dest="serve_trace",
                    help="treat paths as SERVING trace files (router + "
                         "trace_file.replicaN family): per-request "
                         "critical-path breakdown (admit -> queue -> "
                         "coalesce -> dispatch -> respond) with "
                         "slowest-replica attribution")
    ap.add_argument("-o", "--out", default=None,
                    help="--trace: merged trace output path (default "
                         "<first>.merged.json)")
    ap.add_argument("--compare", action="store_true",
                    help="ratio-diff exactly two runs (metrics JSONLs "
                         "or bench JSONs); exit 2 on regression")
    ap.add_argument("--timeline", action="store_true",
                    help="trend view over a stack of bench JSONs "
                         "(BENCH_r*.json): per-key sparkline + "
                         "first-regression attribution using the "
                         "--compare direction vocabulary")
    ap.add_argument("--incident", action="store_true",
                    help="treat the single path as a blackbox incident "
                         "bundle dir (incidents/<ts>_<reason>/): print "
                         "the rule fired, signal trajectories, the "
                         "trace-tail critical path, and slowest rank/"
                         "replica attribution")
    ap.add_argument("--threshold", action="append", default=None,
                    metavar="FLOAT|KEY=FLOAT",
                    help="--compare: regression flag threshold "
                         "(default 0.05 = 5%%); repeat for per-key "
                         "overrides, e.g. --threshold "
                         "ingest_wait_frac=0.10 --threshold "
                         "default=0.05")
    args = ap.parse_args(argv)
    if args.incident:
        if len(args.paths) != 1:
            ap.error("--incident takes exactly one bundle directory")
        return incident_mode(args.paths[0], args.limit)
    if args.serve_trace:
        return serve_trace_mode(args.paths, args.out, args.limit)
    if args.trace:
        return trace_mode(args.paths, args.out, args.limit)
    if args.timeline:
        return timeline_mode(
            args.paths, parse_thresholds(args.threshold)
        )
    if args.compare:
        if len(args.paths) != 2:
            ap.error("--compare takes exactly two paths")
        return compare_mode(
            args.paths[0], args.paths[1],
            parse_thresholds(args.threshold),
        )
    streams = []
    for path in args.paths:
        groups = load(path)
        if groups:
            streams.append((path, groups))
        else:
            print(f"{path}: no records")
    if not streams:
        return 1
    if len(streams) > 1:
        return _merge_ranks(streams)
    groups = streams[0][1]
    headers = groups.get("run_header", [])
    if headers:
        _print_header(headers[-1])
    _print_progress(
        groups.get("train", []), groups.get("validation", []), args.limit
    )
    _print_alerts(groups.get("alert", []), args.limit)
    _print_compiles(groups.get("compile", []))
    _print_autotune(groups.get("autotune", []))
    # The final record is the exact end-of-run report; fall back to the
    # last heartbeat for a run that died mid-flight (that's the point of
    # heartbeats: the stream still says where the time went).
    final = groups.get("final") or groups.get("heartbeat")
    if final:
        _print_breakdown(final[-1])
        hbs = groups.get("heartbeat", [])
        if hbs:
            print(f"\nheartbeats: {len(hbs)} "
                  f"(last at elapsed {hbs[-1].get('elapsed', 0.0):.1f}s)")
    else:
        print("\nno heartbeat/final records (pre-telemetry stream or "
              "heartbeat_secs=0 and the run died before the final record)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
