#!/usr/bin/env python
"""Pretty-print / summarize a telemetry+metrics JSONL stream.

The trainer's ``metrics_file`` is self-describing (every record carries
a ``record`` type: run_header | train | validation | heartbeat | final);
this tool turns one file into a human summary:

  python tools/report.py /path/to/metrics.jsonl
  python tools/report.py rank0.jsonl rank1.jsonl ...   # multi-host merge

Sections: the run header (config fingerprint, dispatch/ingest mode,
platform), the train/validation progression, and the end-of-run
wall-clock attribution — starvation (``ingest_wait_frac``) vs dispatch
vs other, per-stage timing histograms, per-put/get queue-depth
histograms, and the data-integrity counters (truncated features,
out-of-range-id batches, cache outcome).  Records from pre-telemetry
runs (no ``record`` field) are classified by their keys, so old files
still summarize.

Multi-host runs write one metrics_file per process, each tagged with
its ``rank`` (jax.process_index) in the run header; passing several
files merges them into one fleet view — a per-rank attribution table
plus the full breakdown of the SLOWEST rank (the step waits for every
host, so the fleet bottleneck is whichever rank starves hardest).

Dependency-free on purpose: it must run on any box the JSONL lands on,
jax or not.
"""

from __future__ import annotations

import argparse
import json
import sys


def _classify(rec: dict) -> str:
    """Record type, inferring for legacy streams without `record`."""
    kind = rec.get("record")
    if kind:
        return kind
    if "validation_loss" in rec:
        return "validation"
    if "loss" in rec:
        return "train"
    return "unknown"


def load(path: str) -> dict:
    """Group a JSONL file's records by type (order preserved)."""
    groups: dict = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                print(f"  ! line {lineno}: not JSON, skipped",
                      file=sys.stderr)
                continue
            groups.setdefault(_classify(rec), []).append(rec)
    return groups


def _fmt_rate(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}M"
    if v >= 1e3:
        return f"{v / 1e3:.1f}k"
    return f"{v:.0f}"


def _print_header(header: dict) -> None:
    print("run:")
    for key in (
        "rank", "config_fingerprint", "steps_per_dispatch", "ingest_mode",
        "fast_ingest", "cache_epochs", "cache_prestacked", "ring_slots",
        "batch_size", "epoch_num",
        "optimizer", "backend", "jax_version", "mesh", "telemetry",
        "heartbeat_secs", "resume_step", "resume_epoch", "resume_skip",
    ):
        if key in header:
            print(f"  {key:20s} {header[key]}")


def _print_progress(trains: list, valids: list, limit: int) -> None:
    if trains:
        print(f"\ntrain records ({len(trains)}; showing last {limit}):")
        print(f"  {'step':>8} {'examples':>12} {'loss':>9} {'auc':>7} "
              f"{'ex/s':>9}")
        for r in trains[-limit:]:
            print(
                f"  {r.get('step', 0):>8} {r.get('examples', 0):>12.0f} "
                f"{r.get('loss', float('nan')):>9.5f} "
                f"{r.get('auc', float('nan')):>7.4f} "
                f"{_fmt_rate(r.get('examples_per_sec', 0.0)):>9}"
            )
    if valids:
        print(f"\nvalidation records ({len(valids)}; showing last {limit}):")
        for r in valids[-limit:]:
            loss = r.get("validation_loss", r.get("loss", float("nan")))
            auc = r.get("validation_auc", r.get("auc", float("nan")))
            print(f"  step {r.get('step', '?'):>8}  loss {loss:.5f}  "
                  f"auc {auc:.4f}")


def _print_breakdown(rec: dict) -> None:
    kind = rec.get("record", "final")
    wall = max(rec.get("elapsed", 0.0), 1e-9)
    wait = rec.get("wait_input_s", 0.0)
    disp = rec.get("dispatch_s", 0.0)
    other = rec.get("other_s", max(0.0, wall - wait - disp))
    frac = rec.get("ingest_wait_frac", wait / wall)
    print(f"\nwall-clock attribution ({kind} record, step "
          f"{rec.get('step', '?')}, {wall:.1f}s):")
    print(f"  waiting for input   {wait:>9.2f}s  ({100 * wait / wall:5.1f}%)"
          f"   <- starvation: ingest too slow")
    print(f"  dispatch            {disp:>9.2f}s  ({100 * disp / wall:5.1f}%)"
          f"   <- enqueue + device backpressure")
    print(f"  other               {other:>9.2f}s  "
          f"({100 * other / wall:5.1f}%)   <- logging/validation/save")
    verdict = (
        "INGEST-BOUND (grow thread_num/parse_processes, or cache_epochs)"
        if frac > 0.25 else "compute-bound (ingest keeps up)"
    )
    print(f"  ingest_wait_frac    {frac:>9.3f}    -> {verdict}")
    for key in ("truncated_features", "out_of_range_batches",
                "ingest_cache", "examples_in"):
        if key in rec:
            print(f"  {key:22s} {rec[key]}")
    stages = rec.get("stages") or {}
    timers = stages.get("timers") or {}
    if timers:
        print("\nstage timers:")
        print(f"  {'stage':24} {'count':>8} {'total_s':>9} {'p50_ms':>8} "
              f"{'p95_ms':>8} {'max_ms':>8}")
        for name in sorted(timers):
            t = timers[name]
            print(
                f"  {name:24} {t.get('count', 0):>8} "
                f"{t.get('total_s', 0.0):>9.2f} {t.get('p50_ms', 0.0):>8.2f} "
                f"{t.get('p95_ms', 0.0):>8.2f} {t.get('max_ms', 0.0):>8.2f}"
            )
    gauges = stages.get("gauges") or {}
    if gauges:
        print("\ngauges (at snapshot time):")
        for name in sorted(gauges):
            print(f"  {name:24} {gauges[name]}")
    counters = stages.get("counters") or {}
    if counters:
        print("\ncounters:")
        for name in sorted(counters):
            print(f"  {name:24} {counters[name]}")
    depths = stages.get("depths") or {}
    depths = {k: d for k, d in depths.items() if d.get("count")}
    if depths:
        print("\nqueue depths (per put/get histogram):")
        print(f"  {'queue':24} {'events':>8} {'mean':>6} {'max':>5}  "
              f"occupancy")
        for name in sorted(depths):
            d = depths[name]
            buckets = " ".join(
                f"{k}:{v}" for k, v in (d.get("buckets") or {}).items()
            )
            print(
                f"  {name:24} {d['count']:>8} {d.get('mean', 0):>6} "
                f"{d.get('max', 0):>5}  {buckets}"
            )


def _stream_rank(groups: dict, fallback: int) -> int:
    headers = groups.get("run_header", [])
    if headers and "rank" in headers[-1]:
        return int(headers[-1]["rank"])
    return fallback


def _merge_ranks(streams: list) -> int:
    """Fleet view over per-rank metrics files: a rank attribution table
    + the slowest rank's full breakdown."""
    rows = []
    for path, groups in streams:
        rank = _stream_rank(groups, len(rows))
        final = (groups.get("final") or groups.get("heartbeat") or [None])
        rows.append((rank, path, groups, final[-1]))
    rows.sort(key=lambda r: r[0])
    print(f"merged {len(rows)} rank streams: "
          f"{', '.join(str(r[0]) for r in rows)}")
    headers = rows[0][2].get("run_header", [])
    if headers:
        _print_header(headers[-1])
        fps = {
            (r[2].get("run_header") or [{}])[-1].get("config_fingerprint")
            for r in rows
        }
        if len(fps) > 1:
            print("  ! config fingerprints DIFFER across ranks:", fps)
    print("\nper-rank attribution:")
    print(f"  {'rank':>4} {'step':>8} {'elapsed':>9} {'wait_frac':>9} "
          f"{'examples_in':>12}  verdict")
    slowest = None
    for rank, path, groups, final in rows:
        if final is None:
            print(f"  {rank:>4} {'?':>8} {'?':>9} {'?':>9} {'?':>12}  "
                  f"no final/heartbeat record ({path})")
            continue
        frac = final.get("ingest_wait_frac", 0.0)
        verdict = "ingest-bound" if frac > 0.25 else "compute-bound"
        print(
            f"  {rank:>4} {final.get('step', 0):>8} "
            f"{final.get('elapsed', 0.0):>9.1f} {frac:>9.3f} "
            f"{final.get('examples_in', 0):>12}  {verdict}"
        )
        if slowest is None or frac > slowest[1].get("ingest_wait_frac", 0):
            slowest = (rank, final)
    if slowest is not None:
        print(f"\nslowest rank: {slowest[0]} (the step waits for every "
              f"host — this rank sets the fleet's pace)")
        _print_breakdown(slowest[1])
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a fast_tffm_tpu metrics/telemetry JSONL"
    )
    ap.add_argument("paths", nargs="+",
                    help="metrics_file JSONL(s) written by a run; pass "
                         "one per rank to merge a multi-host fleet")
    ap.add_argument("--limit", type=int, default=8,
                    help="train/validation rows to show (default 8)")
    args = ap.parse_args(argv)
    streams = []
    for path in args.paths:
        groups = load(path)
        if groups:
            streams.append((path, groups))
        else:
            print(f"{path}: no records")
    if not streams:
        return 1
    if len(streams) > 1:
        return _merge_ranks(streams)
    groups = streams[0][1]
    headers = groups.get("run_header", [])
    if headers:
        _print_header(headers[-1])
    _print_progress(
        groups.get("train", []), groups.get("validation", []), args.limit
    )
    # The final record is the exact end-of-run report; fall back to the
    # last heartbeat for a run that died mid-flight (that's the point of
    # heartbeats: the stream still says where the time went).
    final = groups.get("final") or groups.get("heartbeat")
    if final:
        _print_breakdown(final[-1])
        hbs = groups.get("heartbeat", [])
        if hbs:
            print(f"\nheartbeats: {len(hbs)} "
                  f"(last at elapsed {hbs[-1].get('elapsed', 0.0):.1f}s)")
    else:
        print("\nno heartbeat/final records (pre-telemetry stream or "
              "heartbeat_secs=0 and the run died before the final record)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
