"""Shared on-chip timing helpers for the tools/ scripts.

The ONE copy of the scalar-readback protocol: ``block_until_ready``
under-reports through the remote tunnel (it can return before queued
executions drain), so completion is forced by fetching one scalar from
every output leaf.
"""

from __future__ import annotations

import time

import numpy as np


def drain(tree) -> None:
    import jax

    for leaf in jax.tree.leaves(tree):
        np.asarray(jax.device_get(
            leaf.reshape(-1)[:1] if hasattr(leaf, "reshape") else leaf
        ))


def bench(fn, *args, steps=20):
    for _ in range(2):
        drain(fn(*args))
    t0 = time.perf_counter()
    r = None
    for _ in range(steps):
        r = fn(*args)
    drain(r)
    return (time.perf_counter() - t0) * 1e3 / steps
