#!/usr/bin/env python
"""Offline kernel-autotune driver for the interaction hot path.

Two modes:

- **Pre-populate** (default, needs a config): resolve the
  interaction impl for the config's train and serve shapes exactly as
  a run with ``interaction_impl=auto`` would, and persist the
  decisions to the autotune cache — so the actual run (or a whole
  replica fleet sharing the cache file) starts with zero measurement.

      python tools/autotune.py model.cfg
      python tools/autotune.py model.cfg --cache /shared/autotune_cache.json

- **--check** (no config needed; tools/verify.sh wires this): validate
  the autotuner's own invariants on the current backend —

  1. on CPU, ``auto`` must resolve to ``reference`` WITHOUT running a
     single measurement (the near-zero-overhead contract the
     ``autotune_overhead`` bench budget pins);
  2. a forced multi-candidate measurement must pick a parity-gated
     winner and a second resolve must hit the cache (0 additional
     measurements);
  3. an existing cache file (``--cache``, or the config's default
     location) must be self-consistent: readable, versioned, every
     entry's impl a known name.

  Exit 0 = all hold; nonzero with a message otherwise.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("cfg", nargs="?", default=None,
                   help="config file to pre-populate the cache for "
                        "(omit with --check)")
    p.add_argument("--cache", default=None, metavar="PATH",
                   help="autotune cache file (default: the config's "
                        "default_cache_path; with --check and no cfg, "
                        "no file check unless given)")
    p.add_argument("--check", action="store_true",
                   help="validate autotuner invariants + cache "
                        "self-consistency instead of pre-populating")
    p.add_argument("--context", choices=["train", "serve", "both"],
                   default="both",
                   help="which shapes to pre-populate (default both)")
    return p


def _load_cfg(path: str):
    from fast_tffm_tpu.config import load_config

    cfg = load_config(path)
    if cfg.interaction_impl not in ("", "auto"):
        print(f"note: config pins interaction_impl="
              f"{cfg.interaction_impl}; the run will not consult the "
              "cache, but pre-populating anyway for auto consumers")
    import dataclasses

    # Pre-population measures what `auto` WOULD choose regardless of
    # what the file currently pins.
    return dataclasses.replace(cfg, interaction_impl="auto",
                               interaction="")


def _prepopulate(args) -> int:
    from fast_tffm_tpu.ops import autotune

    cfg = _load_cfg(args.cfg)
    cache = (
        args.cache if args.cache is not None
        else autotune.default_cache_path(cfg)
    )
    if not cache:
        print("no cache path resolvable (set --cache, compile_cache_dir "
              "or model_file); decisions would not persist", file=sys.stderr)
        return 2
    contexts = (
        ("train", "serve") if args.context == "both" else (args.context,)
    )
    for context in contexts:
        d = autotune.resolve(cfg, context=context, cache_path=cache)
        times = (
            " ".join(f"{k}={v}ms" for k, v in sorted(d.times_ms.items()))
            or "no measurement needed"
        )
        print(f"{context}: {d.impl} ({d.source}; {times})")
    print(f"cache: {cache}")
    return 0


def _check(args) -> int:
    import dataclasses

    import numpy as np

    from fast_tffm_tpu.config import FmConfig, load_config
    from fast_tffm_tpu.ops import autotune
    from fast_tffm_tpu.platform import is_tpu_backend

    failures = []

    # (1) + (2) run against a small synthetic config and a throwaway
    # in-memory cache so --check never touches a real cache file.
    os.environ["FAST_TFFM_AUTOTUNE_CACHE"] = ""
    cfg = FmConfig(vocabulary_size=512, factor_num=4, max_features=8,
                   batch_size=64, interaction_impl="auto")
    d = autotune.resolve(cfg, context="train")
    n0 = autotune.measurement_count()
    if not is_tpu_backend():
        if d.impl != "reference":
            failures.append(
                f"CPU auto resolved to {d.impl!r}, expected reference"
            )
        if d.source not in ("single_candidate",):
            failures.append(
                f"CPU auto source {d.source!r}, expected "
                "single_candidate (zero measurement)"
            )
        if n0 != 0:
            failures.append(
                f"CPU auto ran {n0} measurement(s), expected 0"
            )
    # (2) forced multi-candidate measurement + cache hit.  "packed" is
    # runnable on every backend (pure XLA), so this exercises the full
    # measure -> parity-gate -> persist -> hit loop even on CPU.
    cands = ("reference", "packed")
    d1 = autotune.resolve(cfg, context="train", candidates=cands)
    n1 = autotune.measurement_count()
    if d1.source != "measured" or n1 <= n0:
        failures.append(
            f"forced measurement did not measure (source={d1.source}, "
            f"count {n0}->{n1})"
        )
    if d1.impl not in ("reference", "packed"):
        failures.append(f"measured winner {d1.impl!r} not a candidate")
    bad = [k for k, v in d1.parity_err.items()
           if v > autotune.PARITY_TOL and k in d1.times_ms]
    if bad:
        failures.append(f"parity-gate leak: {bad} timed despite err>tol")
    d2 = autotune.resolve(cfg, context="train", candidates=cands)
    if d2.source != "cache" or autotune.measurement_count() != n1:
        failures.append(
            f"second resolve missed the cache (source={d2.source})"
        )
    if d2.impl != d1.impl:
        failures.append(
            f"cache returned {d2.impl!r} but measurement chose {d1.impl!r}"
        )

    # (3) optional cache-file self-consistency.
    cache = args.cache
    if cache is None and args.cfg:
        fcfg = load_config(args.cfg)
        fcfg = dataclasses.replace(fcfg)
        del os.environ["FAST_TFFM_AUTOTUNE_CACHE"]
        cache = autotune.default_cache_path(fcfg)
    if cache and os.path.exists(cache):
        entries = autotune.load_cache(cache)
        if not entries:
            failures.append(
                f"cache file {cache} exists but holds no valid entries "
                "(corrupt or version drift)"
            )
        for key, e in (entries or {}).items():
            if not isinstance(e, dict) or e.get("impl") not in autotune.INTERNAL:
                failures.append(f"cache entry {key!r} invalid: {e!r}")
        if not failures:
            print(f"cache {cache}: {len(entries)} entrie(s) OK")

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("autotune check OK (backend: %s)" % (
        "tpu" if is_tpu_backend() else "cpu/other"
    ))
    return 0


def main(argv=None) -> int:
    args = _build_argparser().parse_args(argv)
    if args.check:
        return _check(args)
    if not args.cfg:
        print("a config file is required unless --check", file=sys.stderr)
        return 2
    return _prepopulate(args)


if __name__ == "__main__":
    sys.exit(main())
