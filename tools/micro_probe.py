#!/usr/bin/env python
"""On-chip micro-experiments behind the step-time hot spots.

The first v5e run (TPU_RESULTS.md) showed three XLA-side costs dwarfing
the kernels: the 640k-row table gather (16.8 ms), the id sort (10.8 ms)
and a length-640k cumsum (4.7 ms).  Each experiment here isolates one
design question for those:

  gather:  does row width (burst size) or index sortedness change the
           achieved row rate?  Decides whether packing the table to
           128-lane rows is worth plumbing through the framework.
  cumsum:  XLA lowers 1-D cumsum to log-depth passes; a blocked
           [rows, 128] reformulation (cumsum inside lanes via matmul
           with a triangular matrix + row-offset broadcast) keeps it
           MXU/VPU-shaped.  Decides how _prep should compute upos.
  sort:    cost vs N and vs key width (the sharded path sorts N/shards
           per device; 32- vs 64-bit keys tests packing id+perm into
           one key as an alternative to sort_key_val).

Timing matches tools/tpu_validate.py: scalar readback drains.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timing import bench, drain  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


from functools import partial as _partial

import jax.experimental.pallas as pl
import jax.experimental.pallas.tpu as pltpu

from fast_tffm_tpu.ops import sparse_apply as sa

def _k2t_kernel(ts_ref, table_ref, acc_ref, u_hbm_ref, table_out_ref,
                acc_out_ref, u_vmem, sem, *, tile, group, d, lr, eps):
    def body(j, u, cnt):
        e_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
        u = jnp.where(e_iota < cnt, u, 0.0)
        lrow = u[:, 2 * d:2 * d + 1].astype(jnp.int32)
        r_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, tile), 1)
        p = ((lrow == r_iota) & (e_iota < cnt)).astype(jnp.bfloat16)
        u_hi = u.astype(jnp.bfloat16)
        u_lo = (u - u_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        dn = (((0,), (0,)), ((), ()))  # contract entries -> [L, R]
        dense_t = (
            jax.lax.dot_general(u_hi, p, dn,
                                preferred_element_type=jnp.float32)
            + jax.lax.dot_general(u_lo, p, dn,
                                  preferred_element_type=jnp.float32)
        )
        g1t = dense_t[:d, :]  # [D, R]
        g2t = dense_t[d:2 * d, :]
        cols = pl.ds(j * tile, tile)
        acc_new = acc_ref[:, cols] + g2t
        table_out_ref[:, cols] = table_ref[:, cols] - lr * g1t * (
            jax.lax.rsqrt(acc_new + eps))
        acc_out_ref[:, cols] = acc_new

    sa._window_loop_raw(
        ts_ref, u_hbm_ref, u_vmem, sem, tile=tile, group=group, body=body
    )

def k2t_apply(table_t, acc_t, ids_, g_rows, *, lr, eps):
    vocab = table_t.shape[1]
    d = table_t.shape[0]
    u, tile_start = sa._dedup_and_starts(ids_, g_rows, vocab)
    tile, group = sa.TILE, sa._group_for(vocab // sa.TILE)
    block = tile * group
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(vocab // block,),
        in_specs=[pl.BlockSpec((d, block), lambda t, *_: (0, t))] * 2
        + [pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec((d, block), lambda t, *_: (0, t))] * 2,
        scratch_shapes=[
            pltpu.VMEM((2, tile, u.shape[1]), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _partial(_k2t_kernel, tile=tile, group=group, d=d, lr=lr,
                 eps=eps),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((d, vocab), jnp.float32)] * 2,
        input_output_aliases={1: 0, 2: 1},
        interpret=jax.default_backend() == "cpu",
    )(tile_start, table_t, acc_t, u)


def _k2p_kernel(ts_ref, table_ref, acc_ref, u_hbm_ref, table_out_ref,
                acc_out_ref, u_vmem, sem, *, tile, group, d, lr, eps):
    """Packed-layout K2: tables stored [V/8, 128] — 8 consecutive rows
    of 16 lanes (d values + pad) per 128-lane line, so the physical HBM
    stream is ~1.8x logical instead of the ~14x a lane-padded [V, 9]
    layout costs (decision tree in TPU_STATUS.md).  Placement: entry
    payloads are lane-shifted into their slot with pure VPU iota math
    (no relayout reshapes), then one [R, lines] one-hot matmul sums
    them per packed line."""
    lines = tile // 8
    # Loop-invariant one-hot constants, hoisted out of the unrolled
    # subtile loop (this kernel is timed against production — redundant
    # per-iteration VPU constant builds would bias the comparison).
    # Lane-slot packing is done with one-hot matmuls: lane gathers/
    # shuffles have no reliable Mosaic lowering, and 0/1 matrices are
    # bf16-exact so only u needs the hi/lo split.  G_g1[a, c] =
    # (a == c%16 < d) spreads the g1 lanes into every 16-lane slot.
    e_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, 128), 1)
    a_iota = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
    cmod = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1) % 16
    keep = cmod < d
    g_g1 = ((a_iota == cmod) & keep).astype(jnp.bfloat16)
    g_g2 = ((a_iota == cmod + d) & keep).astype(jnp.bfloat16)
    l_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, lines), 1)
    dn = (((0,), (0,)), ((), ()))  # contract entries

    def body(j, u, cnt):
        valid = e_iota < cnt
        u = jnp.where(valid, u, 0.0)
        lrow = u[:, 2 * d:2 * d + 1].astype(jnp.int32)  # [R, 1]
        # slotmask keeps only the entry's own 16-lane slot.
        slotmask = ((c_iota // 16) == (lrow % 8)).astype(jnp.float32)
        u_hi = u.astype(jnp.bfloat16)
        u_lo = (u - u_hi.astype(jnp.float32)).astype(jnp.bfloat16)

        def spread(gmat):  # [R, 128] with pay lanes in every slot
            return (
                jax.lax.dot(u_hi, gmat,
                            preferred_element_type=jnp.float32)
                + jax.lax.dot(u_lo, gmat,
                              preferred_element_type=jnp.float32)
            )

        g1_sl = spread(g_g1) * slotmask  # [R, 128] slotted
        g2_sl = spread(g_g2) * slotmask
        # Line one-hot [R, lines] and the two placement matmuls.
        p = (((lrow // 8) == l_iota) & valid).astype(jnp.bfloat16)

        def place(x):
            x_hi = x.astype(jnp.bfloat16)
            x_lo = (x - x_hi.astype(jnp.float32)).astype(jnp.bfloat16)
            return (
                jax.lax.dot_general(p, x_hi, dn,
                                    preferred_element_type=jnp.float32)
                + jax.lax.dot_general(p, x_lo, dn,
                                      preferred_element_type=jnp.float32)
            )  # [lines, 128]

        g1p = place(g1_sl)
        g2p = place(g2_sl)
        rows = pl.ds(j * lines, lines)
        acc_new = acc_ref[rows, :] + g2p
        table_out_ref[rows, :] = table_ref[rows, :] - lr * g1p * (
            jax.lax.rsqrt(acc_new + eps))
        acc_out_ref[rows, :] = acc_new

    sa._window_loop_raw(
        ts_ref, u_hbm_ref, u_vmem, sem, tile=tile, group=group, body=body
    )


def k2p_apply(table_p, acc_p, ids_, g_rows, *, lr, eps):
    """table_p/acc_p are packed [vocab/8, 128] (8 rows x 16 lanes)."""
    vocab = table_p.shape[0] * 8
    d = g_rows.shape[1]
    u, tile_start = sa._dedup_and_starts(ids_, g_rows, vocab)
    tile, group = sa.TILE, sa._group_for(vocab // sa.TILE)
    block_lines = (tile * group) // 8
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(vocab // (tile * group),),
        in_specs=[pl.BlockSpec((block_lines, 128), lambda t, *_: (t, 0))] * 2
        + [pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec((block_lines, 128),
                                lambda t, *_: (t, 0))] * 2,
        scratch_shapes=[
            pltpu.VMEM((2, tile, u.shape[1]), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _partial(_k2p_kernel, tile=tile, group=group, d=d, lr=lr, eps=eps),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((vocab // 8, 128), jnp.float32)] * 2,
        input_output_aliases={1: 0, 2: 1},
        interpret=jax.default_backend() == "cpu",
    )(tile_start, table_p, acc_p, u)


def pack_table(t, d):
    """[V, d] -> packed [V/8, 128] (8 rows x 16 lanes, zero pad)."""
    v = t.shape[0]
    padded = jnp.concatenate(
        [t, jnp.zeros((v, 16 - d), t.dtype)], axis=1
    )
    return padded.reshape(v // 8, 128)


def unpack_table(tp, d):
    v8 = tp.shape[0]
    return tp.reshape(v8 * 8, 16)[:, :d]


def main() -> int:
    import jax

    # The packed-key sort experiment needs real int64: without x64 JAX
    # silently downcasts to int32 and (id << 20) wraps for id >= 2^12,
    # timing a 32-bit sort of garbage keys.
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    print(f"devices: {jax.devices()}", flush=True)
    rng = np.random.default_rng(0)
    V, N = 1 << 22, 16384 * 39

    # ---- physical size of narrow-minor-dim HBM buffers ----------------
    # If XLA tiles [V, 9] f32 to 128 lanes in HBM, the table physically
    # occupies ~14x its logical bytes and K2's "stream the table" pass
    # moves ~8.6 GB/step instead of ~600 MB — the deciding fact for a
    # packed [V/8, 128] storage format.
    dev = jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if stats:
        base = stats["bytes_in_use"]
        tb = jax.device_put(jnp.zeros((V, 9), jnp.float32))
        tb.block_until_ready()
        used = dev.memory_stats()["bytes_in_use"] - base
        logical = V * 9 * 4
        print(
            f"  [V,9] f32 table: logical {logical / 1e6:.0f} MB, device "
            f"{used / 1e6:.0f} MB ({used / logical:.1f}x)", flush=True)
        del tb
    else:
        print("  memory_stats unavailable on this backend", flush=True)

    # ---- gather: row width x index sortedness ------------------------
    ids_np = rng.integers(0, V, (N,)).astype(np.int32)
    ids = jax.device_put(jnp.asarray(ids_np))
    ids_sorted = jax.device_put(jnp.asarray(np.sort(ids_np)))
    gather = jax.jit(lambda tb, i: tb[i])
    for d in (9, 16, 32, 64, 128):
        tb = jax.device_put(
            jnp.asarray(rng.uniform(-1, 1, (V, d)), jnp.float32))
        ms_r = bench(gather, tb, ids)
        ms_s = bench(gather, tb, ids_sorted)
        rate = N / (ms_r * 1e-3) / 1e6
        print(
            f"  gather [{V},{d:3d}] x {N}: random {ms_r:7.3f} ms "
            f"({rate:5.1f}M rows/s)  sorted {ms_s:7.3f} ms", flush=True)
        del tb

    # Packed-layout gather: table as [V/8, 128] super-rows (8 logical
    # rows x 16-lane slots).  The gather touches V/8-row space at 512-
    # byte rows; the slot select is VPU work.  Compares the end-to-end
    # cost of producing the same [N, 16] rows against the [V, 9] gather
    # above — decides whether packing pays on the lookup side too.
    packed = jax.device_put(
        jnp.asarray(rng.uniform(-1, 1, (V // 8, 128)), jnp.float32))

    def packed_gather(tb, i):
        sup = tb[i >> 3]  # [N, 128]
        slot = (i & 7).astype(jnp.int32)
        oh = (slot[:, None] == jnp.arange(8, dtype=jnp.int32)[None, :])
        sel = jnp.einsum(
            "ns,nsl->nl", oh.astype(jnp.float32),
            sup.reshape(-1, 8, 16), precision=jax.lax.Precision.HIGHEST)
        return sel  # [N, 16]

    pg = jax.jit(packed_gather)
    ms_r = bench(pg, packed, ids)
    ms_s = bench(pg, packed, ids_sorted)
    print(
        f"  packed-gather [V/8,128]+select: random {ms_r:7.3f} ms  "
        f"sorted {ms_s:7.3f} ms", flush=True)
    del packed

    # Transposed-table gather: storing the table as [9, V] (minor dim
    # dense, sublanes 9->16) cuts its physical HBM footprint ~8x vs the
    # lane-padded [V, 9], which would shrink K2's table streaming the
    # same way — IF gathering 640k columns isn't pathological.
    tb_t = jax.device_put(
        jnp.asarray(rng.uniform(-1, 1, (9, V)), jnp.float32))
    cg = jax.jit(lambda tb, i: tb[:, i])
    ms_r = bench(cg, tb_t, ids)
    ms_s = bench(cg, tb_t, ids_sorted)
    print(
        f"  column-gather [9,V] x {N}: random {ms_r:7.3f} ms  "
        f"sorted {ms_s:7.3f} ms", flush=True)
    del tb_t

    # ---- lane efficiency of [B, F, 9] elementwise chains --------------
    # fwd/bwd stream [B, F, D] arrays whose minor dim pads 9 -> 128
    # (7% lane use).  Times one representative op in three layouts.
    B, F = 16384, 39
    r3 = jax.device_put(
        jnp.asarray(rng.uniform(-1, 1, (B, F, 9)), jnp.float32))
    vals2 = jax.device_put(
        jnp.asarray(rng.uniform(0.1, 1.0, (B, F)), jnp.float32))
    t_bfd = bench(
        jax.jit(lambda r, v: jnp.sum(r * v[..., None], axis=1)), r3, vals2)
    rflat = jax.device_put(
        jnp.asarray(rng.uniform(-1, 1, (B, F * 9)), jnp.float32))
    # Same logical workload as the [B,F,9] variant: vals stay [B, F] and
    # broadcast per-factor inside the jitted fn (an independent [B,F*9]
    # vals array would add ~3x the vals HBM traffic and bias the
    # comparison against the flat layout).
    t_flat = bench(
        jax.jit(lambda r, v: jnp.sum(
            (r * jnp.repeat(v, 9, axis=1)).reshape(-1, F, 9), axis=1)),
        rflat, vals2)
    t_flat_nosum = bench(
        jax.jit(lambda r, v: r * jnp.repeat(v, 9, axis=1)), rflat, vals2)
    print(
        f"  elementwise+field-sum: [B,F,9] {t_bfd:6.3f} ms   "
        f"[B,F*9]->reshape-sum {t_flat:6.3f} ms   "
        f"[B,F*9] mult-only {t_flat_nosum:6.3f} ms", flush=True)

    # one-hot matmul gather at 128 width for contrast (tile-streamed
    # idea lower bound, measured as pure XLA): skipped, O(N*V) infeasible.

    # ---- reshape relayout + forward-path variants ---------------------
    # fm_pallas calls rows.reshape(b, F*D) "a free bitcast" — on TPU the
    # two shapes tile differently ([B,F,9] pads 9->128 lanes; [B,351]
    # pads to 384), so the reshape may be a real relayout copy.  Time it,
    # and time three full forward implementations: the production jnp
    # oracle, the Pallas kernel, and a pure-XLA version of the kernel's
    # flat one-hot-matmul math (no Pallas overhead; XLA free to fuse).
    Dd = 9
    rows3 = r3  # reuse the lane-efficiency section's arrays (and vals2)
    t_resh = bench(
        jax.jit(lambda r: r.reshape(B, F * Dd) + 1.0), rows3)
    t_noop = bench(jax.jit(lambda r: r + 1.0), rows3)
    print(
        f"  reshape [B,F,9]->[B,351] (+1): {t_resh:6.3f} ms   "
        f"(+1 alone in 3-D: {t_noop:6.3f} ms)", flush=True)

    from fast_tffm_tpu.ops import fm_pallas, interaction

    fwd_flat_xla = interaction._scores_flat  # the production flat impl

    import functools

    jnp_fwd = jax.jit(interaction._scores_jnp)
    flat_fwd = jax.jit(fwd_flat_xla)
    t_jnp = bench(jnp_fwd, rows3, vals2)
    if jax.default_backend() != "cpu":
        # fm_scores_pallas is itself jitted (reshape/pad fused in); the
        # partial only pins the static interpret flag.
        t_pal = bench(
            functools.partial(fm_pallas.fm_scores_pallas, interpret=False),
            rows3, vals2)
    else:
        t_pal = float("nan")  # compiled Pallas needs the chip
    t_flatx = bench(flat_fwd, rows3, vals2)
    s_ref, _ = jnp_fwd(rows3, vals2)
    s_got, _ = flat_fwd(rows3, vals2)
    err = float(jnp.max(jnp.abs(s_ref - s_got)))
    print(
        f"  fwd: jnp {t_jnp:6.3f} ms   pallas {t_pal:6.3f} ms   "
        f"flat-xla {t_flatx:6.3f} ms (err {err:.1e})", flush=True)

    # ---- scatter-add: same axes --------------------------------------
    for d in (9, 128):
        tb = jax.device_put(jnp.zeros((V, d), jnp.float32))
        g = jax.device_put(
            jnp.asarray(rng.uniform(-1, 1, (N, d)), jnp.float32))
        sc = jax.jit(lambda tb, i, g: tb.at[i].add(g))
        ms_r = bench(sc, tb, ids, g)
        ms_s = bench(sc, tb, ids_sorted, g)
        print(
            f"  scatter-add [{V},{d:3d}]: random {ms_r:7.3f} ms  "
            f"sorted {ms_s:7.3f} ms", flush=True)
        del tb, g

    # ---- transposed-K2 prototype --------------------------------------
    # The production K2 streams the [V, 9] table whose HBM rows are
    # 128-lane padded (~14x physical traffic if the memory_stats probe
    # above confirms tiling).  This prototype streams a TRANSPOSED
    # [9, V] table in column blocks (dense minor dim; sublanes pad
    # 9->16, only ~1.8x) with the placement matmul transposed to match.
    # If it wins by the traffic ratio, the table-layout redesign is
    # justified; adagrad only, same windowed u stream as production K2.
    d9 = 9
    gk = jax.device_put(
        jnp.asarray(rng.uniform(-1e-2, 1e-2, (N, d9)), jnp.float32))
    tbl = jax.device_put(
        jnp.asarray(rng.uniform(-0.1, 0.1, (V, d9)), jnp.float32))
    accv = jnp.full((V, d9), 0.1, jnp.float32)
    k2t = jax.jit(lambda tt, at, i, g: k2t_apply(
        tt, at, i, g, lr=0.05, eps=1e-7))
    try:
        # Correctness vs the scatter reference (transposed back).
        if jax.default_backend() == "cpu":
            # Interpret mode runs the grid in Python: tiny shapes only.
            vs, ns = 4096, 2048
            tbs = jnp.asarray(rng.uniform(-0.1, 0.1, (vs, d9)), jnp.float32)
            acs = jnp.full((vs, d9), 0.1, jnp.float32)
            idss = jnp.asarray(rng.integers(0, vs, (ns,)), jnp.int32)
            gs = jnp.asarray(
                rng.uniform(-1e-2, 1e-2, (ns, d9)), jnp.float32)
            t_t, a_t = k2t(tbs.T, acs.T, idss, gs)
            a_ref2 = acs.at[idss].add(gs * gs)
            t_ref2 = tbs.at[idss].add(
                -0.05 * gs * jax.lax.rsqrt(a_ref2[idss] + 1e-7))
            errt = float(jnp.max(jnp.abs(t_t.T - t_ref2)))
            print(f"  K2-transposed parity err {errt:.2e} (interpret, "
                  f"V={vs} n={ns})", flush=True)
        else:
            t_t, a_t = k2t(tbl.T, accv.T, ids, gk)
            a_ref2 = accv.at[ids].add(gk * gk)
            t_ref2 = tbl.at[ids].add(
                -0.05 * gk * jax.lax.rsqrt(a_ref2[ids] + 1e-7))
            errt = float(jnp.max(jnp.abs(t_t.T - t_ref2)))
            ms_t = bench(k2t, tbl.T, accv.T, ids, gk)
            prod = jax.jit(lambda tb, a, i, g: sa.adagrad_apply(
                tb, a, i, g, lr=0.05, eps=1e-7))
            ms_p = bench(prod, tbl, accv, ids, gk)
            print(
                f"  K2 transposed [9,V]: {ms_t:7.3f} ms vs production "
                f"[V,9]: {ms_p:7.3f} ms (parity err {errt:.2e})",
                flush=True)
        del t_t, a_t
    except Exception as exc:  # noqa: BLE001 — a probe must not die here
        print(f"  K2-transposed probe FAILED: {type(exc).__name__}: "
              f"{str(exc).splitlines()[0][:140]}", flush=True)

    # ---- packed-K2 prototype ------------------------------------------
    # Third layout option: [V/8, 128] super-rows (8 rows x 16 lanes).
    # Physical stream ~1.8x logical (16/9) with a dense 128-lane minor
    # dim — vs ~14x for lane-padded [V, 9].  Costs two extra lane-spread
    # matmuls per subtile; whether that trade wins is exactly what this
    # times against production and the transposed prototype.
    k2p = jax.jit(_partial(k2p_apply, lr=0.05, eps=1e-7))
    try:
        if jax.default_backend() == "cpu":
            vs, ns = 4096, 2048
            tbs = jnp.asarray(rng.uniform(-0.1, 0.1, (vs, d9)), jnp.float32)
            acs = jnp.full((vs, d9), 0.1, jnp.float32)
            idss = jnp.asarray(rng.integers(0, vs, (ns,)), jnp.int32)
            gs = jnp.asarray(
                rng.uniform(-1e-2, 1e-2, (ns, d9)), jnp.float32)
            t_p, a_p = k2p(
                pack_table(tbs, d9), pack_table(acs, d9), idss, gs)
            a_ref3 = acs.at[idss].add(gs * gs)
            t_ref3 = tbs.at[idss].add(
                -0.05 * gs * jax.lax.rsqrt(a_ref3[idss] + 1e-7))
            errp = float(jnp.max(jnp.abs(unpack_table(t_p, d9) - t_ref3)))
            print(f"  K2-packed parity err {errp:.2e} (interpret, "
                  f"V={vs} n={ns})", flush=True)
        else:
            tp, ap = pack_table(tbl, d9), pack_table(accv, d9)
            t_p, a_p = k2p(tp, ap, ids, gk)
            a_ref3 = accv.at[ids].add(gk * gk)
            t_ref3 = tbl.at[ids].add(
                -0.05 * gk * jax.lax.rsqrt(a_ref3[ids] + 1e-7))
            errp = float(jnp.max(jnp.abs(unpack_table(t_p, d9) - t_ref3)))
            ms_pk = bench(k2p, tp, ap, ids, gk)
            print(
                f"  K2 packed [V/8,128]: {ms_pk:7.3f} ms (parity err "
                f"{errp:.2e}); compare transposed/production above",
                flush=True)
        del t_p, a_p
    except Exception as exc:  # noqa: BLE001 — a probe must not die here
        print(f"  K2-packed probe FAILED: {type(exc).__name__}: "
              f"{str(exc).splitlines()[0][:140]}", flush=True)
    del gk, tbl, accv

    # ---- cumsum variants ---------------------------------------------
    flags = jax.device_put(
        jnp.asarray(rng.integers(0, 2, (N,)), jnp.int32))
    t_plain = bench(jax.jit(lambda f: jnp.cumsum(f)), flags)
    t_assoc = bench(
        jax.jit(lambda f: jax.lax.associative_scan(jnp.add, f)), flags)

    def cumsum_blocked(f):
        # [N] -> [rows, 128]; within-row prefix via triangular matmul,
        # across-row offsets via a tiny second cumsum on row sums.
        rows = f.shape[0] // 128
        m = f.reshape(rows, 128).astype(jnp.float32)
        # within[r, c] = sum_{k<=c} m[r, k] needs tri[k, c] = (k <= c),
        # i.e. upper-triangular (tril would give suffix sums).
        tri = jnp.triu(jnp.ones((128, 128), jnp.float32))
        within = jax.lax.dot_general(
            m, tri, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        row_tot = within[:, -1]
        offs = jnp.cumsum(row_tot) - row_tot
        return (within + offs[:, None]).reshape(-1).astype(jnp.int32)

    t_block = bench(jax.jit(cumsum_blocked), flags)
    ref = np.cumsum(np.asarray(flags))
    got = np.asarray(jax.jit(cumsum_blocked)(flags))
    ok = bool((ref == got).all())
    print(
        f"  cumsum[{N}]: plain {t_plain:6.3f} ms  assoc {t_assoc:6.3f} ms"
        f"  blocked-matmul {t_block:6.3f} ms (exact={ok})", flush=True)

    # ---- sort scaling -------------------------------------------------
    iota = jnp.arange(N, dtype=jnp.int32)
    for n in (N // 8, N // 2, N):
        sub = ids[:n]
        t_kv = bench(
            jax.jit(lambda i: jax.lax.sort_key_val(i, iota[: i.shape[0]])),
            sub)
        packed = (sub.astype(jnp.int64) << 20) | iota[:n].astype(jnp.int64)
        t_pk = bench(jax.jit(lambda p: jnp.sort(p)), packed)
        t_1 = bench(jax.jit(lambda i: jnp.sort(i)), sub)
        print(
            f"  sort n={n:7d}: key_val(i32,i32) {t_kv:7.3f} ms   "
            f"packed-i64 {t_pk:7.3f} ms   keys-only {t_1:7.3f} ms",
            flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
