#!/usr/bin/env bash
# One-command correctness gate: the static audits (tier-1 markers, obs
# metric-name drift), the live-observability smoke, and the PINNED
# tier-1 pytest invocation from ROADMAP.md — builders and bench
# preflight run the exact same thing, so "it passed locally" and "the
# gate passed" can never mean different commands.
#
#   tools/verify.sh            # lint + obs smoke + full tier-1 suite
#   tools/verify.sh --audit    # static analysis only (milliseconds, no jax)
#
# Exit: 0 = every stage ok; nonzero otherwise.  The DOTS_PASSED line at
# the end is the machine-readable passed count the driver compares
# against the recorded baseline.

set -u
cd "$(dirname "$0")/.."

echo "== static analysis (python -m tools.lint; rule catalog: LINTING.md) =="
# All seven analyzers: thread/queue/SHM/server lifecycle, donation/
# aliasing, blocking-under-lock, knob drift, record-schema drift, plus
# the folded-in tier-1 marker audit (T1001) and obs metric-name drift
# (OB001/OB002) that used to run here as separate check_tier1/check_obs
# invocations.  Fails on any NEW finding (tools/lint/baseline.txt
# grandfathers old ones) — run before anything jax-heavy.
python -m tools.lint || exit 1

if [ "${1:-}" = "--audit" ]; then
    exit 0
fi

echo
echo "== kernel-autotune invariants (tools/autotune.py --check) =="
# The autotuner's own contract on this backend: CPU `auto` resolves to
# reference with ZERO measurements (the near-zero-overhead budget), a
# forced multi-candidate measurement parity-gates and caches its
# winner, and any cache file on disk is self-consistent.
JAX_PLATFORMS=cpu python tools/autotune.py --check || exit 1

echo
echo "== fleet parity gate (tools/parity_probe.py --fleet-gate) =="
# Two real gloo ranks (one model column each) vs the single-process
# (1x2) reference: per-shard table hashes must match bitwise at init
# and after each of 3 dispatches.  Catches cross-process init drift
# and step drift in seconds, long before a full fleet bench would.
JAX_PLATFORMS=cpu python tools/parity_probe.py --fleet-gate \
    --dispatches 3 --out /tmp/_fleet_gate.jsonl || exit 1

echo
echo "== live observability + serving smoke (tools/obs_smoke.py) =="
# A real CLI run with --status_port: /metrics must serve parseable
# Prometheus text (incl. the resource block + tffm_build_info) and
# /status the heartbeat JSON, mid-run; /debug/threadz must dump every
# thread; /profile must capture once and 409 a concurrent request.
# Then the serve smoke against the checkpoint that run wrote:
# run_tffm.py serve must score over the socket, expose tffm_serve_*
# on /metrics, and hot-swap once when a second training run
# republishes the checkpoint manifest.  Then the incident smoke: an
# injected alert breach must dump a valid blackbox bundle,
# report.py --incident must render it, and the TFC1 traffic capture
# must replay bitwise against a fresh server (tools/replay.py).
JAX_PLATFORMS=cpu python tools/obs_smoke.py || exit 1

echo
echo "== quantized-table smoke (tools/quant_smoke.py) =="
# The migration story end-to-end through the real CLI: train with a
# bf16 cold store (~20 steps), predict the fp32 reference, convert the
# checkpoint to int8 (tools/convert_checkpoint), serve it quantized,
# and tolerance-check the served scores against fp32 over the socket.
JAX_PLATFORMS=cpu python tools/quant_smoke.py || exit 1

echo
echo "== tier-1 pytest (pinned invocation from ROADMAP.md) =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
