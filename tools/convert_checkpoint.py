#!/usr/bin/env python
"""Checkpoint dtype converter: dense fp32 <-> quantized (bf16 / int8).

Existing fp32 checkpoints migrate to the compact serving formats (and
back) without retraining:

    python -m tools.convert_checkpoint ./fm_model --to int8 --out ./m8
    python -m tools.convert_checkpoint ./m8 --to fp32 --out ./m32

A LOSSY in-place conversion (``--to bf16/int8`` without ``--out``)
deletes the full-precision params and optimizer state — recoverable
only as dequantized values — so it refuses unless ``--force`` says
you mean it.

Reads either the dense Orbax checkpoint (``<dir>/params``) or a
quantized ``<dir>/quant.npz``; writes the requested format via the
same ``train.checkpoint`` save paths the trainer uses — so precedence
stays single-format (a quant save removes the dense dirs and vice
versa) and the serving manifest republishes, meaning a running server
watching the directory hot-swaps onto the converted table at its next
poll.

fp32 -> bf16/int8 is lossy (that is the point); int8 uses symmetric
per-chunk scales (``--chunk`` consecutive rows share one fp32 scale,
matching the ``quant_chunk`` knob — a server must be configured with
the same value).  The tool prints the max |dequant - fp32| element
error and the table bytes before/after.  bf16/int8 -> fp32 dequantizes
into an ordinary dense checkpoint a trainer can warm-start from
(training never warm-starts from quant.npz directly — it refuses, and
points here).

Tiered ``tiered.npz`` overlays are NOT convertible here: their rows
are deltas over a deterministic init bound to the training config
(seed / init range / cold_dtype) — re-encoding them would silently
redefine every never-written row.  Retrain with the desired
``cold_dtype``, or merge to dense at a small vocabulary first.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def _load_fp32(model_file: str):
    """(step, w0 f32, table f32 [V, D]) from dense or quant format."""
    from fast_tffm_tpu.ops import quant
    from fast_tffm_tpu.train import checkpoint

    if checkpoint.exists_tiered(model_file):
        raise SystemExit(
            f"{model_file} holds a tiered overlay (tiered.npz): overlay "
            "rows are bound to the training config's deterministic init "
            "and cannot be dtype-converted standalone — retrain with "
            "the desired cold_dtype, or merge to dense first"
        )
    got = checkpoint.restore_quant(model_file)
    if got is not None:
        step, w0, qt = got
        return step, np.float32(w0), quant.dequantize_table(qt), qt.dtype
    if not checkpoint.exists(model_file):
        raise SystemExit(
            f"no convertible checkpoint at {model_file} (neither the "
            "dense params dir nor quant.npz)"
        )
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        raw = ckptr.restore(checkpoint._params_dir(model_file))
    step = int(np.asarray(raw["step"]))
    params = raw["params"]
    if isinstance(params, dict):
        w0, table = params["w0"], params["table"]
    else:  # restored as a sequence (w0, table)
        w0, table = params[0], params[1]
    return step, np.asarray(w0, np.float32), np.asarray(
        table, np.float32
    ), "fp32"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="convert a checkpoint between fp32 and the "
                    "quantized (bf16/int8) dense formats"
    )
    ap.add_argument("model_file", help="checkpoint directory")
    ap.add_argument("--to", required=True,
                    choices=["fp32", "bf16", "int8"], dest="to_dtype",
                    help="target table dtype")
    ap.add_argument("--out", default=None,
                    help="output checkpoint directory (default: convert "
                         "in place)")
    ap.add_argument("--chunk", type=int, default=64,
                    help="int8 scale chunk: this many consecutive rows "
                         "share one fp32 scale (0 = per-row; must match "
                         "the server's quant_chunk)")
    ap.add_argument("--force", action="store_true",
                    help="allow a LOSSY conversion to overwrite its "
                         "own source (in-place --to bf16/int8 deletes "
                         "the fp32 params and optimizer state)")
    args = ap.parse_args(argv)

    in_place = args.out is None or (
        os.path.abspath(args.out) == os.path.abspath(args.model_file)
    )
    if args.to_dtype != "fp32" and in_place and not args.force:
        raise SystemExit(
            "refusing to quantize IN PLACE: this deletes the fp32 "
            "params and optimizer state (only dequantized values "
            "would remain).  Write to a new directory with --out, or "
            "pass --force if you really mean to overwrite"
        )

    from fast_tffm_tpu.models import fm
    from fast_tffm_tpu.ops import quant
    from fast_tffm_tpu.train import checkpoint

    step, w0, table, src_dtype = _load_fp32(args.model_file)
    out = args.out if args.out is not None else args.model_file
    src_bytes = table.nbytes if src_dtype == "fp32" else None
    print(
        f"loaded {src_dtype} checkpoint step={step} "
        f"table=[{table.shape[0]}, {table.shape[1]}] from "
        f"{args.model_file}"
    )
    if args.to_dtype == "fp32":
        checkpoint.save(
            out, step, fm.FmParams(w0=w0, table=table), opt_state=None
        )
        print(
            f"wrote dense fp32 checkpoint ({table.nbytes >> 20} MiB "
            f"table) to {out}"
        )
        if src_dtype != "fp32":
            print(
                "note: a trainer warm-starting from this table resumes "
                "the DEQUANTIZED values (optimizer state reinitializes)"
            )
        return 0
    qt = quant.quantize_table(table, args.to_dtype, args.chunk)
    # Max element error in row blocks: dequantizing the whole [V, D]
    # table just to print one number would double-to-triple peak RSS
    # at real vocabularies (same hazard class the serve probe avoids
    # via quant.dequantize_rows).
    err, block = 0.0, 1 << 20
    for i in range(0, len(table), block):
        ids = np.arange(i, min(i + block, len(table)))
        err = max(err, float(np.abs(
            quant.dequantize_rows(qt, ids) - table[ids]
        ).max()))
    checkpoint.save_quant(out, step, w0, qt)
    ratio = (src_bytes or table.nbytes) / max(1, qt.nbytes)
    print(
        f"wrote {args.to_dtype} quant.npz to {out}: table "
        f"{table.nbytes} -> {qt.nbytes} bytes ({ratio:.2f}x smaller), "
        f"max |dequant - fp32| element error {err:.3e}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
