#!/usr/bin/env python
"""One-shot TPU validation + timing sweep for the Pallas paths.

Run on the real chip (one TPU process at a time!):

    python tools/tpu_validate.py [--quick]

Sections:
  1. correctness: flat fwd/bwd kernels + tile sparse apply vs XLA oracle
  2. component timings: sort / perm / cumsum / K1 / K2 / fwd+bwd
  3. step timings: full train step under scatter vs tile apply

All timings force completion with scalar readbacks (block_until_ready
under-reports through the remote tunnel; see bench.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timing import bench, drain  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny shapes for an off-TPU plumbing check (interpret-mode "
        "kernels at real shapes would take hours on CPU)",
    )
    ap.add_argument(
        "--out", default="",
        help="also write a markdown report (e.g. TPU_RESULTS.md)",
    )
    ap.add_argument(
        "--sweep-blocks", action="store_true",
        help="time K1/K2 across CHUNK/TILE/GROUP sizes (grid-overhead vs "
        "MXU tradeoff is hardware-dependent; sweep on the chip, then pin "
        "winners via FAST_TFFM_K1_CHUNK / FAST_TFFM_K2_TILE / "
        "FAST_TFFM_K2_GROUP)",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from fast_tffm_tpu.ops import fm_pallas, interaction, sparse_apply
    from fast_tffm_tpu.platform import is_tpu_backend

    report: list[str] = []

    def emit(line: str) -> None:
        print(line, flush=True)
        report.append(line)

    emit(f"devices: {jax.devices()}")
    # 'axon' (the remote-tunnel PJRT plugin) serves a real TPU; gating on
    # the literal "tpu" would silently run every kernel in interpret mode.
    on_tpu = is_tpu_backend()
    emit(f"backend: {jax.default_backend()} (tpu={on_tpu})")

    B, F, K = (4096, 39, 8) if args.quick else (16384, 39, 8)
    V = 1 << 22
    if args.smoke:
        B, F, K, V = 256, 8, 8, 1 << 12
    D = 1 + K
    rng = np.random.default_rng(0)

    # ---- 1. correctness ------------------------------------------------
    rows = jax.device_put(
        jnp.asarray(rng.uniform(-0.1, 0.1, (B, F, D)), jnp.float32))
    vals = jax.device_put(
        jnp.asarray(rng.uniform(0.1, 1.0, (B, F)), jnp.float32))
    g = jax.device_put(jnp.asarray(rng.uniform(-1, 1, (B,)), jnp.float32))

    sc_p, s1_p = fm_pallas.fm_scores_pallas(rows, vals, interpret=not on_tpu)
    sc_o, s1_o = jax.jit(interaction._scores_jnp)(rows, vals)
    err_f = float(jnp.max(jnp.abs(sc_p - sc_o)))
    dr_p = fm_pallas.fm_grad_pallas(rows, vals, s1_p, g, interpret=not on_tpu)
    dr_o = jax.jit(interaction._grads_jnp)(rows, vals, s1_o, g)
    err_b = float(jnp.max(jnp.abs(dr_p - dr_o)))
    emit(f"fwd kernel max err: {err_f:.3e}  bwd: {err_b:.3e}")
    assert err_f < 1e-4 and err_b < 1e-4, "KERNEL MISMATCH"

    N = B * F
    ids = jax.device_put(
        jnp.asarray(rng.integers(0, V, (N,)), jnp.int32))
    g_rows = jax.device_put(
        jnp.asarray(rng.uniform(-1e-2, 1e-2, (N, D)), jnp.float32))
    table = jax.device_put(
        jnp.asarray(rng.uniform(-0.1, 0.1, (V, D)), jnp.float32))
    acc = jnp.full((V, D), 0.1, jnp.float32)
    lr, eps = 0.05, 1e-7

    t_tile, a_tile = jax.jit(
        lambda t, a, i, gg: sparse_apply.adagrad_apply(
            t, a, i, gg, lr=lr, eps=eps)
    )(table, acc, ids, g_rows)
    a_ref = acc.at[ids].add(g_rows * g_rows)
    t_ref = table.at[ids].add(
        -lr * g_rows * jax.lax.rsqrt(a_ref[ids] + eps))
    terr = float(jnp.max(jnp.abs(t_tile - t_ref)))
    aerr = float(jnp.max(jnp.abs(a_tile - a_ref)))
    emit(f"tile adagrad max err: table {terr:.3e} acc {aerr:.3e}")
    assert terr < 1e-4, "TILE APPLY MISMATCH"

    # ---- 2. component timings -----------------------------------------
    iota = jnp.arange(N, dtype=jnp.int32)
    t = {}
    t["sort_key_val"] = bench(
        jax.jit(lambda i: jax.lax.sort_key_val(i, iota)), ids)
    perm = jax.device_put(jnp.asarray(rng.permutation(N), jnp.int32))
    t["perm_gather"] = bench(jax.jit(lambda gg, p: gg[p]), g_rows, perm)
    t["cumsum"] = bench(
        jax.jit(lambda i: jnp.cumsum((i != 0).astype(jnp.int32))), ids)
    t["fwd_pallas"] = bench(
        lambda r, v: fm_pallas.fm_scores_pallas(r, v, interpret=not on_tpu),
        rows, vals)
    t["fwd_jnp"] = bench(jax.jit(interaction._scores_jnp), rows, vals)
    t["bwd_pallas"] = bench(
        lambda r, v, s, gg: fm_pallas.fm_grad_pallas(
            r, v, s, gg, interpret=not on_tpu), rows, vals, s1_p, g)
    t["bwd_jnp"] = bench(jax.jit(interaction._grads_jnp), rows, vals, s1_o, g)
    t["tile_adagrad_apply"] = bench(
        jax.jit(lambda tb, a, i, gg: sparse_apply.adagrad_apply(
            tb, a, i, gg, lr=lr, eps=eps)), table, acc, ids, g_rows)
    t["scatter_adagrad_apply"] = bench(
        jax.jit(lambda tb, a, i, gg: (
            lambda an: (tb.at[i].add(-lr * gg * jax.lax.rsqrt(an[i] + eps)),
                        an))(a.at[i].add(gg * gg))),
        table, acc, ids, g_rows)
    t["gather_2d"] = bench(
        jax.jit(lambda tb, i: tb[i]), table,
        jax.device_put(jnp.asarray(
            rng.integers(0, V, (B, F)), jnp.int32)))
    for k_, v_ in t.items():
        emit(f"  {k_:24s} {v_:9.3f} ms")
    # K2 (tile apply) is bandwidth-bound by design: it streams table+acc
    # in AND out once per step (4 x V x D x 4 bytes) plus the sorted
    # unique-entry stream.  Derived utilization makes the claim testable
    # against the chip's HBM spec (v5e ~= 819 GB/s) — that comparison is
    # only meaningful on the chip, not in CPU interpret mode.
    k2_bytes = 4 * V * D * 4
    k2_gbs = k2_bytes / (t["tile_adagrad_apply"] * 1e-3) / 1e9
    spec = " (v5e HBM ~819 GB/s peak)" if on_tpu else " (CPU interpret)"
    emit(
        f"  tile apply moves {k2_bytes / 1e6:.0f} MB/step -> "
        f"{k2_gbs:.0f} GB/s achieved{spec}"
    )
    emit(
        f"  tile vs scatter speedup: "
        f"{t['scatter_adagrad_apply'] / t['tile_adagrad_apply']:.1f}x"
    )

    # Compact K2 A/B (small batch): with 900 ids (-> 1024 padded
    # entries) the touched-group grid covers at most half of V=2^22's
    # 2048 groups, so FAST_TFFM_K2_COMPACT's auto heuristic would
    # engage — this measures whether touched-only streaming wins on
    # real DMA behavior (TPU_STATUS.md round-5 measurement list) and
    # verifies both paths agree on chip.  Fail-soft like the sweep.
    try:
        ids_small = jax.device_put(
            jnp.asarray(rng.integers(0, V, (900,)), jnp.int32))
        g_small = jax.device_put(
            jnp.asarray(rng.uniform(-1, 1, (900, D)), jnp.float32))
        fns = {
            compact: jax.jit(
                lambda tb, a, i, gg, c=compact: sparse_apply.adagrad_apply(
                    tb, a, i, gg, lr=lr, eps=eps, compact=c))
            for compact in (False, True)
        }
        # Parity first, outputs freed BEFORE timing (the sweep's rule:
        # extra (V, D) arrays held across a bench can OOM / skew it).
        outs = {c: fn(table, acc, ids_small, g_small)
                for c, fn in fns.items()}
        err_c = max(
            float(jnp.max(jnp.abs(a_ - b_)))
            for a_, b_ in zip(outs[False], outs[True])
        )
        del outs
        flag = "" if err_c < 1e-4 else "  WRONG"
        emit(f"  compact parity err {err_c:.2e}{flag}")
        for compact, fn in fns.items():
            ms_c = bench(fn, table, acc, ids_small, g_small)
            emit(f"  small-batch apply compact={int(compact)}: "
                 f"{ms_c:9.3f} ms")
    except Exception as exc:  # noqa: BLE001 — must not kill the window
        emit(f"  compact A/B FAILED: {type(exc).__name__}: "
             f"{str(exc).splitlines()[0][:150]}")

    if args.sweep_blocks:
        # K1 runs N/CHUNK sequential grid steps (per-step overhead) with
        # one-hot matmul work growing ~CHUNK per occurrence; K2's TILE
        # fixes the window DMA size and placement-matmul shape.  The
        # optimum is a hardware property — measure, don't guess.
        emit("block-size sweep (ms):")
        orig_chunk, orig_tile = sparse_apply.CHUNK, sparse_apply.TILE

        def try_candidate(label):
            # Fail-soft: Mosaic VMEM allocation happens at COMPILE time
            # (the big candidates' one-hot intermediates approach the
            # ~16MB scoped-VMEM limit), which cross-platform lowering
            # tests cannot check — a losing candidate must not kill the
            # hardware window.  Each candidate is also verified against
            # the scatter reference: a fast-but-WRONG block size must
            # never win the sweep.
            try:
                fn = jax.jit(
                    lambda tb, a, i, gg: sparse_apply.adagrad_apply(
                        tb, a, i, gg, lr=lr, eps=eps)
                )
                t_c, a_c = fn(table, acc, ids, g_rows)
                err = max(
                    float(jnp.max(jnp.abs(t_c - t_ref))),
                    float(jnp.max(jnp.abs(a_c - a_ref))),
                )
                # Free the check outputs before timing: two extra (V, D)
                # arrays held across the bench could OOM a big candidate
                # that would fit in production.
                del t_c, a_c
                ms = bench(fn, table, acc, ids, g_rows)
                flag = "" if err < 1e-4 else f"  WRONG (err {err:.2e})"
                emit(f"  {label}: {ms:9.3f}{flag}")
            except Exception as exc:  # noqa: BLE001
                emit(f"  {label}: FAILED {type(exc).__name__}: "
                     f"{str(exc).splitlines()[0][:150]}")

        orig_group = sparse_apply.GROUP
        orig_k1_group = sparse_apply.K1_GROUP
        try:
            for chunk in (256, 512, 1024, 2048):
                sparse_apply.CHUNK = chunk
                try_candidate(f"K1 CHUNK={chunk:5d} (TILE={orig_tile})")
            sparse_apply.CHUNK = orig_chunk
            for tile in (256, 512):
                if V % tile:
                    continue
                sparse_apply.TILE = tile
                try_candidate(f"K2 TILE={tile:6d} (CHUNK={orig_chunk})")
            sparse_apply.TILE = orig_tile
            for group in (1, 4, 8, 16, 32):
                sparse_apply.GROUP = group
                try_candidate(
                    f"K2 GROUP={group:5d} (TILE={orig_tile})"
                )
            sparse_apply.GROUP = orig_group
            for group in (1, 4, 16):
                sparse_apply.K1_GROUP = group
                try_candidate(
                    f"K1 GROUP={group:5d} (CHUNK={orig_chunk})"
                )
        finally:
            sparse_apply.CHUNK = orig_chunk
            sparse_apply.TILE = orig_tile
            sparse_apply.GROUP = orig_group
            sparse_apply.K1_GROUP = orig_k1_group

    # ---- 3. full steps -------------------------------------------------
    import shutil

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.libsvm import Batch
    from fast_tffm_tpu.train.loop import Trainer

    combos = [
        # (sparse_apply, use_pallas, dtype, field_num, host_sort, env)
        ("scatter", False, "float32", 0, True, {}),
        ("scatter", True, "float32", 0, True, {}),
        ("tile", False, "float32", 0, True, {}),
        # host_sort on/off at the default config: isolates the win from
        # moving the id sort + prep metadata onto pipeline threads.
        ("tile", True, "float32", 0, False, {}),
        ("tile", True, "float32", 0, True, {}),
        ("tile", True, "bfloat16", 0, True, {}),  # the fast path's bf16
        ("tile", "flat", "float32", 0, True, {}),  # pure-XLA flat
        # Field-aware FM (BASELINE config 5): closed-form ffm_interaction
        # (pinned "0" so an externally exported variable can't silently
        # turn this into a second autodiff run) vs the autodiff einsum
        # oracle — one window settles which backward wins on chip.
        ("tile", True, "float32", 4, True, {"FAST_TFFM_FFM_AUTODIFF": "0"}),
        ("tile", True, "float32", 4, True, {"FAST_TFFM_FFM_AUTODIFF": "1"}),
    ]
    for mode, use_pallas, dtype, field_num, host_sort, env in combos:
        env_saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        cfg = FmConfig(
            vocabulary_size=V, factor_num=K, max_features=F,
            batch_size=B, learning_rate=0.05, log_steps=0,
            sparse_apply=mode,
            use_pallas=(use_pallas is True),
            interaction="flat" if use_pallas == "flat" else "",
            compute_dtype=dtype, field_num=field_num,
            host_sort=host_sort,
            model_file=(
                f"/tmp/tpuval_{mode}_{use_pallas}_{dtype}_{field_num}"
                f"_{int(host_sort)}"
            ),
        )
        shutil.rmtree(cfg.model_file, ignore_errors=True)
        trainer = Trainer(cfg)
        batches = []
        for _ in range(4):
            batches.append(trainer._put(Batch(
                labels=rng.integers(0, 2, (B,)).astype(np.float32),
                ids=rng.integers(0, V, (B, F)).astype(np.int32),
                vals=rng.uniform(0.1, 1.0, (B, F)).astype(np.float32),
                fields=(
                    rng.integers(0, field_num, (B, F)).astype(np.int32)
                    if field_num else np.zeros((B, F), np.int32)
                ),
                weights=np.ones((B,), np.float32),
            )))

        # rotate batches without host sync
        def run_n(n, trainer=trainer, batches=batches):
            for i in range(n):
                trainer.state = trainer._train_step(
                    trainer.state, batches[i % 4])
            return trainer.state

        drain(run_n(3))
        steps = 10 if args.quick else 30
        t0 = time.perf_counter()
        st = run_n(steps)
        drain((st.metrics.loss_sum, st.params.table[0, 0], st.step))
        dt = time.perf_counter() - t0
        ms = dt * 1e3 / steps
        emit(json.dumps({
            "step": (
                f"sparse_apply={mode} interaction={cfg.interaction_resolved} "
                f"compute_dtype={dtype}"
                + (f" field_num={field_num}" if field_num else "")
                + ("" if host_sort else " host_sort=off")
                + ("".join(f" {k}={v}" for k, v in env.items()))
            ),
            "ms_per_step": round(ms, 2),
            "examples_per_sec": round(B * steps / dt, 1),
        }))
        for k, old in env_saved.items():  # restore, don't just delete
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old

    # ---- 3b. north-star vocab single chip (fail-soft) ------------------
    # The flagship config (examples/criteo_1tb_dist.cfg) is V=2^26; the
    # standard combos run V=2^22, where the O(V) terms of the tile apply
    # are 16x cheaper.  One V=2^26 step answers (a) whether the [V, 9]
    # table's PHYSICAL footprint allows it at all (HBM tiling may pad the
    # minor dim to 128 lanes — the memory_stats question in
    # TPU_STATUS.md's decision tree) and (b) what the tile path costs at
    # the vocab the project is judged on.  Fail-soft: an OOM here is
    # itself the measurement.
    if on_tpu and not args.quick and not args.smoke:
        v_ns = 1 << 26
        cfg = FmConfig(
            vocabulary_size=v_ns, factor_num=K, max_features=F,
            batch_size=B, learning_rate=0.05, log_steps=0,
            sparse_apply="tile", use_pallas=True,
            model_file="/tmp/tpuval_northstar",
        )
        shutil.rmtree(cfg.model_file, ignore_errors=True)
        try:
            trainer = Trainer(cfg)
            b_ns = trainer._put(Batch(
                labels=rng.integers(0, 2, (B,)).astype(np.float32),
                ids=rng.integers(0, v_ns, (B, F)).astype(np.int32),
                vals=rng.uniform(0.1, 1.0, (B, F)).astype(np.float32),
                fields=np.zeros((B, F), np.int32),
                weights=np.ones((B,), np.float32),
            ))
            for _ in range(3):
                trainer.state = trainer._train_step(trainer.state, b_ns)
            drain(trainer.state)
            steps = 10
            t0 = time.perf_counter()
            for i in range(steps):
                trainer.state = trainer._train_step(trainer.state, b_ns)
            drain((trainer.state.metrics.loss_sum,
                   trainer.state.params.table[0, 0], trainer.state.step))
            dt = time.perf_counter() - t0
            stats = {}
            try:
                stats = jax.devices()[0].memory_stats() or {}
            except Exception:  # noqa: BLE001 - optional on some backends
                pass
            emit(json.dumps({
                "step": f"NORTH-STAR vocab=2^26 sparse_apply=tile B={B}",
                "ms_per_step": round(dt * 1e3 / steps, 2),
                "examples_per_sec": round(B * steps / dt, 1),
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            }))
            del trainer
        except Exception as e:  # noqa: BLE001 - OOM IS the data point
            emit(json.dumps({
                "step": "NORTH-STAR vocab=2^26 sparse_apply=tile",
                "error": f"{type(e).__name__}: {e}"[:400],
            }))

    if args.out:
        flags = "".join(
            f" --{name.replace('_', '-')}" for name in
            ("quick", "smoke", "sweep_blocks")
            if getattr(args, name)
        )
        header = [
            "# TPU validation results",
            "",
            f"`python tools/tpu_validate.py{flags} --out {args.out}`"
            f" — B={B}, F={F}, k={K}, vocab=2^{V.bit_length() - 1}.",
            "",
            "```",
        ]
        with open(args.out, "w") as f:
            f.write("\n".join(header + report + ["```", ""]))
        print(f"report written to {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
