#!/usr/bin/env python
"""Live-observability smoke: a real 20-step CLI run with --status_port,
scraped over HTTP while it trains.

tools/verify.sh runs this before the tier-1 gate.  It exercises the
exact production path — ``run_tffm.py train <cfg> --status_port`` in a
SUBPROCESS (pinned to CPU), not an in-process Trainer — and asserts:

1. ``/status`` answers mid-run with well-formed JSON carrying the
   heartbeat-record shape (``record``, ``step``, ``stages``);
2. ``/metrics`` answers non-empty, every line Prometheus-parseable
   (``# HELP``/``# TYPE`` comments or ``name{labels} value``), and
   includes the core series;
3. the run itself exits 0.

Exit 0 = all three held; any other exit fails the audit.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One sample line per Prometheus text-format metric: bare name or
# name{labels}, then a number (int/float/scientific/inf/nan).
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"[-+]?(\d+\.?\d*([eE][-+]?\d+)?|\.\d+|[Ii]nf|[Nn]a[Nn])$"
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gen_data(path: str, n_lines: int = 640, vocab: int = 50) -> None:
    import random

    rng = random.Random(0)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.sample(range(vocab), 3)
            toks = " ".join(
                f"{i}:{rng.uniform(0.1, 1.0):.3f}" for i in feats
            )
            f.write(f"{rng.randint(0, 1)} {toks}\n")


def _scrape_both(port: int, deadline: float, proc) -> tuple:
    """(status_bytes, metrics_bytes) fetched back-to-back mid-run.

    The server is up for the whole of train() (it outlives jit compile
    and every dispatch), so one retry loop covers both routes; a child
    that dies before answering fails fast instead of burning the
    deadline.
    """
    base = f"http://127.0.0.1:{port}"
    last_err = None
    while time.time() < deadline:
        try:
            status = urllib.request.urlopen(
                f"{base}/status", timeout=2).read()
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=2).read()
            return status, metrics
        except (urllib.error.URLError, OSError) as e:
            last_err = e
            if proc.poll() is not None:
                out, _ = proc.communicate()
                sys.stderr.write(out.decode(errors="replace")[-2000:])
                raise SystemExit(
                    f"FAIL: run exited {proc.returncode} before the "
                    f"status endpoint answered ({e})"
                )
            time.sleep(0.1)
    raise SystemExit(f"FAIL: {base} unreachable before deadline "
                     f"({last_err})")


def check_prometheus(text: str) -> int:
    """Validate Prometheus exposition text; returns the sample count."""
    samples = 0
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        if not _SAMPLE.match(line):
            raise SystemExit(
                f"FAIL: /metrics line {lineno} is not Prometheus-"
                f"parseable: {line!r}"
            )
        samples += 1
    if samples == 0:
        raise SystemExit("FAIL: /metrics served zero samples")
    return samples


def main() -> int:
    port = _free_port()
    tmpdir = tempfile.mkdtemp(prefix="tffm_obs_smoke_")
    try:
        return _run(port, tmpdir)
    finally:
        # verify.sh runs this on every invocation; leaked data/model
        # dirs would accumulate on CI boxes.
        shutil.rmtree(tmpdir, ignore_errors=True)


def _run(port: int, tmpdir: str) -> int:
    data = os.path.join(tmpdir, "train.libsvm")
    _gen_data(data)  # 640 lines / batch 32 = the 20-step run
    cfg_path = os.path.join(tmpdir, "smoke.cfg")
    with open(cfg_path, "w") as f:
        f.write(f"""[General]
vocabulary_size = 50
factor_num = 4
model_file = {tmpdir}/model
[Train]
train_files = {data}
epoch_num = 1
batch_size = 32
log_steps = 0
thread_num = 2
heartbeat_secs = 0.2
metrics_file = {tmpdir}/metrics.jsonl
[Tpu]
max_features = 4
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "train",
         cfg_path, "--status_port", str(port)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 180
        status_raw, metrics_raw = _scrape_both(port, deadline, proc)
        status = json.loads(status_raw)
        for key in ("record", "step", "stages"):
            if key not in status:
                raise SystemExit(
                    f"FAIL: /status record missing {key!r}: {status}"
                )
        if status["record"] != "status":
            raise SystemExit(
                f"FAIL: /status record type {status['record']!r}"
            )
        metrics = metrics_raw.decode()
        n = check_prometheus(metrics)
        for series in ("tffm_step", "tffm_counter_ingest_examples_total",
                       "tffm_timer_train_dispatch_count"):
            if series not in metrics:
                raise SystemExit(
                    f"FAIL: /metrics missing core series {series}"
                )
        out, _ = proc.communicate(timeout=180)
        if proc.returncode != 0:
            sys.stderr.write(out.decode(errors="replace")[-2000:])
            raise SystemExit(
                f"FAIL: training run exited {proc.returncode}"
            )
        print(
            f"obs smoke ok: /status step={status['step']}, /metrics "
            f"served {n} Prometheus samples, run exited 0"
        )
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


if __name__ == "__main__":
    sys.exit(main())
